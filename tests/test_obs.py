"""Observability tests: histogram math, span tracer, sentinels, and the
no-new-traces contract.

The load-bearing assertions are the trace-count pins: enabling spans +
sentinels must add ZERO jit compilations to the train step and the
serving decode tick — the whole obs/ layer is host-side by construction,
and these tests keep it that way.
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mamba_distributed_tpu.config import ModelConfig, TelemetryConfig
from mamba_distributed_tpu.models import init_lm_params
from mamba_distributed_tpu.obs import (
    NULL_TRACER,
    DivergenceError,
    DivergenceSentinel,
    FlightRecorder,
    SpanTracer,
    StreamingHistogram,
)
from mamba_distributed_tpu.serving import GenerationRequest, ServingEngine
from mamba_distributed_tpu.utils.metrics import ServingMetrics

# the obs marker covers the whole file; fast (the sub-2-minute inner-loop
# tier) goes per-test on the host-only unit tests — the Trainer/engine
# integration tests below each compile real jit steps and belong to the
# unmarked middle tier
pytestmark = [pytest.mark.obs]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from obs_report import build_report, format_report, load_events  # noqa: E402


# -------------------------------------------------------------- histogram


@pytest.mark.fast
def test_histogram_single_sample_is_exact():
    h = StreamingHistogram()
    h.record(5.0)
    for q in (0, 50, 95, 99, 100):
        assert h.percentile(q) == 5.0  # clamped to [min, max]
    assert h.mean == 5.0 and h.count == 1


@pytest.mark.fast
def test_histogram_empty():
    h = StreamingHistogram()
    assert h.percentile(50) is None and h.mean is None
    assert h.summary()["count"] == 0 and h.summary()["p99"] is None


@pytest.mark.fast
def test_histogram_percentiles_within_relative_error():
    h = StreamingHistogram()
    values = [float(v) for v in range(1, 101)]  # 1..100
    for v in values:
        h.record(v)
    g = h.growth
    for q, true in [(50, 50.0), (95, 95.0), (99, 99.0)]:
        got = h.percentile(q)
        assert true / g <= got <= true * g, (q, got)
    # extremes are exact (min/max clamp)
    assert h.percentile(0) >= 1.0 and h.percentile(100) == 100.0


@pytest.mark.fast
def test_histogram_percentiles_monotonic_in_q():
    h = StreamingHistogram()
    rng = np.random.default_rng(0)
    for v in rng.lognormal(mean=2.0, sigma=1.5, size=500):
        h.record(float(v))
    qs = [0, 10, 25, 50, 75, 90, 95, 99, 100]
    ps = [h.percentile(q) for q in qs]
    assert ps == sorted(ps)


@pytest.mark.fast
def test_histogram_merge_counts_and_monotonicity():
    """Merging equals recording the combined stream: counts/totals add,
    and every percentile of the merged histogram matches a histogram fed
    both streams directly (satellite: monotonicity under merges)."""
    a, b, both = (StreamingHistogram() for _ in range(3))
    rng = np.random.default_rng(1)
    xs = [float(v) for v in rng.lognormal(1.0, 1.0, size=200)]
    ys = [float(v) for v in rng.lognormal(3.0, 0.5, size=300)]
    for v in xs:
        a.record(v)
        both.record(v)
    for v in ys:
        b.record(v)
        both.record(v)
    a.merge(b)
    assert a.count == both.count == 500
    assert a.total == pytest.approx(both.total)
    assert a.vmin == both.vmin and a.vmax == both.vmax
    for q in (5, 50, 95, 99):
        assert a.percentile(q) == pytest.approx(both.percentile(q))
    ps = [a.percentile(q) for q in (50, 95, 99)]
    assert ps == sorted(ps)


@pytest.mark.fast
def test_histogram_merge_rejects_mismatched_geometry():
    with pytest.raises(ValueError, match="geometry"):
        StreamingHistogram().merge(StreamingHistogram(lo=1.0))


@pytest.mark.fast
def test_histogram_json_round_trip():
    h = StreamingHistogram()
    for v in (0.5, 2.0, 2.0, 70.0, 1e9):  # incl. an overflow-bucket value
        h.record(v)
    h2 = StreamingHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert h2.count == h.count and h2.total == pytest.approx(h.total)
    for q in (0, 50, 99, 100):
        assert h2.percentile(q) == h.percentile(q)


@pytest.mark.fast
def test_histogram_weighted_and_nonfinite():
    h = StreamingHistogram()
    h.record(10.0, n=7)
    h.record(float("nan"))
    h.record(float("inf"))
    h.record(3.0, n=0)
    assert h.count == 7 and h.percentile(99) == 10.0


@pytest.mark.fast
def test_histogram_out_of_range_clamps_to_observed():
    h = StreamingHistogram(lo=1.0, hi=100.0)
    h.record(0.25)  # underflow bucket
    h.record(4000.0)  # overflow bucket
    assert h.percentile(0) == 0.25
    assert h.percentile(100) == 4000.0


# ----------------------------------------------------------------- tracer


@pytest.mark.fast
def test_span_tracer_nesting_and_attrs(tmp_path):
    path = str(tmp_path / "events.jsonl")
    t = SpanTracer(path)
    with t.span("outer", step=3):
        with t.span("inner"):
            pass
    t.event("mark", loss=float("nan"))
    ev = load_events([path])
    inner, outer, mark = ev
    assert inner["name"] == "inner" and inner["depth"] == 1
    assert inner["parent"] == "outer"
    assert outer["name"] == "outer" and outer["depth"] == 0
    assert outer["step"] == 3
    assert outer["dur_ms"] >= inner["dur_ms"] >= 0
    assert mark["kind"] == "event" and mark["loss"] is None  # NaN -> null


@pytest.mark.fast
def test_span_tracer_records_on_exception(tmp_path):
    t = SpanTracer(str(tmp_path / "e.jsonl"))
    with pytest.raises(RuntimeError):
        with t.span("dies"):
            raise RuntimeError("boom")
    (rec,) = load_events([str(tmp_path / "e.jsonl")])
    assert rec["name"] == "dies"


@pytest.mark.fast
def test_span_tracer_resume_preserves_history(tmp_path):
    """A rebuilt tracer truncates on first write UNLESS preserve_history()
    ran (the checkpoint-resume / --auto-restart path, same contract as
    MetricsLogger) — the pre-crash spans are the post-mortem artifact."""
    path = str(tmp_path / "events.jsonl")
    t = SpanTracer(path)
    with t.span("before_crash"):
        pass
    t2 = SpanTracer(path)  # fresh run: truncates on first write
    with t2.span("fresh"):
        pass
    assert [e["name"] for e in load_events([path])] == ["fresh"]
    t3 = SpanTracer(path)  # resumed run: appends
    t3.preserve_history()
    with t3.span("after_resume"):
        pass
    assert [e["name"] for e in load_events([path])] == ["fresh", "after_resume"]
    NULL_TRACER.preserve_history()  # must exist on the disabled tracer too


@pytest.mark.fast
def test_telemetry_config_rejects_overflow_without_sentinel():
    with pytest.raises(ValueError, match="sentinel"):
        TelemetryConfig(sentinel=False, overflow_threshold=1.0)
    with pytest.raises(ValueError, match=">= 0"):
        TelemetryConfig(overflow_threshold=-1.0)
    with pytest.raises(ValueError, match="flight_recorder_len"):
        TelemetryConfig(flight_recorder_len=0)


@pytest.mark.fast
def test_null_tracer_is_noop(tmp_path):
    with NULL_TRACER.span("anything", x=1):
        pass
    NULL_TRACER.event("mark")
    assert not NULL_TRACER.enabled
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------------- StepTimer (satellite)


@pytest.mark.fast
def test_step_timer_stop_without_start_warns():
    from mamba_distributed_tpu.utils.profiling import StepTimer

    timer = StepTimer()
    with pytest.warns(RuntimeWarning, match="without start"):
        assert timer.stop() == 0.0
    timer.start()
    assert timer.stop() >= 0.0  # normal path unaffected


# ------------------------------------------- flight recorder + sentinel


@pytest.mark.fast
def test_flight_recorder_ring_and_dump(tmp_path):
    fr = FlightRecorder(capacity=3)
    for i in range(5):
        fr.record("train_step", step=i, loss=float(i))
    assert len(fr) == 3
    assert [e["step"] for e in fr.events()] == [2, 3, 4]
    path = fr.dump(str(tmp_path / "fr.json"), reason="test")
    doc = json.load(open(path))
    assert doc["reason"] == "test" and doc["capacity"] == 3
    assert [e["step"] for e in doc["events"]] == [2, 3, 4]


@pytest.mark.fast
def test_sentinel_divergence_dumps_once(tmp_path):
    path = str(tmp_path / "flight_record.json")
    s = DivergenceSentinel(path, capacity=4)
    for i in range(6):
        assert not s.observe_step(i, loss=4.0 - 0.1 * i, grad_norm=1.0)
    assert s.observe_step(6, loss=float("nan"), grad_norm=1.0)
    doc = json.load(open(path))
    assert "non-finite" in doc["reason"] and "step 6" in doc["reason"]
    assert len(doc["events"]) == 4  # bounded ring, not the whole run
    assert doc["events"][-1]["loss"] is None  # NaN serialized as null
    # a later crash must not overwrite the divergence dump
    s.on_crash(RuntimeError("later"))
    assert "non-finite" in json.load(open(path))["reason"]


@pytest.mark.fast
def test_sentinel_without_dump_path_still_detects():
    s = DivergenceSentinel(None)
    assert s.observe_step(0, loss=float("inf"), grad_norm=1.0)
    assert s.dumped_to is None


@pytest.mark.fast
def test_sentinel_overflow_accumulates():
    s = DivergenceSentinel(None)
    s.observe_step(0, 1.0, 0.5, overflow=0)
    s.observe_step(1, 1.0, 9.0, overflow=1)
    s.observe_step(2, 1.0, 9.5, overflow=1)
    assert s.overflow_count == 2
    assert s.flight.events()[-1]["overflow_total"] == 2


# -------------------------------------------------- trainer integration


def _trainer_cfg(tmp, **telemetry):
    from tests.test_parallel import make_cfg

    cfg = make_cfg(tmp, micro=4, accum=1, T=32)
    return dataclasses.replace(cfg, telemetry=TelemetryConfig(**telemetry))


def test_trainer_telemetry_zero_extra_traces(tmp_path):
    """Acceptance pin (train half): spans + sentinels add zero jit
    compilations to the train step (and eval step)."""
    from mamba_distributed_tpu.training import Trainer
    from mamba_distributed_tpu.training.train_step import TRACE_COUNTS

    t = Trainer(_trainer_cfg(tmp_path / "base", sentinel=False), verbose=False)
    t.run(max_steps=2)
    base = dict(TRACE_COUNTS)

    t = Trainer(_trainer_cfg(tmp_path / "tele", spans=True, sentinel=True),
                verbose=False)
    t.run(max_steps=2)
    delta = {k: TRACE_COUNTS[k] - base[k] for k in base}
    # each Trainer builds (and traces) its own step exactly once; the
    # telemetry-enabled trainer must not trace any more than the baseline
    assert delta == {"train_step": 1, "eval_step": 1}, delta

    ev = load_events([os.path.join(t.cfg.log_dir, "events.jsonl")])
    names = {e["name"] for e in ev}
    assert {"data_load", "train_step", "eval"} <= names
    # sentinel saw every step, nothing diverged, no dump
    assert len(t.sentinel.flight) >= 2
    assert t.sentinel.dumped_to is None
    assert not os.path.exists(
        os.path.join(t.cfg.log_dir, "flight_record.json")
    )


def test_trainer_divergence_halts_and_dumps(tmp_path):
    from mamba_distributed_tpu.training import Trainer

    t = Trainer(_trainer_cfg(tmp_path, sentinel=True), verbose=False)
    real_step = t.train_step
    def nan_step(params, opt_state, x, y):
        params, opt_state, _, grad_norm = real_step(params, opt_state, x, y)
        return params, opt_state, jnp.float32(float("nan")), grad_norm
    t.train_step = nan_step
    with pytest.raises(DivergenceError, match="step 0"):
        t.run(max_steps=2)
    doc = json.load(open(os.path.join(t.cfg.log_dir, "flight_record.json")))
    assert "non-finite" in doc["reason"]
    kinds = {e["kind"] for e in doc["events"]}
    assert "train_step" in kinds and "val" in kinds


def test_trainer_overflow_counter(tmp_path):
    """Opt-in on-device overflow flag: a microscopic threshold trips on
    every step and the host counter accumulates (and the loop still
    runs — overflow is a signal, not a failure)."""
    from mamba_distributed_tpu.training import Trainer

    t = Trainer(_trainer_cfg(tmp_path, overflow_threshold=1e-9),
                verbose=False)
    t.run(max_steps=2)
    assert t.sentinel.overflow_count == 2
    assert t.sentinel.flight.events()[-1]["overflow"] == 1


def test_trainer_crash_dumps_flight_record(tmp_path):
    from mamba_distributed_tpu.training import Trainer

    t = Trainer(_trainer_cfg(tmp_path, sentinel=True), verbose=False)

    def boom(*a, **k):
        raise RuntimeError("loader died")

    t.run(max_steps=1)  # one clean step feeds the ring
    t._global_batch = boom
    with pytest.raises(RuntimeError, match="loader died"):
        t.run(max_steps=2)
    doc = json.load(open(os.path.join(t.cfg.log_dir, "flight_record.json")))
    assert doc["reason"].startswith("crash: RuntimeError")
    assert any(e["kind"] == "train_step" for e in doc["events"])


# -------------------------------------------------- serving integration


def _tiny_serving(layer_count=2):
    cfg = ModelConfig(d_model=32, n_layer=layer_count, vocab_size=64,
                      ssm_layer="mamba2", headdim=8, chunk_size=16,
                      d_state=16, compute_dtype="float32")
    return cfg, init_lm_params(jax.random.PRNGKey(0), cfg)


def test_engine_request_telemetry_and_stream(tmp_path):
    cfg, params = _tiny_serving()
    jsonl = str(tmp_path / "serving.jsonl")
    tracer = SpanTracer(str(tmp_path / "events.jsonl"))
    metrics = ServingMetrics(capacity=2, jsonl_path=jsonl)
    eng = ServingEngine(params, cfg, capacity=2, tokens_per_tick=2,
                        metrics=metrics, tracer=tracer)
    budgets = [5, 3, 4, 6]
    eng.run([GenerationRequest(prompt_ids=np.ones(4 + i, np.int32),
                               max_new_tokens=budgets[i],
                               key=jax.random.PRNGKey(i))
             for i in range(4)])
    s = metrics.summary()
    lat = s["latency"]
    assert s["finished_requests"] == 4
    assert lat["queue_wait_ms"]["count"] == 4
    assert lat["ttft_ms"]["count"] == 4
    # one ITL observation per generated token after each request's first
    assert lat["itl_ms"]["count"] == sum(b - 1 for b in budgets)
    for m in lat.values():
        assert m["p50"] is not None and m["p50"] <= m["p95"] <= m["p99"]
    # TTFT includes queue wait by definition (stamps share t_submit)
    assert lat["ttft_ms"]["p50"] >= lat["queue_wait_ms"]["p50"]
    # satellite: throughput fields present in summary()
    assert s["prefill_tokens_per_sec"] > 0 and s["mean_tick_ms"] > 0

    recs = load_events([jsonl])
    reqs = [r for r in recs if r["kind"] == "request"]
    assert len(reqs) == 4 and len(
        [r for r in recs if r["kind"] == "serving_tick"]) == s["ticks"]
    for r in reqs:
        assert r["queue_wait_ms"] <= r["ttft_ms"] <= r["e2e_ms"]
        assert r["itl_hist"]["count"] == r["new_tokens"] - 1
    spans = {e["name"] for e in load_events([str(tmp_path / "events.jsonl")])}
    assert {"serving_admit", "serving_tick"} <= spans


def test_engine_telemetry_zero_extra_traces(tmp_path):
    """Acceptance pin (serving half): telemetry (tracer + jsonl metrics +
    request stamps) adds zero jit compilations to prefill and the decode
    tick.  Own model shape so the jit cache can't already hold it."""
    from mamba_distributed_tpu.serving.engine import TRACE_COUNTS

    cfg = ModelConfig(d_model=16, n_layer=2, vocab_size=32, ssm_layer="mamba2",
                      headdim=4, chunk_size=8, d_state=8,
                      compute_dtype="float32")
    params = init_lm_params(jax.random.PRNGKey(0), cfg)
    reqs = lambda: [GenerationRequest(prompt_ids=np.ones(4, np.int32),
                                      max_new_tokens=3, top_k=16,
                                      key=jax.random.PRNGKey(i))
                    for i in range(3)]
    eng = ServingEngine(params, cfg, capacity=2, tokens_per_tick=2,
                        max_top_k=16)
    eng.run(reqs())
    base = dict(TRACE_COUNTS)
    metrics = ServingMetrics(capacity=2, jsonl_path=str(tmp_path / "s.jsonl"))
    eng = ServingEngine(params, cfg, capacity=2, tokens_per_tick=2,
                        max_top_k=16, metrics=metrics,
                        tracer=SpanTracer(str(tmp_path / "e.jsonl")))
    eng.run(reqs())
    assert TRACE_COUNTS == base  # zero additional compilations
    assert metrics.summary()["latency"]["ttft_ms"]["count"] == 3


# ------------------------------------------------------------ obs_report


@pytest.mark.fast
def test_obs_report_exact_request_percentiles():
    """queue-wait/TTFT percentiles are exact (scalars in the records)."""
    events = [
        {"kind": "request", "request_id": i, "prompt_tokens": 4,
         "new_tokens": 8, "finish_reason": "length",
         "queue_wait_ms": float(i + 1), "ttft_ms": float(10 * (i + 1)),
         "e2e_ms": float(100 * (i + 1))}
        for i in range(100)  # queue waits 1..100
    ]
    r = build_report(events)["requests"]
    assert r["count"] == 100 and r["finish_reasons"] == {"length": 100}
    assert r["queue_wait_ms"]["p50"] == 50.0
    assert r["queue_wait_ms"]["p95"] == 95.0
    assert r["queue_wait_ms"]["p99"] == 99.0
    assert r["ttft_ms"]["p99"] == 990.0
    assert r["itl_ms"] is None  # no histograms in these records


@pytest.mark.fast
def test_obs_report_merges_itl_histograms():
    def req(rid, itl_values):
        h = StreamingHistogram()
        for v in itl_values:
            h.record(v)
        return {"kind": "request", "request_id": rid, "new_tokens": 9,
                "finish_reason": "length", "queue_wait_ms": 1.0,
                "ttft_ms": 2.0, "e2e_ms": 3.0, "itl_hist": h.to_dict()}

    events = [req(0, [10.0] * 8), req(1, [20.0] * 8)]
    itl = build_report(events)["requests"]["itl_ms"]
    assert itl["count"] == 16
    g = StreamingHistogram().growth
    assert 10.0 / g <= itl["p50"] <= 10.0 * g
    assert 20.0 / g <= itl["p99"] <= 20.0 * g


def test_obs_report_round_trip_through_files(tmp_path):
    """jsonl round-trip (satellite): a real serve() stream + a span
    stream land in files, obs_report ingests them and prints the
    latency-percentile and phase tables (acceptance criterion)."""
    cfg, params = _tiny_serving()
    jsonl = str(tmp_path / "serving.jsonl")
    events = str(tmp_path / "events.jsonl")
    metrics = ServingMetrics(capacity=2, jsonl_path=jsonl)
    eng = ServingEngine(params, cfg, capacity=2, tokens_per_tick=2,
                        metrics=metrics, tracer=SpanTracer(events))
    consumed = sum(1 for _ in eng.serve(
        [GenerationRequest(prompt_ids=np.ones(3 + i, np.int32),
                           max_new_tokens=4, key=jax.random.PRNGKey(i))
         for i in range(3)]
    ))
    assert consumed == 12  # serve() streamed every token
    report = build_report(load_events([jsonl, events]))
    assert report["requests"]["count"] == 3
    for metric in ("queue_wait_ms", "ttft_ms"):
        for q in ("p50", "p95", "p99"):
            assert report["requests"][metric][q] is not None
    assert report["requests"]["itl_ms"]["count"] == 9
    assert report["serving"]["decode_tokens"] == 12
    assert "serving_tick" in report["spans"]
    text = format_report(report)
    assert "queue_wait_ms" in text and "p99" in text and "phase" in text
    # in-process report == CLI report (the script is the product surface)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         jsonl, events, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert json.loads(out.stdout)["requests"] == json.loads(
        json.dumps(report["requests"])
    )


@pytest.mark.fast
def test_obs_report_survives_torn_lines(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text(
        json.dumps({"kind": "train", "step": 0, "loss": 2.0,
                    "step_ms": 10.0, "tokens_per_sec": 100.0}) + "\n"
        + '{"kind": "train", "step": 1, "lo'  # torn mid-write
    )
    report = build_report(load_events([str(path)]))
    assert report["train"]["steps"] == 1


@pytest.mark.fast
def test_obs_report_train_and_span_sections(tmp_path):
    """MetricsLogger's metrics.jsonl is directly ingestible."""
    from mamba_distributed_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(str(tmp_path))
    logger.train_step(0, 2.5, 1e-4, 0.9, 0.1, 1000.0, 0.1)
    logger.train_step(1, float("nan"), 1e-4, 0.9, 0.1, 1000.0, 0.1)
    logger.val(1, 2.4)
    report = build_report(load_events([str(tmp_path / "metrics.jsonl")]))
    assert report["train"]["steps"] == 2
    assert report["train"]["non_finite_losses"] == 1
    assert report["val"]["last_loss"] == 2.4
