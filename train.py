"""Train a Mamba LM on TPU.

TPU-native replacement for the reference's ``torchrun --standalone
--nproc_per_node=8 train.py`` (/root/reference/README.md:16): no process-
per-device — one process per host, a `jax.sharding.Mesh` over the chips,
and XLA SPMD for every collective.

Examples:
  python train.py --preset mamba2-280m --max-steps 30
  python train.py --preset mamba2-280m-dp8            # 8-chip data parallel
  python train.py --preset mamba2-1.3b-fsdp16         # FSDP
  python train.py --preset mamba2-280m --mesh-data 4  # override mesh axes
"""

from __future__ import annotations

import argparse
import dataclasses

import jax


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="mamba2-280m",
                   help="one of config.PRESETS")
    p.add_argument("--max-steps", type=int, default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--log-dir", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="steps between checkpoints (preset default 1000, "
                        "the reference's cadence)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --checkpoint-dir")
    p.add_argument("--micro-batch-size", type=int, default=None)
    p.add_argument("--total-batch-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--mesh-data", type=int, default=None)
    p.add_argument("--mesh-fsdp", type=int, default=None)
    p.add_argument("--mesh-seq", type=int, default=None)
    p.add_argument("--mesh-tensor", type=int, default=None)
    p.add_argument("--ssm-impl", choices=["xla", "pallas"], default=None,
                   help="kernel backend for the SSM scan")
    p.add_argument("--attn-impl", choices=["auto", "xla", "pallas"],
                   default=None,
                   help="SDPA backend for hybrid attention layers (pallas: "
                        "flash kernel)")
    p.add_argument("--attn-sp-impl", choices=["ring", "ulysses"], default=None,
                   help="attention strategy under sequence parallelism "
                        "(ring: KV rotation; ulysses: all-to-all head "
                        "sharding, needs heads %% mesh-seq == 0)")
    p.add_argument("--remat-policy", choices=["all", "dots", "mixer"],
                   default=None)
    p.add_argument("--chunk-size", type=int, default=None,
                   help="SSD chunk length (numerics-neutral perf knob; "
                        "larger chunks measured faster on v5e)")
    p.add_argument("--loss-impl", choices=["dense", "blocked"], default=None,
                   help="LM-head+CE formulation; blocked never "
                        "materializes the (b, t, V) logits")
    p.add_argument("--conv-impl", choices=["shift", "xla_conv"], default=None,
                   help="causal-conv formulation (same math)")
    p.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() first (TPU pods)")
    p.add_argument("--sample-prompt", default=None, metavar="TEXT",
                   help="sample 4x32-token continuations of TEXT every "
                        "sample_every steps, like the reference's in-loop "
                        "sampling (tokenized by the vendored GPT-2 BPE from "
                        "$GPT2_BPE_DIR / ./gpt2_bpe, tiktoken fallback)")
    p.add_argument("--sample-prompt-ids", default=None, metavar="IDS",
                   help="same, but the prompt as comma-separated token ids "
                        "(no tokenizer needed)")
    p.add_argument("--spans", action="store_true",
                   help="host-side span tracing (obs/): data_load/"
                        "train_step/eval/checkpoint phase timings to "
                        "{log_dir}/events.jsonl, readable by "
                        "scripts/obs_report.py; zero device overhead")
    p.add_argument("--overflow-threshold", type=float, default=None,
                   metavar="NORM",
                   help="on-device divergence sentinel: the train step "
                        "also reports pre-clip global grad norm > NORM "
                        "(counted into the flight record); 0 disables")
    p.add_argument("--no-halt-on-divergence", action="store_true",
                   help="keep training through a non-finite loss instead "
                        "of dumping the flight record and stopping")
    p.add_argument("--auto-restart", type=int, default=0, metavar="N",
                   help="on a crash, rebuild the trainer from the latest "
                        "checkpoint in --checkpoint-dir and continue, up to "
                        "N times (restart-based failure recovery)")
    return p.parse_args()


def resolve_sampling(args):
    """-> (prompt_ids | None, decode_fn | None).

    The reference hardcodes tiktoken-GPT2("Hello, I'm a language model,")
    (/root/reference/train.py:170-171); here the prompt is a flag, and a
    zero-egress environment can pass raw ids instead.
    """
    if args.sample_prompt_ids is not None:
        return [int(t) for t in args.sample_prompt_ids.split(",")], None
    if args.sample_prompt is None:
        return None, None
    from mamba_distributed_tpu.data.gpt2_bpe import load_encoder

    try:
        # vendored zero-egress BPE (local gpt2_bpe/ files), tiktoken fallback
        encode, decode = load_encoder()
    except FileNotFoundError as e:
        raise SystemExit(
            f"--sample-prompt: {e}\nOr pass --sample-prompt-ids instead."
        )
    return encode(args.sample_prompt), decode


def build_config(args):
    from mamba_distributed_tpu.config import get_preset

    cfg = get_preset(args.preset)
    overrides = {}
    for field, arg in [
        ("micro_batch_size", args.micro_batch_size),
        ("total_batch_size", args.total_batch_size),
        ("seq_len", args.seq_len),
        ("seed", args.seed),
        ("checkpoint_every", args.checkpoint_every),
    ]:
        if arg is not None:
            overrides[field] = arg
    mesh_over = {
        k: v for k, v in [
            ("data", args.mesh_data), ("fsdp", args.mesh_fsdp),
            ("seq", args.mesh_seq), ("tensor", args.mesh_tensor),
        ] if v is not None
    }
    if mesh_over:
        overrides["mesh"] = dataclasses.replace(cfg.mesh, **mesh_over)
    model_over = {
        k: v for k, v in [
            ("ssm_impl", args.ssm_impl), ("remat_policy", args.remat_policy),
            ("attn_sp_impl", args.attn_sp_impl),
            ("attn_impl", args.attn_impl),
            ("chunk_size", args.chunk_size),
            ("loss_impl", args.loss_impl),
            ("conv_impl", args.conv_impl),
        ] if v is not None
    }
    if model_over:
        overrides["model"] = dataclasses.replace(cfg.model, **model_over)
    if args.data_dir is not None:
        overrides["data"] = dataclasses.replace(cfg.data, data_dir=args.data_dir)
    tele_over = {}
    if args.spans:
        tele_over["spans"] = True
    if args.overflow_threshold is not None:
        tele_over["overflow_threshold"] = args.overflow_threshold
    if args.no_halt_on_divergence:
        tele_over["halt_on_divergence"] = False
    if tele_over:
        overrides["telemetry"] = dataclasses.replace(cfg.telemetry, **tele_over)
    if args.log_dir is not None:
        overrides["log_dir"] = args.log_dir
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


def main():
    args = parse_args()
    from mamba_distributed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    if args.multihost:
        jax.distributed.initialize()
    cfg = build_config(args)

    from mamba_distributed_tpu.training import Trainer

    prompt_ids, decode_fn = resolve_sampling(args)
    if args.auto_restart < 0:
        raise SystemExit(f"--auto-restart must be >= 0, got {args.auto_restart}")
    if args.auto_restart and not args.checkpoint_dir:
        raise SystemExit("--auto-restart needs --checkpoint-dir to recover from")

    def make_trainer(resume: bool, after_crash: bool = False):
        trainer = Trainer(cfg, sample_prompt_ids=prompt_ids, decode_fn=decode_fn)
        if resume and args.checkpoint_dir:
            try:
                trainer.restore_checkpoint(args.checkpoint_dir)
                print(f"resumed from step {trainer.step}")
            except FileNotFoundError:
                if after_crash:
                    # a crash before the first checkpoint: a "restart" would
                    # replay from step 0 — no recovery value, just repeated
                    # data and burned restart budget (ADVICE r3)
                    raise SystemExit(
                        "auto-restart: crashed before any checkpoint was "
                        "written; refusing to silently restart from step 0 "
                        "(lower --checkpoint-every or rerun manually)"
                    )
                print("no checkpoint found; starting fresh")
        return trainer

    # restart-based failure recovery (the reference has none: any crash
    # kills the torchrun job, /root/reference/train.py): rebuild from the
    # latest full-state checkpoint and continue, up to --auto-restart times
    trainer = None
    try:
        for attempt in range(args.auto_restart + 1):
            try:
                # (re)build INSIDE the protected block, with the previous
                # trainer's buffers already released: a failed restore or a
                # rebuild OOM consumes restart budget instead of dying, and
                # device memory never holds two full parameter sets
                if trainer is None:
                    trainer = make_trainer(
                        resume=args.resume if attempt == 0 else True,
                        after_crash=attempt > 0,
                    )
                trainer.run(max_steps=args.max_steps,
                            checkpoint_dir=args.checkpoint_dir)
                break
            except Exception as e:
                from mamba_distributed_tpu.obs import DivergenceError

                # a divergence is deterministic from the restored state:
                # a restart would replay the same data/RNG back to the
                # same NaN, burning the whole budget for nothing — the
                # flight record is the actionable artifact, stop here
                if isinstance(e, DivergenceError):
                    raise
                if attempt == args.auto_restart:
                    raise
                print(f"run crashed ({type(e).__name__}: {e}); "
                      f"restart {attempt + 1}/{args.auto_restart} "
                      "from the latest checkpoint")
                if trainer is not None:
                    try:
                        trainer.finish()
                    except Exception:
                        pass
                trainer = None
    finally:
        if trainer is not None:
            trainer.finish()


if __name__ == "__main__":
    main()
