"""Generate text from a trained checkpoint (recurrent O(1) decode).

Ships the reference's ``LMHeadModel.generate``/``top_k_sampling``
capability (/root/reference/model.py:49-95) as a standalone CLI — but
with parallel prefill + carried recurrent state in one jit instead of
the reference's full-prefix re-forward per token (SURVEY.md §3.3).

Examples:
  python generate.py --checkpoint ckpt --preset mamba2-280m \
      --prompt "Hello, I'm a language model,"
  python generate.py --hf-path /path/to/state-spaces-dir \
      --prompt-ids "15496,11,314" --max-new-tokens 64
"""

from __future__ import annotations

import argparse
import os


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint", help="Orbax checkpoint dir (train.py)")
    src.add_argument("--hf-path",
                     help="local HF dir (config.json + pytorch_model.bin) "
                          "or reference-style .pt")
    p.add_argument("--preset", default="mamba2-280m",
                   help="model preset (ignored for --hf-path dirs, which "
                        "carry their own config.json)")
    p.add_argument("--prompt", default=None,
                   help="text (tokenized by the vendored GPT-2 BPE from "
                        "$GPT2_BPE_DIR / ./gpt2_bpe, tiktoken fallback)")
    p.add_argument("--prompt-ids", default=None,
                   help="comma-separated token ids (no tokenizer needed)")
    p.add_argument("--num-return", type=int, default=4)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--top-k", type=int, default=50)
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=42)  # reference train.py:174
    return p.parse_args()


def main():
    args = parse_args()

    from mamba_distributed_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    import jax
    import jax.numpy as jnp

    # --- prompt ---
    decode_fn = None
    if args.prompt_ids is not None:
        ids = [int(t) for t in args.prompt_ids.split(",")]
    elif args.prompt is not None:
        from mamba_distributed_tpu.data.gpt2_bpe import load_encoder

        try:
            # vendored zero-egress BPE (local gpt2_bpe/), tiktoken fallback
            encode, decode_fn = load_encoder()
        except FileNotFoundError as e:
            raise SystemExit(f"--prompt: {e}\nOr pass --prompt-ids instead.")
        ids = encode(args.prompt)
    else:
        raise SystemExit("pass --prompt or --prompt-ids")

    # --- params + config (same routing as eval.py: .pt files go through
    # the HF/reference-style importer, directories through Orbax) ---
    from eval import load_custom, load_hf

    if args.hf_path:
        if os.path.isdir(args.hf_path):
            params, cfg_model = load_hf(args.hf_path)
        else:
            params, cfg_model = load_custom(args.hf_path, args.preset)
    else:
        params, cfg_model = load_custom(args.checkpoint, args.preset)

    from mamba_distributed_tpu.inference import generate

    prompt = jnp.tile(jnp.asarray(ids, jnp.int32)[None, :],
                      (args.num_return, 1))
    out = generate(
        params, cfg_model, prompt, jax.random.PRNGKey(args.seed),
        max_new_tokens=args.max_new_tokens, top_k=args.top_k,
        temperature=args.temperature,
    )
    import numpy as np

    for row in np.asarray(out):
        text = decode_fn(row.tolist()) if decode_fn else f"tokens {row.tolist()}"
        print(f"> {text}")


if __name__ == "__main__":
    main()
