"""Benchmark: time the jitted 280M train step on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline: the reference's derived ~174K tokens/sec/GPU on 8xA100
(BASELINE.md "Aggregate throughput"); vs_baseline = ours / 174000.  It is
a throughput-per-chip comparison at the same model + seq_len (each side
runs its own batch size — the reference used B=32/GPU), and the key is
omitted entirely for other presets/seq_lens, which have no reference
number to compare against.

Progress goes to stderr with timestamps so a hung run is diagnosable from
the log tail (device claim on pooled/tunneled TPUs can queue for minutes).

Env knobs (for sweeps; defaults are the shipped configuration):
  BENCH_PRESET     preset name            (default mamba2-280m)
  BENCH_B          micro batch size       (default 8)
  BENCH_T          sequence length        (default 1024)
  BENCH_SSM_IMPL   xla | pallas           (default preset's)
  BENCH_REMAT      0 | 1                  (default preset's)
  BENCH_REMAT_POLICY all | dots | mixer   (default preset's)
  BENCH_CHUNK_SIZE SSD chunk length       (default preset's)
  BENCH_ITERS      timed iterations       (default 10)
  BENCH_CLAIM_ATTEMPTS  backend-claim attempts; each failed claim can
                   block ~25 min in the axon relay (default 1 so the
                   fallback always gets to emit within one block; raise
                   only when the caller's timeout budget is known)
  BENCH_CLAIM_RETRY_S   sleep between claim attempts (default 60)
  BENCH_LAST_GOOD_PATH  where the on-chip default-recipe fallback record
                   lives (default ./bench_last_good.json; emitted with
                   provenance when the pool is unclaimable)
  BENCH_NO_FALLBACK=1   disable the last-good stand-in entirely (battery
                   wrappers want a clean exit-1 outage signal; the
                   fallback exists for the driver's end-of-round run)
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

_T0 = time.time()

# reference-derived tokens/sec/GPU for the 280M @ T=1024 recipe (BASELINE.md)
BASELINE_TOK_PER_SEC = 174_000.0
BASELINE_PRESET = "mamba2-280m"
BASELINE_T = 1024

# shipped single-chip defaults (shared by time_config and _env_spec)
DEFAULT_B = 8

# ModelConfig fields a bench/sweep spec may override (single source of
# truth for build_step, time_config, and the sweep-matrix validity test)
MODEL_SPEC_KEYS = ("ssm_impl", "attn_impl", "remat", "remat_policy",
                   "chunk_size", "loss_impl", "conv_impl",
                   "residual_in_fp32")
DEFAULT_T = BASELINE_T
DEFAULT_PRESET = BASELINE_PRESET


def _progress(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def _metric_name(preset: str) -> str:
    return f"train_tokens_per_sec_per_chip_{preset.replace('-', '_')}"


def init_backend():
    """Force BENCH_PLATFORM if set, then initialize and report the backend.

    The env var JAX_PLATFORMS alone is not enough on axon-site machines
    (the site plugin overrides it programmatically), so the config is set
    too.  Shared by bench.py and scripts/profile_step.py so the measured
    and profiled backends can never diverge.
    """
    import jax

    from mamba_distributed_tpu.utils.platform import honor_jax_platforms_env

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    else:
        honor_jax_platforms_env()
    _progress(f"jax {jax.__version__} imported; initializing backend...")
    dev = jax.devices()[0]
    _progress(f"backend up: {len(jax.devices())}x {dev.device_kind or dev.platform}")
    return dev


def build_step(spec: dict):
    """Build the single-chip jitted train step for one configuration.

    Shared by time_config and scripts/profile_step.py so the measured and
    profiled setup can never diverge.  Returns (cfg, step, params,
    opt_state, x, y) with x/y carrying the (1, B, T) accum axis.
    """
    import jax
    import jax.numpy as jnp

    from mamba_distributed_tpu.config import get_preset
    from mamba_distributed_tpu.models import init_lm_params
    from mamba_distributed_tpu.parallel.mesh import build_mesh
    from mamba_distributed_tpu.parallel.sharding import (
        opt_state_shardings,
        param_shardings,
    )
    from mamba_distributed_tpu.training.optimizer import make_optimizer
    from mamba_distributed_tpu.training.train_step import make_train_step

    B = spec.get("B", DEFAULT_B)
    T = spec.get("T", DEFAULT_T)
    preset = spec.get("preset", DEFAULT_PRESET)
    cfg = get_preset(preset, micro_batch_size=B, seq_len=T, total_batch_size=B * T)
    model_over = {k: spec[k] for k in MODEL_SPEC_KEYS if k in spec}
    if model_over:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, **model_over)
        )
    mesh = build_mesh(cfg.mesh, jax.devices()[:1])

    key = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(lambda k: init_lm_params(k, cfg.model), key)
    pshard = param_shardings(shapes, mesh, False)
    params = jax.jit(
        lambda k: init_lm_params(k, cfg.model), out_shardings=pshard
    )(key)
    jax.block_until_ready(params)
    _progress(f"{spec or 'default'}: params initialized on device")
    optimizer = make_optimizer(cfg)
    opt_shapes = jax.eval_shape(optimizer.init, params)
    oshard = opt_state_shardings(opt_shapes, shapes, pshard, mesh)
    opt_state = jax.jit(optimizer.init, out_shardings=oshard)(params)
    step = make_train_step(cfg, optimizer, mesh, params, opt_state)

    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.device_put(
        jax.random.randint(kx, (1, B, T), 0, cfg.model.vocab_size, jnp.int32)
    )
    y = jax.device_put(
        jax.random.randint(ky, (1, B, T), 0, cfg.model.vocab_size, jnp.int32)
    )
    return cfg, step, params, opt_state, x, y


def time_config(spec: dict, iters: int = 10) -> dict:
    """Time the jitted train step for one configuration on the local chip.

    spec keys (all optional): preset, B, T, ssm_impl, attn_impl, remat,
    remat_policy, chunk_size.
    Returns {**spec, tok_per_sec, mfu, step_ms} or {**spec, error} on
    failure (e.g. OOM at large batch) so sweeps can continue.  Unknown
    spec keys raise immediately — a typo in a sweep config is a bug, not
    a data point.
    """
    from mamba_distributed_tpu.utils.flops import flops_per_token, peak_flops_per_chip

    known = {"preset", "B", "T", *MODEL_SPEC_KEYS}
    unknown = set(spec) - known
    if unknown:
        raise KeyError(
            f"unknown bench spec keys {sorted(unknown)}; known: {sorted(known)}"
        )

    try:
        cfg, step, params, opt_state, x, y = build_step(spec)
        B, T = cfg.micro_batch_size, cfg.seq_len
        # warmup (compile + 2 steps); float() forces a host transfer because
        # block_until_ready is a no-op on some experimental platforms
        for i in range(3):
            params, opt_state, loss, _ = step(params, opt_state, x, y)
            if i == 0:
                float(loss)
                _progress("train step compiled + first step done")
        float(loss)

        t0 = time.time()
        for _ in range(iters):
            params, opt_state, loss, _ = step(params, opt_state, x, y)
        final_loss = float(loss)  # steps chain on params; closes all iters
        dt = (time.time() - t0) / iters
    except Exception as e:  # e.g. OOM at larger B — report and let sweeps go on
        return {**spec, "error": f"{type(e).__name__}: {str(e)[:200]}"}

    tok_per_sec = B * T / dt
    peak = peak_flops_per_chip()
    fpt_hw = flops_per_token(cfg.model, T, training=True, convention="hardware")
    fpt_model = flops_per_token(cfg.model, T, training=True, convention="model")
    return {
        **spec,
        "tok_per_sec": round(tok_per_sec, 1),
        # the >=45% target is judged on mfu_model, the stricter convention
        "mfu_model": round(fpt_model * tok_per_sec / peak, 4),
        "mfu_hw": round(fpt_hw * tok_per_sec / peak, 4),
        "step_ms": round(dt * 1000, 2),
        "loss": round(final_loss, 4),
        "ssm_impl": cfg.model.ssm_impl,
        "remat": cfg.model.remat,
    }


def _env_spec() -> dict:
    spec = {
        "B": int(os.environ.get("BENCH_B", str(DEFAULT_B))),
        "T": int(os.environ.get("BENCH_T", str(DEFAULT_T))),
        "preset": os.environ.get("BENCH_PRESET", DEFAULT_PRESET),
    }
    if os.environ.get("BENCH_SSM_IMPL"):
        spec["ssm_impl"] = os.environ["BENCH_SSM_IMPL"]
    if os.environ.get("BENCH_REMAT"):
        v = os.environ["BENCH_REMAT"]
        if v not in ("0", "1"):
            raise SystemExit(f"BENCH_REMAT must be 0 or 1, got {v!r}")
        spec["remat"] = v == "1"
    if os.environ.get("BENCH_REMAT_POLICY"):
        spec["remat_policy"] = os.environ["BENCH_REMAT_POLICY"]
    if os.environ.get("BENCH_CHUNK_SIZE"):
        spec["chunk_size"] = int(os.environ["BENCH_CHUNK_SIZE"])
    return spec


# Written after every successful on-chip run; read back as the fallback
# when the pooled TPU is unclaimable at driver time (VERDICT r4: the one
# claim window of the round closed hours before the driver ran bench.py,
# so BENCH_r04.json recorded null despite a full in-window battery).
LAST_GOOD_PATH = os.environ.get(
    "BENCH_LAST_GOOD_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_last_good.json"),
)


def _git_rev() -> str | None:
    try:
        import subprocess

        return subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        return None


def _record_last_good(out: dict) -> None:
    rec = {**out,
           "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "git_rev": _git_rev()}
    try:
        # atomic replace: a SIGTERM mid-write (battery timeout) must never
        # truncate the only fallback record
        tmp = LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.write("\n")
        os.replace(tmp, LAST_GOOD_PATH)
    except OSError as e:  # never let bookkeeping kill a good measurement
        _progress(f"could not write {LAST_GOOD_PATH}: {e}")


def _fail(stage: str, detail: str, device=None, fallback: bool = True,
          spec: dict | None = None) -> None:
    """Emit ONE parseable JSON line and exit.

    Every failure mode — above all backend init when the pooled TPU is
    unclaimable — must leave the driver a structured record, never a raw
    traceback with `parsed: null` (VERDICT r3 weak #1).  If a previous
    successful run left bench_last_good.json, that measurement is emitted
    with provenance (`source: last_good@<timestamp>`) instead of a null
    value, so a pool outage at driver time can't erase an in-window
    result (VERDICT r4 next-round item 5); exit 0 in that case because
    the line carries a real number.
    """
    err = f"{stage}: {detail[:300]}"
    last = None
    if os.environ.get("BENCH_NO_FALLBACK") == "1":
        fallback = False
    if fallback:  # operator errors (bad env spec) must NOT emit stale numbers
        try:
            with open(LAST_GOOD_PATH) as f:
                last = json.load(f)
            if not isinstance(last, dict):
                last = None
        except (OSError, ValueError):
            last = None
        # only a record of the SAME benchmark may stand in: match on the
        # metric name (preset) and seq_len — B is each run's own choice,
        # like vs_baseline's per-chip comparison (module docstring) — and
        # reject if any model-knob override (ssm_impl, chunk_size, ...)
        # differs from what the record measured
        if last is not None:
            batch = last.get("batch")
            rec_t = batch[1] if isinstance(batch, list) and len(batch) == 2 else None
            if (spec is None
                    or last.get("metric") != _metric_name(spec["preset"])
                    or rec_t != spec["T"]
                    or any(k in spec for k in MODEL_SPEC_KEYS)):
                # records are only written for the pristine default spec,
                # so any knob override in the request is a different
                # benchmark — no stand-in
                last = None
    if last and last.get("value") is not None:
        out = {
            **last,
            # git_rev (when present) stays top-level: the fallback number
            # was measured on THAT commit, not necessarily the current one
            "source": f"last_good@{last.get('measured_at', 'unknown')}",
            "fallback_error": err,
        }
        out.pop("measured_at", None)
        print(json.dumps(out), flush=True)
        raise SystemExit(0)
    print(
        json.dumps(
            {
                "metric": "train_tokens_per_sec_per_chip",
                "value": None,
                "unit": "tokens/sec/chip",
                "error": err,
                "device": device,
            }
        ),
        flush=True,
    )
    raise SystemExit(1)


def main() -> None:
    # env parsing first: a malformed variable is an operator error and
    # must emit its structured line BEFORE any ~25-min claim attempt
    try:
        spec = _env_spec()
        iters = int(os.environ.get("BENCH_ITERS", "10"))
        # default ONE attempt: a failed claim blocks ~25 min in the axon
        # relay, and the driver's own timeout budget is unknown — a second
        # attempt (~51 min total) risks being killed before the last-good
        # fallback can emit, recreating the null-record failure this file
        # exists to prevent.  Opt into retries explicitly when the budget
        # is known.
        attempts = max(1, int(os.environ.get("BENCH_CLAIM_ATTEMPTS", "1")))
        retry_s = max(0, int(os.environ.get("BENCH_CLAIM_RETRY_S", "60")))
    except (SystemExit, ValueError) as e:
        _fail("bad_env_spec", str(e), fallback=False)

    # Bounded claim retry: each failed claim blocks ~25 min inside the
    # axon relay before raising, so the default keeps a second attempt
    # only (BENCH_CLAIM_ATTEMPTS=1 for single-shot sweep wrappers).
    # Only the pool-outage error class retries — a deterministic failure
    # (bad platform, broken install) would just double the block.
    dev = None
    for i in range(attempts):
        try:
            dev = init_backend()
            break
        except Exception as e:
            _progress(f"claim attempt {i + 1}/{attempts} failed: {e}")
            retryable = "UNAVAILABLE" in str(e) or "DEADLINE" in str(e)
            if i + 1 == attempts or not retryable:
                # a deterministic failure (bad platform, broken install) is
                # not an outage — masking it with a stale success would hide
                # a permanently broken environment behind exit 0 forever
                _fail("backend_unavailable", f"{type(e).__name__}: {e}",
                      fallback=retryable, spec=spec)
            time.sleep(retry_s)
    r = time_config(spec, iters=iters)
    if "error" in r:
        # on-chip per-config failure (e.g. OOM): the chip WAS claimed and
        # a fresh measurement failed — a stale success must not stand in.
        # Echo the spec for attribution, like sweep rows do.
        print(json.dumps({"value": None, "device": dev.device_kind, **r}),
              flush=True)
        raise SystemExit(1)

    out = {
        "metric": _metric_name(spec["preset"]),
        "value": r["tok_per_sec"],
        "unit": "tokens/sec/chip",
        # two conventions (docs/KERNELS.md): the >=45% target is judged on
        # mfu_model (parameter matmuls + recurrent state math); mfu_hw
        # additionally counts the chunked algorithm's Gram/decay matmuls
        "mfu_model": r["mfu_model"],
        "mfu_hw": r["mfu_hw"],
        "step_ms": r["step_ms"],
        "device": dev.device_kind,
        "batch": [spec["B"], spec["T"]],
        "ssm_impl": r["ssm_impl"],
        "remat": r["remat"],
        "loss": r["loss"],
    }
    # vs_baseline is only defined for the reference's model + seq_len
    if spec["preset"] == BASELINE_PRESET and spec["T"] == BASELINE_T:
        out["vs_baseline"] = round(r["tok_per_sec"] / BASELINE_TOK_PER_SEC, 4)
    # the fallback record preserves the *on-chip, pristine-default-recipe*
    # number across pool outages; a CPU smoke run, a non-baseline preset,
    # or a knob-overridden sweep point must never clobber it
    if ("tpu" in (dev.device_kind or dev.platform).lower()
            and "vs_baseline" in out
            and spec.get("B", DEFAULT_B) == DEFAULT_B
            and not any(k in spec for k in MODEL_SPEC_KEYS)):
        _record_last_good(out)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
