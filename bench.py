"""Benchmark: time the jitted 280M train step on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline: the reference's derived ~174K tokens/sec/GPU on 8xA100
(BASELINE.md "Aggregate throughput"); vs_baseline = ours / 174000.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from mamba_distributed_tpu.config import get_preset
    from mamba_distributed_tpu.models import init_lm_params
    from mamba_distributed_tpu.parallel.mesh import build_mesh
    from mamba_distributed_tpu.parallel.sharding import opt_state_shardings, param_shardings
    from mamba_distributed_tpu.training.optimizer import make_optimizer
    from mamba_distributed_tpu.training.train_step import make_train_step
    from mamba_distributed_tpu.utils.flops import flops_per_token, peak_flops_per_chip

    B, T = 8, 1024
    cfg = get_preset("mamba2-280m", micro_batch_size=B, total_batch_size=B * T)
    mesh = build_mesh(cfg.mesh, jax.devices()[:1])

    key = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(lambda k: init_lm_params(k, cfg.model), key)
    pshard = param_shardings(shapes, mesh, False)
    params = jax.jit(
        lambda k: init_lm_params(k, cfg.model), out_shardings=pshard
    )(key)
    optimizer = make_optimizer(cfg)
    opt_shapes = jax.eval_shape(optimizer.init, params)
    oshard = opt_state_shardings(opt_shapes, shapes, pshard, mesh)
    opt_state = jax.jit(optimizer.init, out_shardings=oshard)(params)
    step = make_train_step(cfg, optimizer, mesh, params, opt_state)

    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.device_put(
        jax.random.randint(kx, (1, B, T), 0, cfg.model.vocab_size, jnp.int32)
    )
    y = jax.device_put(
        jax.random.randint(ky, (1, B, T), 0, cfg.model.vocab_size, jnp.int32)
    )

    # warmup (compile + 2 steps); float() forces a host transfer because
    # block_until_ready is a no-op on some experimental platforms
    for _ in range(3):
        params, opt_state, loss, _ = step(params, opt_state, x, y)
    float(loss)

    iters = 10
    t0 = time.time()
    for _ in range(iters):
        params, opt_state, loss, _ = step(params, opt_state, x, y)
    float(loss)  # steps chain on params, so this closes all iters
    dt = (time.time() - t0) / iters

    tok_per_sec = B * T / dt
    fpt = flops_per_token(cfg.model, T, training=True)
    mfu = fpt * tok_per_sec / peak_flops_per_chip()
    print(
        json.dumps(
            {
                "metric": "train_tokens_per_sec_per_chip_mamba2_280m",
                "value": round(tok_per_sec, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(tok_per_sec / 174_000.0, 4),
                "mfu": round(mfu, 4),
                "step_ms": round(dt * 1000, 2),
                "device": jax.devices()[0].device_kind,
                "batch": [B, T],
                "loss": round(float(loss), 4),
            }
        )
    )


if __name__ == "__main__":
    main()
