"""Plot train/val loss curves from the reference-format log file.

Script equivalent of the reference's plot.ipynb (cells 0-1): parses
``"{step} train {loss}"`` / ``"{step} val {loss}"`` lines — the format both
the reference and this framework write — and saves ``validation_loss.png``.

  python plot.py [--log log/log.txt] [--out log/validation_loss.png]
"""

from __future__ import annotations

import argparse
import os


def parse_log(path: str):
    train, val = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) != 3:
                continue
            step, kind, loss = parts
            try:
                entry = (int(step), float(loss))
            except ValueError:
                continue
            if kind == "train":
                train.append(entry)
            elif kind == "val":
                val.append(entry)
    return train, val


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--log", default="log/log.txt")
    p.add_argument("--out", default="log/validation_loss.png")
    p.add_argument("--ref-log", default=None,
                   help="optional second log to overlay (e.g. the reference's)")
    args = p.parse_args()

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    train, val = parse_log(args.log)
    fig, ax = plt.subplots(figsize=(8, 5))
    if train:
        ax.plot(*zip(*train), label="train loss", alpha=0.6, linewidth=0.8)
    if val:
        ax.plot(*zip(*val), label="val loss", marker="o", markersize=3)
    if args.ref_log:
        rt, rv = parse_log(args.ref_log)
        if rv:
            ax.plot(*zip(*rv), label="reference val", linestyle="--")
    ax.set_xlabel("step")
    ax.set_ylabel("loss")
    ax.legend()
    ax.grid(alpha=0.3)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    fig.savefig(args.out, dpi=120, bbox_inches="tight")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
