"""Configuration system for the TPU-native Mamba framework.

The reference (pie33000/mamba-distributed) has no config system: every
hyperparameter is a hard-coded constant (train.py:43-53,75,89-94,114;
dataloader.py:23; eval.py:14).  Here everything becomes a typed dataclass
field, with named presets for the five BASELINE.json configurations.

Model defaults mirror the semantics of ``mamba_ssm.models.config_mamba.
MambaConfig`` (mamba-ssm 2.2.2) plus the mixer defaults in
``modules/mamba_simple.py`` (Mamba-1) and ``modules/mamba2.py`` (Mamba-2),
which is what ``MambaConfig(d_model=768, vocab_size=50304)`` at
reference train.py:75 actually builds.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture config (reference: mamba_ssm MambaConfig + mixer defaults)."""

    d_model: int = 768
    n_layer: int = 64
    vocab_size: int = 50304
    # mamba_ssm MambaConfig.pad_vocab_size_multiple=8; 50304 is already padded.
    pad_vocab_size_multiple: int = 8
    # "mamba1" -> selective-scan mixer (what the reference's default ssm_cfg
    # builds, see SURVEY.md section 2.4); "mamba2" -> SSD mixer (the headline).
    ssm_layer: str = "mamba2"
    # 0 => no MLP between mixers (pure mixer stack, the reference default).
    d_intermediate: int = 0
    # --- MoE (beyond the reference; completes the parallelism menu with
    # expert parallelism over mesh.expert) ---
    # 0 => dense gated MLP; > 1 => the MLP becomes a token-choice top-k
    # mixture of experts (GShard-style dense-dispatch einsums: static
    # shapes, MXU-friendly; experts shard over the mesh's expert axis)
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # weight of the Switch/GShard load-balance aux loss added by lm_loss
    moe_aux_weight: float = 0.01
    rms_norm: bool = True
    residual_in_fp32: bool = True
    tie_embeddings: bool = True
    norm_eps: float = 1e-5

    # --- shared mixer knobs (mamba_simple.py / mamba2.py defaults) ---
    d_state: int = 0  # 0 => auto: 16 for mamba1, 128 for mamba2
    d_conv: int = 4
    expand: int = 2
    conv_bias: bool = True
    proj_bias: bool = False
    dt_min: float = 0.001
    dt_max: float = 0.1
    dt_init_floor: float = 1e-4

    # --- mamba1-only ---
    dt_rank: int = 0  # 0 => auto: ceil(d_model / 16)
    dt_init: str = "random"  # "random" | "constant"
    dt_scale: float = 1.0

    # --- mamba2-only ---
    headdim: int = 64
    ngroups: int = 1
    chunk_size: int = 256
    a_init_min: float = 1.0
    a_init_max: float = 16.0
    d_has_hdim: bool = False

    # --- hybrid (Jamba-style) attention layers; empty => pure SSM stack ---
    attn_layer_idx: tuple[int, ...] = ()
    attn_num_heads: int = 0  # 0 => auto: d_model // 64
    attn_num_kv_heads: int = 0  # 0 => same as attn_num_heads (MHA)
    attn_head_dim: int = 0  # 0 => auto: d_model // num_heads
    # -1 => full head dim; 0 => NO rotary (mamba_ssm MHA's rotary_emb_dim
    # convention, so imported hybrid configs keep their semantics)
    attn_rotary_dim: int = -1
    rope_theta: float = 10000.0
    # attention strategy under sequence parallelism: "ring" (KV rotates,
    # O(t/S) per-chip memory) or "ulysses" (all-to-all head sharding —
    # needs heads % seq == 0; parallel/ulysses.py)
    attn_sp_impl: str = "ring"
    # SDPA backend for full-sequence attention: "xla" (blockwise online-
    # softmax scan, ops/blockwise_attention.py) or "pallas" (flash kernel,
    # ops/pallas/attention_kernels.py — skips fully-masked blocks).  Under
    # SP, ulysses runs flash after its head all-to-all and ring runs the
    # flash pair kernels per hop (fully-future hops skipped outright).
    # Decode steps always use the tiny-t XLA path.  "auto" (default)
    # resolves to "pallas" on TPU — where the flash kernels measured +12%
    # train throughput on hybrid-280m (round-4 sweep, MEASUREMENTS.md) —
    # and "xla" elsewhere (ops/pallas/common.py:resolve_attn_impl).
    attn_impl: str = "auto"

    # --- precision policy (reference: bf16 autocast + fp32 master weights,
    # train.py:72,142,211) ---
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"

    # --- init ---
    initializer_range: float = 0.02  # embedding init std (mamba_ssm _init_weights)
    rescale_prenorm_residual: bool = True

    # --- memory ---
    remat: bool = True  # per-block activation checkpointing
    # "all": recompute everything (min memory); "dots": save matmul
    # outputs, recompute elementwise (jax dots_with_no_batch_dims policy —
    # trades HBM for a lighter backward); "mixer": save only the
    # scan/attention outputs so the backward never recomputes the SSD
    # scan (checkpoint_name "mixer_out" in the mixers)
    remat_policy: str = "all"

    # --- kernel backend for the SSD scan: "xla" (einsum formulation) or
    # "pallas" (fused VMEM kernels, ops/pallas/) ---
    ssm_impl: str = "xla"

    # causal-conv formulation: "shift" (width shifted multiply-adds) or
    # "xla_conv" (grouped conv_general_dilated — XLA's dedicated
    # depthwise path; sweepable, same math)
    conv_impl: str = "shift"

    # --- LM-head + CE formulation: "dense" (one head matmul, logits
    # materialized once in bf16) or "blocked" (vocab-blocked online
    # logsumexp, ops/loss.py — no (b, t, V) tensor ever exists; frees
    # ~0.8 GB at B=8 / ~3.3 GB at the reference's B=32) ---
    loss_impl: str = "dense"
    loss_vocab_blocks: int = 8

    # --- chunked prompt prefill (serving/prefill.py; pure-SSM only) ---
    # Prompts longer than this many tokens prefill as fixed-size chunks
    # threaded through the mixers' initial_conv_state/initial_ssm_state
    # carries: one compiled chunk shape regardless of prompt length
    # (instead of one pow2 bucket trace per length class, and instead of
    # up-to-2x pow2 padding waste), and the serving engine can interleave
    # a long prompt's chunks with decode ticks.  Lives on ModelConfig —
    # not an engine knob — so ``generate()`` and the engine always chunk
    # the same prompt identically (the token-parity contract, same rule
    # as the pow2 buckets).  Consumers read
    # ``effective_prefill_chunk_tokens``, which rounds this up to a
    # multiple of ``chunk_size`` for mamba2 (SSD chunk alignment).
    # 0 disables (always one-shot pow2-bucketed prefill).
    prefill_chunk_tokens: int = 256
    # --- paged attention KV cache (hybrid decode/serving; models/
    # attention.py, serving/state_cache.py, ops/pallas/attention_kernels
    # .py ragged decode kernel).  The decode-time KV cache is a pool of
    # fixed-size pages plus a per-row page table and per-row lengths, so
    # serving slots at different positions share one cache and KV HBM is
    # O(pages in use), not O(slots * max_len). ---
    # Tokens per KV page.  Must be a multiple of 8: padded-width masked
    # attention is bit-stable across page-count buckets only at 8-lane
    # granularity (the engine<->generate() exact-parity contract leans
    # on it), and 8 sublanes is the TPU tile granule anyway.
    kv_page_tokens: int = 64
    # Per-request KV budget in the SERVING pool: one slot's page-table
    # row holds ceil(kv_slot_tokens / kv_page_tokens) entries, so a
    # hybrid request needs prompt + max_new_tokens <= kv_slot_tokens.
    kv_slot_tokens: int = 1024
    # Total pages in the serving pool.  0 => auto: capacity * pages-per-
    # slot (every slot can run to kv_slot_tokens simultaneously — the
    # dense-equivalent worst case).  Set lower to oversubscribe HBM when
    # typical sequences are far shorter than kv_slot_tokens; admission
    # then waits for pages, never OOMs mid-flight (pages for the whole
    # request are reserved up front, serving/engine.py).
    kv_pool_pages: int = 0
    # Serving-engine interleaving budget: max prefill-chunk tokens
    # dispatched between two decode ticks (serving/engine.py).  Bounds
    # the tick-to-tick stall a long prompt can inject (ITL of running
    # slots) while it streams in.  0 => unbounded (a whole prompt
    # prefills between two ticks, the pre-chunking behavior).
    prefill_tokens_per_tick: int = 512
    # How the per-tick chunk budget is scheduled across concurrent
    # partial prefills (serving/engine.py): "rr" rotates one chunk at a
    # time in admission order; "srpt" grants the prompt with the FEWEST
    # remaining chunks first (shortest-remaining-processing-time — a
    # nearly-done prompt reaches its first token before a fresh long one
    # begins), with a starvation guard so a long prompt still gets a
    # chunk at least every few grants.
    prefill_schedule: str = "rr"
    # --- data-parallel serving fabric (serving/router.py) ---
    # Engine replicas the request router places over (least-loaded
    # placement; each replica is a full ServingEngine with its own slot
    # pool).  The router/bench default; 1 => a single engine.
    serving_replicas: int = 1
    # Shards of the serving slot pool's batch axis over `mesh.data`
    # (parallel/mesh.serving_mesh): slot/page state and the decode
    # tick's batch axis partition over the data axis via NamedSharding
    # (weights replicated), so one engine spans every device in the
    # mesh.  1 => single-device pool (the pre-fabric behavior).
    # capacity must divide evenly across the shards.
    serving_data_shards: int = 1
    # --- disaggregated prefill/decode tiers (serving/router.py,
    # serving/replica.py role=) ---
    # Prompt-length cutoff (tokens) above which the router places a
    # request on the PREFILL tier (EngineReplica(role="prefill")): the
    # replica runs the chunked prefill, then at prefill-complete the
    # request's O(1) carry snapshot (+ hybrid KV pages) MIGRATES to a
    # decode-tier replica where state_cache.restore resumes the stream
    # bit-exactly — long prompts stop taxing short-request ITL on the
    # decode tier (docs/SERVING.md "Disaggregated tiers").  0 (default)
    # disables role-aware routing: every replica serves mixed, the
    # exact pre-disagg fabric.
    disagg_prompt_threshold: int = 0
    # --- prefix-state cache + preemption (serving/prefix_cache.py,
    # serving/engine.py) ---
    # Prefix-state cache entry cap: chunk-boundary conv/SSM carry
    # snapshots (and full-prompt state+logits pairs) keyed by
    # prompt-prefix hash, so requests sharing a system prompt / few-
    # shot preamble skip the shared prefill work — near-zero TTFT on
    # full hits.  0 disables (the default: the cache pins device
    # buffers alive and — for hybrids — holds KV page refs past
    # request eviction, so it is opt-in).  Hybrid caches are engine-
    # private (entries pin the engine's own page pool).
    prefix_cache_entries: int = 0
    # Byte cap over cached state (carries + logits + pinned KV page
    # bytes); LRU evicts over either cap.  0 => entry cap only.
    prefix_cache_bytes: int = 0
    # Promotion threshold: a prefix must MISS this many lookups before
    # its snapshot is stored (1 = store on first sight; raise to keep
    # one-off prompts from churning the LRU).
    prefix_min_chunk_hits: int = 1
    # Priority a request defaults to when GenerationRequest.priority
    # is None (higher = more important).  When a higher-priority
    # request is queued with no free slot, the engine preempts the
    # lowest-priority DECODING slot: its carry swaps to host RAM (KV
    # page refs held — no page churn) and it resumes later without
    # re-prefill, mid-stream, bit-exactly.
    serving_default_priority: int = 0
    # --- quantized serving (ops/quant.py; docs/SERVING.md "Quantized
    # serving") ---
    # Serving/decode weight dtype.  "bf16" (default) is the byte-stable
    # status quo: the decode cast (inference/generate._decode_params)
    # casts matmul kernels + embedding to ``compute_dtype`` exactly as
    # before.  "int8" quantizes the same leaves symmetric per-channel
    # (q int8 + f32 scale per output column for column-parallel params,
    # per input row for row-parallel, per vocab row for the embedding/
    # head — the scale axis is always the tensor-parallel axis, so
    # scales shard with their weight and no cross-shard rescale is ever
    # needed) and the matmul sites dequantize AT USE: ``(x @ q) * scale``
    # / ``(x * scale) @ q``, fused by XLA — no materialized full-
    # precision weight copy.  Both the serving engine and ``generate()``
    # read this knob through the ONE shared decode cast, so quantized
    # engine==generate() parity holds by construction (toleranced —
    # ``ops/quant.assert_stream_close``).
    serving_weight_dtype: str = "bf16"
    # KV page-pool dtype (hybrid stacks).  "bf16" (default) stores
    # pages in ``compute_dtype`` — the byte-stable status quo.  "int8"
    # stores int8 pages with one f32 scale per (physical page, kv head)
    # alongside the head-major pools; the ragged Pallas kernels fuse
    # the dequant into the scalar-prefetched page walk (read int8 tile
    # -> multiply by scale in-register) and prefill's fused page WRITE
    # quantizes the chunk's K/V before the one-hot merge.  Halves page
    # bytes => ~2x pages per chip at fixed pool HBM (the
    # ``quant_kv_capacity`` bench row).
    kv_page_dtype: str = "bf16"
    # --- speculative decoding (serving/spec_decode.py; docs/SERVING.md
    # "Speculative decoding") ---
    # Draft tokens verified per serving tick.  0 (default) disables —
    # the byte-stable status quo: one token per slot per launch.  K > 0
    # turns every decode tick into a K-token draft/verify step: a
    # drafter proposes K cheap continuation guesses per slot and ONE
    # chunk-machinery launch (models/lm.lm_verify_chunk) scores all
    # K+1 positions at once, committing the longest correct prefix —
    # up to K+2 tokens per full-model weight read instead of 1.
    # Greedy-only (requests must use top_k=1; speculation is lossless
    # under argmax — streams stay token-identical to non-speculative
    # greedy).  Both the serving engine and ``generate()`` read this
    # knob, so the two paths speculate identically (the parity
    # contract, tests/test_spec_decode.py).
    spec_tokens: int = 0
    # Who proposes the K draft tokens: "ngram" (host-side prompt-lookup
    # cache over each stream's own prompt + emitted tokens — free, and
    # strong on repetitive/code-like text) or "model" (a small
    # companion LM running the same ``lm_step`` at a tiny config; the
    # engine/generate() take the ``Drafter`` instance since the
    # companion's params aren't derivable from this config).  Draft
    # quality only moves the acceptance rate, never the tokens.
    spec_drafter: str = "ngram"
    # Longest suffix n-gram the "ngram" drafter matches against the
    # stream's history before falling back to shorter ones.
    spec_ngram_order: int = 3
    # --- occupancy-adaptive compacted ticks (serving/engine.py;
    # docs/SERVING.md "Occupancy-adaptive ticks") ---
    # Compact each decode/verify tick to the LIVE slots: gather the
    # decodable slots (conv/SSM carries, logits, meta, page-table rows)
    # into a pow2 lane bucket — per data shard, so the mesh-sharded
    # pool keeps its tiling — run the existing jitted tick at bucket
    # width, and scatter the results back.  Compute per tick then
    # tracks live slots instead of static capacity (the batch-axis
    # analogue of what paged KV does for cache bytes), which is where
    # low/medium-occupancy traffic wins.  One compiled shape per pow2
    # bucket (same trace discipline as the prompt buckets); token
    # streams are bit-identical to the uncompacted tick by construction
    # (same per-row math, fewer pad rows).  False (default) is the
    # byte-stable status quo: no gather/scatter, identical traces,
    # identical records.
    tick_compaction: bool = False
    # Shrink hysteresis for the compacted-tick lane bucket: the bucket
    # GROWS immediately when live slots need it, but only shrinks after
    # this many consecutive ticks that would have fit the smaller
    # bucket — occupancy jitter around a pow2 boundary must not thrash
    # gather/tick/scatter recompiles.  0 shrinks immediately.
    compaction_hysteresis_ticks: int = 4
    # --- multi-tenant LoRA serving (serving/adapters.py; docs/
    # SERVING.md "Multi-tenant LoRA") ---
    # Named LoRA adapters one engine may serve concurrently.  0
    # (default) disables multi-tenancy entirely — the byte-stable
    # status quo: no factor pools ride the params, no record stamps,
    # identical traces.  > 0 enables the segmented batched-LoRA path:
    # an AdapterRegistry holds up to this many named adapters' low-rank
    # {A, B} factors over the linear()-routed projections, a bounded
    # device AdapterCache stacks them into (slots+1, ...) factor pools
    # (row 0 = the zero "no adapter" factors), and every tick computes
    # ``y = base(x) + (x @ A[ids]) @ B[ids]`` with per-slot adapter ids
    # gathered from the slot pool's meta — slots running DIFFERENT
    # adapters share ONE compiled launch.  Parity regime: a stream
    # under adapter a matches solo ``generate()`` on the MERGED weights
    # ``W + (alpha/rank)·A@B`` via ops/quant.assert_stream_close
    # (toleranced — the segmented delta re-associates float sums, so
    # bit-exactness is the wrong pin; greedy tokens agree exactly on
    # the fp32 CPU matrix, tests/test_tenant_lora.py).
    lora_max_adapters: int = 0
    # Low-rank dimension r shared by every adapter on the engine (the
    # factor pools are static-shape).
    lora_rank: int = 8
    # Default LoRA scaling numerator: the delta is weighted alpha/rank
    # (per-adapter alpha may override at registration; the scale is
    # folded into the stored B factors once, so the hot path never
    # multiplies by it).
    lora_alpha: float = 16.0
    # Device factor-pool slots (adapters resident on-device at once).
    # 0 => auto: lora_max_adapters (every registered adapter resident).
    # Set lower to page adapters: admission reserves a slot like it
    # reserves KV pages (waits when all slots are pinned by resident
    # streams — never a mid-flight miss), refcounts pin a slot while
    # any stream uses it, and zero-ref residents evict LRU.
    lora_cache_slots: int = 0
    # Tensor-parallel shards of the serving WEIGHTS over `mesh.model`
    # (the 2-D serving mesh's second axis): Mamba d_inner channels,
    # attention heads and the embedding/head vocab axis split across
    # devices (parallel/sharding.serving_param_specs), so one engine
    # can serve a model bigger than a single device and each device
    # reads 1/N of the weights per decode tick (decode's binding
    # resource).  1 => weights replicated (the exact pre-TP layout:
    # same shardings, same trace counts).  d_inner, padded vocab and
    # (hybrid) head counts must divide evenly — checked with a clear
    # error at engine construction.
    serving_model_shards: int = 1
    # Pipeline-parallel shards of the serving LAYER STACK over
    # `mesh.stage` (the 3-D serving mesh's middle axis,
    # parallel/mesh.serving_mesh): the scan-over-layers parameter
    # stacks AND the slot pool's per-layer conv/SSM carries + KV page
    # pools shard their leading layer axis across stages
    # (parallel/sharding.serving_param_specs / slot_pool_specs), so
    # each stage holds only its own layers' weights and state — the
    # second way (after serving_model_shards) one engine serves a
    # model bigger than a single device, composable with both other
    # axes.  Pure-SSM single-data-shard engines additionally run the
    # decode tick as a GPipe-microbatched schedule over the lane
    # bucket (parallel/pipeline.pipelined_decode_layers).  1 => the
    # exact 2-D status quo: serving_mesh stays ("data", "model") and
    # no spec ever names a stage axis (same shardings, same traces).
    # n_layer (and each hybrid stack family) must divide evenly —
    # checked with a clear error at engine construction.
    serving_stage_shards: int = 1
    # Durable session store (docs/SERVING.md "Durable sessions"):
    # parked sessions' time-to-live in seconds — the background sweeper
    # reaps older ones (0 = park forever; explicit parks may override
    # per call) — and the host-RAM tier's byte budget, above which the
    # LRU parked sessions demote to the disk tier (0 = write-through:
    # everything demotes immediately when a disk tier exists).  Both
    # only take effect where a store is constructed (--state-dir on
    # serve_worker/serve_fabric, or session_store= in code); the
    # default engine/router path carries no store and is byte-stable.
    session_ttl_s: float = 0.0
    session_host_bytes: int = 0
    # --- elastic serving fabric (serving/autoscale/; docs/SERVING.md
    # "Elastic fabric") ---
    # Admission control: fabric-wide queued-request cap above which the
    # router sheds new submits (the named AdmissionRejected -> HTTP 429
    # + Retry-After), and the default per-request queue deadline in
    # milliseconds (requests carrying queue_deadline_ms=None inherit
    # it; shed when the estimated wait exceeds it).  Both 0 (default)
    # = admission control off, the byte-stable status quo.
    admission_queue_cap: int = 0
    admission_deadline_ms: float = 0.0
    # Autoscaling: per-tier fleet ceiling for the AutoscaleController
    # (0 = autoscaling off — the fleet stays operator-sized) and floor,
    # cooldowns after scale-up / any scaling action before the next
    # up / down, consecutive pressured (breached-or-deep-queue) and
    # healthy evaluations before acting (flap absorption), and the
    # mean-queued-per-accepting-replica thresholds that count as
    # pressure / health (the band between them is hysteresis dead
    # zone).  serving/autoscale/controller.AutoscalePolicy validates
    # the cross-field constraints; these knobs only feed it.
    autoscale_max_replicas: int = 0
    autoscale_min_replicas: int = 1
    autoscale_up_cooldown_s: float = 5.0
    autoscale_down_cooldown_s: float = 30.0
    autoscale_breach_evals: int = 3
    autoscale_clear_evals: int = 10
    autoscale_queue_high: float = 2.0
    autoscale_queue_low: float = 0.5
    # --- online per-tenant LoRA tuning (serving/tuning/; docs/
    # SERVING.md "Online adapter tuning") ---
    # Per-tenant fairness quota: max concurrent resident slots one
    # adapter BASE name (any version) may hold on an engine.  0
    # (default) = no quota, the byte-stable status quo.  > 0 makes
    # admission REQUEUE (never shed) a request whose tenant already
    # holds this many slots — the named
    # serving.scheduler.TenantQuotaExceeded deferral, so one hot
    # tenant cannot starve the rest of the slot pool.
    tenant_max_slots: int = 0
    # A/B routing for freshly tuned adapter versions: the fraction of
    # BARE-name requests routed to the tenant's LATEST version; the
    # rest pin the previous one (a deterministic per-request hash of
    # the sampling seed picks the arm, so retries land on the same
    # version).  1.0 (default) routes everyone to the latest — with a
    # single version that is the exact PR-15 status quo.  Explicit
    # ``name@vN`` requests always bypass the split.
    lora_ab_fraction: float = 1.0
    # Online tune-job train-step knobs (serving/tuning/trainer.py):
    # optimizer steps per job (one batch per step, examples cycled),
    # Adam learning rate over the factor leaves, examples per batch,
    # and the fixed sequence length examples are right-padded /
    # truncated to (static shapes keep ONE compiled masked step per
    # fabric).  Inert until a trainer-role replica exists.
    tune_steps: int = 20
    tune_lr: float = 1e-3
    tune_batch_size: int = 4
    tune_seq_len: int = 64

    def __post_init__(self):
        if self.remat_policy not in ("all", "dots", "mixer"):
            raise ValueError(
                f"remat_policy must be 'all', 'dots' or 'mixer', got "
                f"{self.remat_policy!r}"
            )
        if self.ssm_impl not in ("xla", "pallas"):
            raise ValueError(
                f"ssm_impl must be 'xla' or 'pallas', got {self.ssm_impl!r}"
            )
        if self.ssm_impl == "pallas" and self.ssm_layer not in ("mamba1", "mamba2"):
            raise ValueError(
                "ssm_impl='pallas' backs the SSD scan (mamba2) and the "
                f"selective scan (mamba1); got ssm_layer={self.ssm_layer!r}"
            )
        if self.attn_sp_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_sp_impl must be 'ring' or 'ulysses', got "
                f"{self.attn_sp_impl!r}"
            )
        if self.conv_impl not in ("shift", "xla_conv"):
            raise ValueError(
                f"conv_impl must be 'shift' or 'xla_conv', got "
                f"{self.conv_impl!r}"
            )
        if self.loss_impl not in ("dense", "blocked"):
            raise ValueError(
                f"loss_impl must be 'dense' or 'blocked', got "
                f"{self.loss_impl!r}"
            )
        if self.loss_impl == "blocked" and (
            self.loss_vocab_blocks < 1
            or self.vocab_size_padded % self.loss_vocab_blocks != 0
        ):
            raise ValueError(
                f"loss_vocab_blocks={self.loss_vocab_blocks} must be a "
                f"positive divisor of padded vocab {self.vocab_size_padded}"
            )
        if self.prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0 (0 disables chunked "
                f"prefill), got {self.prefill_chunk_tokens}"
            )
        if self.prefill_tokens_per_tick < 0:
            raise ValueError(
                f"prefill_tokens_per_tick must be >= 0 (0 => unbounded), "
                f"got {self.prefill_tokens_per_tick}"
            )
        if self.prefill_schedule not in ("rr", "srpt"):
            raise ValueError(
                f"prefill_schedule must be 'rr' or 'srpt', got "
                f"{self.prefill_schedule!r}"
            )
        if self.serving_replicas < 1:
            raise ValueError(
                f"serving_replicas must be >= 1, got {self.serving_replicas}"
            )
        if self.serving_data_shards < 1:
            raise ValueError(
                f"serving_data_shards must be >= 1, got "
                f"{self.serving_data_shards}"
            )
        if self.serving_model_shards < 1:
            raise ValueError(
                f"serving_model_shards must be >= 1, got "
                f"{self.serving_model_shards}"
            )
        if self.serving_stage_shards < 1:
            raise ValueError(
                f"serving_stage_shards must be >= 1, got "
                f"{self.serving_stage_shards}"
            )
        if self.compaction_hysteresis_ticks < 0:
            raise ValueError(
                f"compaction_hysteresis_ticks must be >= 0 (0 shrinks the "
                f"lane bucket immediately), got "
                f"{self.compaction_hysteresis_ticks}"
            )
        if self.disagg_prompt_threshold < 0:
            raise ValueError(
                f"disagg_prompt_threshold must be >= 0 (0 disables "
                f"role-aware routing), got {self.disagg_prompt_threshold}"
            )
        if self.prefix_cache_entries < 0:
            raise ValueError(
                f"prefix_cache_entries must be >= 0 (0 disables the "
                f"prefix-state cache), got {self.prefix_cache_entries}"
            )
        if self.prefix_cache_bytes < 0:
            raise ValueError(
                f"prefix_cache_bytes must be >= 0 (0 => entry cap only), "
                f"got {self.prefix_cache_bytes}"
            )
        if self.prefix_min_chunk_hits < 1:
            raise ValueError(
                f"prefix_min_chunk_hits must be >= 1 (store on first "
                f"sight), got {self.prefix_min_chunk_hits}"
            )
        if self.kv_page_tokens < 8 or self.kv_page_tokens % 8:
            raise ValueError(
                f"kv_page_tokens must be a positive multiple of 8 (page-"
                f"bucketed masked attention is bit-stable only at 8-lane "
                f"granularity), got {self.kv_page_tokens}"
            )
        if self.kv_slot_tokens < self.kv_page_tokens:
            raise ValueError(
                f"kv_slot_tokens={self.kv_slot_tokens} must hold at least "
                f"one page of kv_page_tokens={self.kv_page_tokens}"
            )
        if self.kv_pool_pages < 0:
            raise ValueError(
                f"kv_pool_pages must be >= 0 (0 => auto-size from "
                f"capacity), got {self.kv_pool_pages}"
            )
        if self.serving_weight_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"serving_weight_dtype must be 'bf16' (the compute-dtype "
                f"decode cast, the status quo) or 'int8', got "
                f"{self.serving_weight_dtype!r}"
            )
        if self.kv_page_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_page_dtype must be 'bf16' (compute-dtype pages, the "
                f"status quo) or 'int8', got {self.kv_page_dtype!r}"
            )
        if self.spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0 (0 disables speculative "
                f"decoding), got {self.spec_tokens}"
            )
        if self.spec_drafter not in ("ngram", "model"):
            raise ValueError(
                f"spec_drafter must be 'ngram' or 'model', got "
                f"{self.spec_drafter!r}"
            )
        if self.spec_ngram_order < 1:
            raise ValueError(
                f"spec_ngram_order must be >= 1, got "
                f"{self.spec_ngram_order}"
            )
        if self.lora_max_adapters < 0:
            raise ValueError(
                f"lora_max_adapters must be >= 0 (0 disables multi-"
                f"tenant LoRA serving), got {self.lora_max_adapters}"
            )
        if self.lora_max_adapters > 0:
            if self.lora_rank < 1:
                raise ValueError(
                    f"lora_rank must be >= 1 when LoRA serving is on, "
                    f"got {self.lora_rank}"
                )
            if self.lora_alpha <= 0:
                raise ValueError(
                    f"lora_alpha must be > 0, got {self.lora_alpha}"
                )
            if self.lora_cache_slots < 0:
                raise ValueError(
                    f"lora_cache_slots must be >= 0 (0 => auto: "
                    f"lora_max_adapters), got {self.lora_cache_slots}"
                )
        if self.tenant_max_slots < 0:
            raise ValueError(
                f"tenant_max_slots must be >= 0 (0 = no per-tenant "
                f"quota), got {self.tenant_max_slots}"
            )
        if not 0.0 <= self.lora_ab_fraction <= 1.0:
            raise ValueError(
                f"lora_ab_fraction must be in [0, 1] (the share of "
                f"bare-name requests routed to the latest adapter "
                f"version), got {self.lora_ab_fraction}"
            )
        if self.tune_steps < 1:
            raise ValueError(
                f"tune_steps must be >= 1, got {self.tune_steps}"
            )
        if self.tune_lr <= 0:
            raise ValueError(
                f"tune_lr must be > 0, got {self.tune_lr}"
            )
        if self.tune_batch_size < 1:
            raise ValueError(
                f"tune_batch_size must be >= 1, got "
                f"{self.tune_batch_size}"
            )
        if self.tune_seq_len < 1:
            raise ValueError(
                f"tune_seq_len must be >= 1, got {self.tune_seq_len}"
            )
        if self.session_ttl_s < 0:
            raise ValueError(
                f"session_ttl_s must be >= 0 (0 = parked sessions never "
                f"expire), got {self.session_ttl_s}"
            )
        if self.session_host_bytes < 0:
            raise ValueError(
                f"session_host_bytes must be >= 0 (0 = write-through to "
                f"the disk tier), got {self.session_host_bytes}"
            )
        if self.admission_queue_cap < 0:
            raise ValueError(
                f"admission_queue_cap must be >= 0 (0 = no cap), got "
                f"{self.admission_queue_cap}"
            )
        if self.admission_deadline_ms < 0:
            raise ValueError(
                f"admission_deadline_ms must be >= 0 (0 = no default "
                f"deadline), got {self.admission_deadline_ms}"
            )
        if self.autoscale_max_replicas < 0:
            raise ValueError(
                f"autoscale_max_replicas must be >= 0 (0 = autoscaling "
                f"off), got {self.autoscale_max_replicas}"
            )
        if self.autoscale_min_replicas < 1:
            raise ValueError(
                f"autoscale_min_replicas must be >= 1, got "
                f"{self.autoscale_min_replicas}"
            )
        if self.autoscale_max_replicas:
            # the cross-field policy constraints (min <= max, low <=
            # high, positive eval counts, non-negative cooldowns) live
            # with AutoscalePolicy — build one so a bad config fails
            # HERE at validation, not at the first controller tick
            self.autoscale_policy()
        if self.attn_impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"attn_impl must be 'auto', 'xla' or 'pallas', got "
                f"{self.attn_impl!r}"
            )
        if self.moe_num_experts:
            if self.moe_num_experts < 2:
                raise ValueError("moe_num_experts must be 0 (dense) or >= 2")
            if self.d_intermediate <= 0:
                raise ValueError(
                    "MoE replaces the gated MLP: moe_num_experts > 0 needs "
                    "d_intermediate > 0"
                )
            if not 1 <= self.moe_top_k <= self.moe_num_experts:
                raise ValueError(
                    f"moe_top_k={self.moe_top_k} must be in "
                    f"[1, {self.moe_num_experts}]"
                )

    def autoscale_policy(self):
        """The ``serving.autoscale.AutoscalePolicy`` these knobs
        describe (its ``__post_init__`` validates the cross-field
        constraints).  Only meaningful with ``autoscale_max_replicas``
        > 0 — callers gate on that, this just packages the fields.
        Lazy import: config must stay importable without the serving
        stack."""
        from mamba_distributed_tpu.serving.autoscale.controller import (
            AutoscalePolicy,
        )

        return AutoscalePolicy(
            min_replicas=self.autoscale_min_replicas,
            max_replicas=self.autoscale_max_replicas,
            scale_up_cooldown_s=self.autoscale_up_cooldown_s,
            scale_down_cooldown_s=self.autoscale_down_cooldown_s,
            breach_evals_up=self.autoscale_breach_evals,
            clear_evals_down=self.autoscale_clear_evals,
            queue_depth_high=self.autoscale_queue_high,
            queue_depth_low=self.autoscale_queue_low,
        )

    @property
    def vocab_size_padded(self) -> int:
        m = self.pad_vocab_size_multiple
        return ((self.vocab_size + m - 1) // m) * m

    @property
    def d_inner(self) -> int:
        return self.expand * self.d_model

    @property
    def effective_d_state(self) -> int:
        if self.d_state:
            return self.d_state
        return 128 if self.ssm_layer == "mamba2" else 16

    @property
    def effective_dt_rank(self) -> int:
        return self.dt_rank or math.ceil(self.d_model / 16)

    @property
    def effective_prefill_chunk_tokens(self) -> int:
        """Chunked-prefill chunk width actually used (0 => disabled).

        For mamba2 the configured width rounds UP to the next multiple
        of ``chunk_size`` so prefill-chunk boundaries always land on SSD
        chunk boundaries (a misaligned split would degrade the chunked
        scan via ``_divisor_chunk``), whatever a sweep sets
        ``chunk_size`` to.  Every chunked-prefill consumer — the serving
        engine, ``generate()``, the planner — reads THIS, never the raw
        field, so the two sides can never disagree on the layout.
        """
        c = self.prefill_chunk_tokens
        if c <= 0:
            return 0
        if self.ssm_layer == "mamba2" and c % self.chunk_size:
            return ((c + self.chunk_size - 1) // self.chunk_size) * self.chunk_size
        return c

    @property
    def effective_lora_cache_slots(self) -> int:
        """Device adapter-cache slots actually allocated (0 = LoRA
        off): ``lora_cache_slots``, or every registered adapter
        resident when the knob is 0."""
        if self.lora_max_adapters <= 0:
            return 0
        return self.lora_cache_slots or self.lora_max_adapters

    @property
    def kv_quantized(self) -> bool:
        """True when the paged attention KV pools store int8 pages with
        per-(page, kv-head) f32 scales (``kv_page_dtype="int8"``)."""
        return self.kv_page_dtype == "int8"

    @property
    def kv_pages_per_slot(self) -> int:
        """Page-table width of one serving slot (ceil of the per-request
        KV budget in pages)."""
        return -(-self.kv_slot_tokens // self.kv_page_tokens)

    @property
    def nheads(self) -> int:
        assert self.d_inner % self.headdim == 0
        return self.d_inner // self.headdim

    @property
    def effective_attn_num_heads(self) -> int:
        return self.attn_num_heads or self.d_model // 64

    @property
    def effective_attn_num_kv_heads(self) -> int:
        return self.attn_num_kv_heads or self.effective_attn_num_heads

    @property
    def effective_attn_head_dim(self) -> int:
        return self.attn_head_dim or self.d_model // self.effective_attn_num_heads

    def num_params(self) -> int:
        """Analytic parameter count (used for MFU and sanity checks)."""
        d, v = self.d_model, self.vocab_size_padded
        di, ds = self.d_inner, self.effective_d_state
        n = 0
        n += v * d  # embedding (tied head adds nothing)
        if not self.tie_embeddings:
            n += v * d
        for i in range(self.n_layer):
            n += d  # pre-norm scale
            if i in self.attn_layer_idx:
                nh = self.effective_attn_num_heads
                nkv = self.effective_attn_num_kv_heads
                hd = self.effective_attn_head_dim
                n += d * (nh * hd) + 2 * d * (nkv * hd) + (nh * hd) * d
            elif self.ssm_layer == "mamba1":
                dtr = self.effective_dt_rank
                n += d * 2 * di  # in_proj
                n += di * self.d_conv + (di if self.conv_bias else 0)
                n += di * (dtr + 2 * ds)  # x_proj
                n += dtr * di + di  # dt_proj (+bias always)
                n += di * ds  # A_log
                n += di  # D
                n += di * d  # out_proj
            else:  # mamba2
                g, nh = self.ngroups, self.nheads
                d_in_proj = 2 * di + 2 * g * ds + nh
                conv_dim = di + 2 * g * ds
                n += d * d_in_proj
                n += conv_dim * self.d_conv + (conv_dim if self.conv_bias else 0)
                n += nh  # dt_bias
                n += nh  # A_log
                n += di if self.d_has_hdim else nh  # D
                n += di  # gated norm scale
                n += di * d  # out_proj
            if self.d_intermediate > 0:
                n += d  # second norm
                mlp = d * self.d_intermediate * 2 + self.d_intermediate * d
                if self.moe_num_experts:
                    n += d * self.moe_num_experts  # router
                    n += self.moe_num_experts * mlp  # expert-stacked MLPs
                else:
                    n += mlp  # gated MLP
        n += d  # final norm
        return n


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. Axis sizes of 1 collapse that axis.

    data  - pure data parallel (gradients psum'd, params replicated)
    fsdp  - data parallel + param/optimizer-state sharding (ZeRO-3 style)
    seq   - sequence/context parallelism (SSD chunk-state passing, ring attn)
    tensor- tensor parallelism over d_inner/heads
    pipe  - GPipe pipeline stages over the layer stack (the grad-accum
            microbatches feed the pipeline; parallel/pipeline.py)
    expert- expert parallelism: MoE expert-stacked MLP weights shard
            their expert axis here; tokens are batch-sharded over it too
            (an extra pure-DP axis for the non-MoE layers), so the MoE
            dispatch/combine einsums become GSPMD all-to-alls
    """

    data: int = 1
    fsdp: int = 1
    seq: int = 1
    tensor: int = 1
    pipe: int = 1
    expert: int = 1

    @property
    def num_devices(self) -> int:
        return (self.data * self.fsdp * self.seq * self.tensor * self.pipe
                * self.expert)

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("data", "fsdp", "seq", "tensor", "pipe", "expert")

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.seq, self.tensor, self.pipe,
                self.expert)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Token-shard data pipeline (reference: dataloader.py)."""

    data_dir: str = "edu_fineweb10B"  # reference dataloader.py:23
    # If True and data_dir is missing, generate deterministic synthetic shards
    # (the real 10B-token corpus is "bring your own data", reference README).
    allow_synthetic: bool = True
    synthetic_tokens_per_shard: int = 2_097_152
    synthetic_num_shards: int = 2


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Host-side telemetry (mamba_distributed_tpu/obs/): spans, divergence
    sentinels, flight recorder.  Everything defaulting to on is strictly
    host-side and free of device syncs; ``overflow_threshold`` is the one
    knob that changes the compiled train step (docs/OBSERVABILITY.md)."""

    # span tracer -> {log_dir}/events.jsonl (trainer, eval, checkpointing)
    spans: bool = False
    # non-finite loss/grad-norm watchdog on already-fetched host scalars,
    # feeding the flight-recorder ring that dumps on crash/divergence
    sentinel: bool = True
    # raise DivergenceError on a non-finite step (after dumping) — a NaN
    # run only burns compute; opt out for loss-spike research
    halt_on_divergence: bool = True
    flight_recorder_len: int = 64
    # > 0: the compiled train step also returns an int32 flag for
    # grad_norm > threshold (or non-finite), accumulated host-side —
    # the on-device global-norm overflow counter.  0 disables.
    overflow_threshold: float = 0.0
    # --- serving SLO targets (obs/slo.py): rolling-window p95 targets
    # in milliseconds over the last `slo_window_requests` finished
    # requests; 0 leaves a metric untargeted.  Crossing a target emits
    # one `slo_breach` event record (and `slo_recovered` on the way
    # back); scripts/obs_report.py renders the attainment table.  All
    # host-side — no device syncs, no extra jit traces. ---
    slo_ttft_p95_ms: float = 0.0
    slo_itl_p95_ms: float = 0.0
    slo_queue_wait_p95_ms: float = 0.0
    slo_window_requests: int = 64
    # --- live telemetry plane (docs/OBSERVABILITY.md "Live telemetry
    # plane") ---
    # byte cap on a SpanTracer's jsonl file: exceeding it rolls the
    # file to `<name>.1` (one generation kept; obs/export.load_jsonl
    # reads the pair oldest-first).  0 = never rotate.
    span_rotate_bytes: int = 0
    # XLA compile watchdog (obs/watchdog.py): count/time every backend
    # compile, stamp `compiles`/`compile_ms` on serving_tick records
    # and expose them on GET /metrics.  Off (default) keeps records
    # byte-stable.
    compile_watchdog: bool = False
    # > threshold compiles inside one tumbling window fires ONE
    # `compile_thrash` event record (0 = count only, never fire)
    compile_thrash_threshold: int = 0
    compile_thrash_window_s: float = 60.0
    # --- tick-latency regression sentinel (obs/slo.py
    # TickRegressionDetector): breach when the EWMA-smoothed tick
    # latency exceeds `tick_regression_factor` x the learned baseline.
    # factor 0 (default) = off. ---
    tick_regression_factor: float = 0.0
    tick_ewma_alpha: float = 0.1
    tick_regression_warmup: int = 32

    def __post_init__(self):
        if self.flight_recorder_len < 1:
            raise ValueError(
                f"flight_recorder_len must be >= 1, got "
                f"{self.flight_recorder_len}"
            )
        if self.overflow_threshold < 0:
            raise ValueError(
                f"overflow_threshold must be >= 0 (0 disables), got "
                f"{self.overflow_threshold}"
            )
        if self.overflow_threshold > 0 and not self.sentinel:
            raise ValueError(
                "overflow_threshold > 0 needs sentinel=True — the host-"
                "side accumulator and flight record that consume the "
                "on-device flag live on the sentinel"
            )
        for name in ("slo_ttft_p95_ms", "slo_itl_p95_ms",
                     "slo_queue_wait_p95_ms"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0 (0 disables), got "
                    f"{getattr(self, name)}"
                )
        if self.slo_window_requests < 1:
            raise ValueError(
                f"slo_window_requests must be >= 1, got "
                f"{self.slo_window_requests}"
            )
        if self.span_rotate_bytes < 0:
            raise ValueError(
                f"span_rotate_bytes must be >= 0 (0 = never rotate), "
                f"got {self.span_rotate_bytes}"
            )
        if self.compile_thrash_threshold < 0:
            raise ValueError(
                f"compile_thrash_threshold must be >= 0 (0 = count "
                f"only), got {self.compile_thrash_threshold}"
            )
        if self.compile_thrash_window_s <= 0:
            raise ValueError(
                f"compile_thrash_window_s must be > 0, got "
                f"{self.compile_thrash_window_s}"
            )
        if self.tick_regression_factor and self.tick_regression_factor <= 1:
            raise ValueError(
                f"tick_regression_factor must be > 1 (breach = factor "
                f"x baseline; 0 disables), got "
                f"{self.tick_regression_factor}"
            )
        if not 0.0 < self.tick_ewma_alpha <= 1.0:
            raise ValueError(
                f"tick_ewma_alpha must be in (0, 1], got "
                f"{self.tick_ewma_alpha}"
            )
        if self.tick_regression_warmup < 1:
            raise ValueError(
                f"tick_regression_warmup must be >= 1, got "
                f"{self.tick_regression_warmup}"
            )


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training loop config (reference: train.py:43-53,89-110,114,133)."""

    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )

    total_batch_size: int = 524288  # tokens/step (train.py:43)
    micro_batch_size: int = 32  # B (train.py:44)
    seq_len: int = 1024  # T (train.py:45)

    max_lr: float = 6e-4
    min_lr_ratio: float = 0.1
    warmup_steps: int = 715
    max_steps: int = 19073
    weight_decay: float = 0.1
    adam_b1: float = 0.9
    adam_b2: float = 0.95
    adam_eps: float = 1e-8
    grad_clip: float = 1.0

    seed: int = 1337  # train.py:37

    val_every: int = 250  # train.py:133
    val_steps: int = 20  # train.py:138
    sample_every: int = 250  # train.py:166
    checkpoint_every: int = 1000  # train.py:152
    log_dir: str = "log"

    # FSDP / remat
    shard_params: bool = False  # shard params+opt state over the fsdp axis
    remat: bool = True  # per-block activation checkpointing

    def __post_init__(self):
        m = self.mesh
        if m.pipe > 1 and (m.seq * m.tensor * m.expert) > 1:
            # the GPipe schedule composes with the pure-DP batch axes
            # (data/fsdp: each replica runs the schedule on its batch
            # slice) but not with seq/tensor/expert, whose shardings cut
            # through the activations the schedule declares stage-local
            raise ValueError(
                f"mesh.pipe={m.pipe} composes with data/fsdp only; got "
                f"seq={m.seq}, tensor={m.tensor}, expert={m.expert}"
            )
        if m.pipe > 1 and self.model.moe_num_experts:
            raise ValueError(
                "MoE models do not pipeline yet (the aux-loss carry is "
                "not threaded through the GPipe schedule); use pipe=1"
            )
        if m.expert > 1:
            if not self.model.moe_num_experts:
                raise ValueError(
                    f"mesh.expert={m.expert} needs a MoE model "
                    "(moe_num_experts > 0)"
                )
            if self.model.moe_num_experts % m.expert:
                raise ValueError(
                    f"moe_num_experts={self.model.moe_num_experts} must "
                    f"divide over mesh.expert={m.expert}"
                )
        if m.pipe > 1 and self.shard_params:
            raise ValueError(
                "mesh.pipe > 1 keeps params replicated across data/fsdp "
                "(stage-sharded over pipe); shard_params=True is not "
                "supported with pipeline parallelism"
            )
        if m.pipe > 1 and self.model.attn_layer_idx:
            # a PERIODIC hybrid pipelines by supersteps (one attn layer per
            # period — models/lm._hybrid_period); aperiodic patterns can't
            # shard evenly over stages
            from mamba_distributed_tpu.models.lm import _hybrid_period

            if _hybrid_period(self.model) is None:
                raise ValueError(
                    "pipeline parallelism needs a uniform layer stack or a "
                    "periodic hybrid (one attn layer every n_layer/n_attn)"
                )
            if len(self.model.attn_layer_idx) % m.pipe != 0:
                raise ValueError(
                    f"hybrid pipeline: n_attn={len(self.model.attn_layer_idx)} "
                    f"supersteps must divide over mesh.pipe={m.pipe} stages"
                )
        elif m.pipe > 1 and self.model.n_layer % m.pipe != 0:
            raise ValueError(
                f"n_layer={self.model.n_layer} must divide over "
                f"mesh.pipe={m.pipe} stages"
            )

    @property
    def grad_accum_steps(self) -> int:
        denom = self.micro_batch_size * self.seq_len * self.data_parallel_size
        assert self.total_batch_size % denom == 0, (
            "make sure total_batch_size is divisible by B * T * dp_size"
        )
        return self.total_batch_size // denom

    @property
    def data_parallel_size(self) -> int:
        # expert is an extra pure-DP batch axis for the non-MoE layers
        return self.mesh.data * self.mesh.fsdp * self.mesh.expert


def _mk(model: Mapping[str, Any], train: Mapping[str, Any]) -> TrainConfig:
    mesh = train.pop("mesh", {})
    data = train.pop("data", {})
    return TrainConfig(
        model=ModelConfig(**dict(model)),
        mesh=MeshConfig(**dict(mesh)),
        data=DataConfig(**dict(data)),
        **dict(train),
    )


# The five BASELINE.json configurations (plus a CPU-runnable smoke preset).
PRESETS: dict[str, TrainConfig] = {
    # 0. quick-start: minutes on a CPU, for smoke runs and demos
    "mamba2-tiny": _mk(
        dict(d_model=128, n_layer=4, ssm_layer="mamba2", headdim=32,
             d_state=64, chunk_size=64, vocab_size=4096),
        dict(
            seq_len=256,
            micro_batch_size=8,
            total_batch_size=4096,
            max_steps=300,
            warmup_steps=20,
            val_every=25,
        ),
    ),
    # 0c. CPU-runnable hybrid: attention every 2nd layer at tiny scale —
    # the serving/bench shape for the paged-KV hybrid decode path
    "hybrid-tiny": _mk(
        dict(d_model=128, n_layer=4, ssm_layer="mamba2", headdim=32,
             d_state=64, chunk_size=64, vocab_size=4096,
             attn_layer_idx=(1, 3), attn_num_heads=4, attn_num_kv_heads=2,
             prefill_chunk_tokens=128, kv_page_tokens=32,
             kv_slot_tokens=512),
        dict(
            seq_len=256,
            micro_batch_size=8,
            total_batch_size=4096,
            max_steps=300,
            warmup_steps=20,
            val_every=25,
        ),
    ),
    # 0b. CPU-runnable *artifact* scale: the reference's recipe semantics
    # (T=1024, padded GPT-2 vocab, warmup-715 cosine, 250-step val
    # cadence) at a model/batch size a single CPU core can push past the
    # first val checkpoint overnight — used to produce the >=250-step
    # logged curve scored by compare_parity's val@250 check when no chip
    # window allows the full 280M run (ref first checkpoint:
    # /root/reference/log/log_mamba.txt "250 val 5.4865")
    "mamba2-mini": _mk(
        dict(d_model=256, n_layer=8, ssm_layer="mamba2"),
        dict(
            # measured on the round-5 single-core box: ~21 s/step at
            # 4096 tok/step (8192 was 42 s/step — past the overnight
            # budget for 500 steps)
            micro_batch_size=4,
            total_batch_size=4096,
            val_every=250,
        ),
    ),
    # 1. repo default: Mamba-2 280M, seq 1024, single chip
    "mamba2-280m": _mk(
        dict(d_model=768, n_layer=64, ssm_layer="mamba2"),
        dict(),
    ),
    # reference train.py:75 as-written actually builds Mamba-1 (SURVEY 2.4)
    "mamba1-280m": _mk(
        dict(d_model=768, n_layer=64, ssm_layer="mamba1"),
        dict(),
    ),
    # single-chip hybrid (config-5 architecture at 280M scale): attention
    # every 8th layer, GQA 12q/4kv — the shape the attn_impl sweep benches
    "hybrid-280m": _mk(
        dict(d_model=768, n_layer=64, ssm_layer="mamba2",
             attn_layer_idx=tuple(range(3, 64, 8)), attn_num_heads=12,
             attn_num_kv_heads=4),
        dict(),
    ),
    # 2. 280M data-parallel over 8 chips (DDP -> pjit drop-in)
    "mamba2-280m-dp8": _mk(
        dict(d_model=768, n_layer=64, ssm_layer="mamba2"),
        dict(mesh=dict(data=8)),
    ),
    # 3. 1.3B FSDP on 16 chips (param + optimizer-state sharding)
    "mamba2-1.3b-fsdp16": _mk(
        dict(d_model=2048, n_layer=48, ssm_layer="mamba2"),
        dict(
            mesh=dict(fsdp=16),
            shard_params=True,
            micro_batch_size=8,
            total_batch_size=1048576,
        ),
    ),
    # 4. 2.8B long-context: seq 8192, sequence-parallel over 32 chips
    "mamba2-2.8b-sp32": _mk(
        dict(d_model=2560, n_layer=64, ssm_layer="mamba2"),
        dict(
            mesh=dict(fsdp=8, seq=4),
            shard_params=True,
            seq_len=8192,
            micro_batch_size=8,
            total_batch_size=2097152,
        ),
    ),
    # 5. Jamba-style hybrid 7B (attention every 8th layer) on 64 chips
    "hybrid-7b": _mk(
        dict(
            d_model=4096,
            n_layer=32,
            ssm_layer="mamba2",
            d_intermediate=14336,
            attn_layer_idx=tuple(range(3, 32, 8)),
            attn_num_heads=32,
            attn_num_kv_heads=8,
        ),
        dict(
            mesh=dict(fsdp=16, seq=4),
            shard_params=True,
            seq_len=4096,
            micro_batch_size=4,
            total_batch_size=4194304,
        ),
    ),
}


def get_preset(name: str, **overrides: Any) -> TrainConfig:
    cfg = PRESETS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg
