"""Speculative decoding on the chunk machinery: K-token draft-verify.

Decode is weight-bandwidth-bound: every serving tick launches the full
weight read to emit ONE token per slot, so inter-token latency at small
batch is priced by weight bytes, not math.  The chunked-prefill path
already pushes a K-token chunk through the conv/SSM carries and the
ragged paged-attention write in one launch — exactly the VERIFIER
speculative decoding needs (``models/lm.lm_verify_chunk`` is that chunk
step returning per-position logits).  A cheap drafter proposes K
continuation guesses, one launch scores all of them, and the longest
correct prefix commits: up to K+2 tokens per full weight read.

Greedy-only, and LOSSLESS: under argmax sampling an accepted draft is by
definition the token the model would have emitted, and rejections are
replaced by the model's own argmax at the rejected position — so
speculative streams are token-identical to non-speculative greedy
streams whatever the drafter proposes (draft quality only moves the
acceptance rate).  Sampling-mode rejection sampling is a ROADMAP
residual.

The pending-token scheme (what makes rollback O(1))
---------------------------------------------------

The verify chunk advances the carries through ALL K+1 fed tokens, so a
partial acceptance cannot keep the returned state.  Instead of
recomputing the accepted prefix, commitment is decoupled from state
advance:

  * each stream carries ``pending`` — tokens already COMMITTED to the
    output (emitted, final) but not yet folded into the device state;
  * a verify tick feeds ``pending + drafts`` (static width ``W = K+1``);
    pending tokens are trusted, drafts verify against the previous
    position's argmax;
  * if EVERY fed token verified, the returned carries commit as-is (the
    state advanced W tokens) and the final position's argmax is one
    bonus committed token — the new 1-token pending;
  * on the FIRST rejection the pre-tick carries are restored (a per-row
    ``jnp.where`` — the rollback primitive the PR-9/10 snapshot/restore
    machinery established) and the accepted prefix plus the model's
    correction token become the new pending: the next tick re-feeds
    them as trusted tokens, so every launch still commits >= 1 token.

Hybrid stacks need no KV rollback at all: the verify chunk writes the
fed tokens' K/V at ``[lengths, lengths + W)``, and a rejected tick just
does not advance the host ``lengths`` mirror — the written cells are
dead-by-``lengths`` (the invariant the ragged kernels already honor)
and the next verify overwrites them.  The engine's page-table rows gain
one permanent trash column in spec mode so a fully-allocated slot's
overshoot writes land on the trash page, never on a live cell.

Drafters
--------

``NGramDrafter`` — host-side prompt-lookup: match the stream's trailing
n-gram against its OWN history (prompt + emitted tokens) and propose
the continuation that followed the most recent occurrence.  Free, and
strong on repetitive/code-like text.  ``ModelDrafter`` — a small
companion model running the same ``lm_step`` at a tiny config; drafts
are its greedy rollout.  Both are deterministic, which is what lets the
engine and ``generate()`` speculate identically (same drafts -> same
accept pattern -> same verify-chunk splits -> bit-identical streams —
the parity-by-construction contract, tests/test_spec_decode.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.inference.bucketing import (
    next_pow2_bucket,
    pad_to_bucket,
)
from mamba_distributed_tpu.inference.generate import vocab_pad_mask
from mamba_distributed_tpu.models.lm import (
    lm_prefill,
    lm_step,
    lm_verify_chunk,
)
from mamba_distributed_tpu.serving.prefill import (
    cast_decode_params,
    chunked_prefill,
    plan_chunks,
)

# Python-side-effect trace counters (one bump per jit trace): the verify
# and commit steps run at ONE static shape per engine, so speculation
# adds zero retraces across any accept/reject/occupancy mix — pinned by
# tests/test_spec_decode.py.  The draft-model jits count separately
# (they run the COMPANION config's shapes).
TRACE_COUNTS = {
    "verify": 0,
    "commit": 0,
    "prefill": 0,
    "draft_prefill": 0,
    "draft_step": 0,
    "draft_rollout": 0,
}


# --------------------------------------------------------------- jitted steps


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"),
                   donate_argnums=(1,))
def spec_verify(params: dict, state, ids: jax.Array, token_mask: jax.Array,
                cfg: ModelConfig, mesh=None,
                adapter_ids: jax.Array | None = None):
    """The verify launch: feed every row's ``ids`` (b, W) through
    ``lm_verify_chunk`` from ``state`` and score all W positions.

    ``state`` is donated (hybrid page pools write in place, exactly like
    the prefill chunk step); the PRE-tick conv/SSM carries — and, for
    hybrids, the pre-tick ``attn_meta`` — come back as ``old`` so the
    caller can roll rejected rows back without ever copying host-side.
    ``token_mask`` rows are all-1 for live slots and all-0 for masked
    ones (empty/done/mid-prefill): masked rows' KV writes flush to the
    trash page and their garbage carries are discarded by
    ``spec_commit``'s per-row select.

    Returns ``(greedy (b, W) int32, final_logits (b, V) fp32, new_state,
    old)`` where ``greedy[:, i]`` is the argmax (over the real vocab)
    after fed token i — the entire accept/reject decision input, small
    enough that fetching it is the tick's one host sync.

    ``mesh`` (static; a 2-D serving mesh with model > 1, else None)
    re-asserts the tensor-parallel weight layout — the same constraint
    the prefill chunk step applies, so speculative and non-speculative
    launches partition identically at ``serving_model_shards > 1``.
    """
    TRACE_COUNTS["verify"] += 1
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            constrain_serving_params,
        )

        params = constrain_serving_params(params, mesh)
    if adapter_ids is not None:
        # multi-tenant LoRA (serving/adapters.py): per-row adapter ids
        # bound into the attached factor pools, so heterogeneous-
        # adapter streams share this ONE verify launch exactly as they
        # share the plain tick
        from mamba_distributed_tpu.serving.adapters import (
            bind_adapter_ids,
        )

        params = bind_adapter_ids(params, adapter_ids)
    old = {"blocks": state["blocks"]}
    if "attn_meta" in state:
        old["attn_meta"] = state["attn_meta"]
    pos_logits, new_state = lm_verify_chunk(
        params, cfg, ids, state, token_mask=token_mask
    )
    pad_mask = vocab_pad_mask(cfg)
    greedy = jnp.argmax(
        pos_logits + pad_mask[None, None, :], axis=-1
    ).astype(jnp.int32)
    return greedy, pos_logits[:, -1], new_state, old


# donate one side of each per-row select only (the output aliases it);
# donating both sides would leave half the buffers unusable
@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def spec_commit(new_state, old_blocks, logits, meta, final_logits,
                advance, width):
    """Per-row accept/rollback select: rows with ``advance`` keep the
    verify step's carries and final logits (their state moved ``width``
    tokens), the rest keep the pre-tick ``old_blocks``/``logits`` —
    all-or-nothing per row, which is what the pending-token scheme buys.
    Hybrid attention pages always ride forward from ``new_state`` (they
    were written in place; rejected rows' cells are dead-by-lengths).
    Returns the reassembled slot pool."""
    TRACE_COUNTS["commit"] += 1
    keep = lambda n, o: jnp.where(
        advance.reshape((1, -1) + (1,) * (n.ndim - 2)), n, o
    )
    blocks = jax.tree.map(keep, new_state["blocks"], old_blocks)
    state = {**new_state, "blocks": blocks}
    new_logits = jnp.where(advance[:, None], final_logits, logits)
    new_meta = {
        **meta,
        "step": meta["step"]
        + jnp.where(advance, width, 0).astype(jnp.int32),
    }
    return {"state": state, "logits": new_logits, "meta": new_meta}


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"))
def _spec_prefill(params: dict, ids: jax.Array, mask: jax.Array,
                  cfg: ModelConfig, mesh=None):
    """Bucketed one-shot prompt prefill for ``spec_generate`` (params
    already decode-cast).  The same ``lm_prefill`` computation the
    serving engine's admission runs, in its own jit so the spec path
    never perturbs the engine/generate trace counters."""
    TRACE_COUNTS["prefill"] += 1
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            constrain_serving_params,
        )

        params = constrain_serving_params(params, mesh)
    return lm_prefill(params, cfg, ids, token_mask=mask)


# ------------------------------------------------------------ host-side logic


def greedy_token(logits, vocab_size: int) -> int:
    """argmax of one logits row over the REAL vocab columns — the exact
    token a greedy (top_k=1) sampler emits (vocab padding rows carry
    logit 0.0 from the zero-padded tied embedding and must not win).
    Host mirror of the device-side ``argmax(logits + vocab_pad_mask)``;
    both break ties toward the lowest index."""
    row = np.asarray(logits).reshape(-1)
    return int(np.argmax(row[:vocab_size]))


def verify_greedy(fed, greedy, n_trusted: int):
    """The accept/rollback decision for one stream.

    ``fed`` (W,) are the tick's fed tokens — the first ``n_trusted``
    are committed (pending) tokens that need no verification, the rest
    are drafts.  ``greedy`` (W,) are the model's argmaxes, ``greedy[i]``
    scoring the position AFTER ``fed[i]``.  Draft ``fed[i]`` is correct
    iff it equals ``greedy[i-1]`` and every earlier draft was too.

    Returns ``(accepted, advance, next_token)``: the accepted draft
    count, whether EVERY fed token verified (state commits) and the
    model's next token after the last valid fed position — the bonus
    token on a full accept, the correction at the first rejection.
    ``n_trusted >= 1`` always (the pending queue is never empty for a
    live stream), so the index is in range.  Shared verbatim by the
    engine and ``spec_generate`` — one copy of the decision rule.
    """
    a = 0
    for i in range(n_trusted, len(fed)):
        if int(fed[i]) == int(greedy[i - 1]):
            a += 1
        else:
            break
    advance = n_trusted + a == len(fed)
    return a, advance, int(greedy[n_trusted + a - 1])


def build_feed(pending, drafts, width: int):
    """Compose one verify row: pending (trusted) + drafts, zero-filled
    to the static ``width``.  Fill tokens are just more drafts — they
    verify like any other guess and are almost always rejected, so a
    short draft never needs masking (masking a SUFFIX would corrupt the
    conv carry; the chunk machinery only supports left pads)."""
    fed = [int(t) for t in pending] + [int(t) for t in drafts]
    fed = fed[:width]
    fed += [0] * (width - len(fed))
    return fed


# ------------------------------------------------------------------- drafters


class Drafter:
    """Draft-token source interface.  One drafter serves many streams
    (keyed by an opaque stream id); all methods are host-side.

    ``observe(stream, tokens)`` appends committed tokens (the prompt
    first, then emissions) to the stream's history; ``draft(stream, n)``
    proposes up to ``n`` continuation guesses — fewer (or none) is
    always legal, correctness never depends on draft quality;
    ``forget(stream)`` drops the stream's state."""

    def observe(self, stream, tokens) -> None:  # pragma: no cover
        raise NotImplementedError

    def draft(self, stream, n: int) -> list:  # pragma: no cover
        raise NotImplementedError

    def forget(self, stream) -> None:  # pragma: no cover
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: match the stream's trailing ``order``-gram
    (falling back to shorter ones) against its own history and propose
    the tokens that followed the MOST RECENT earlier occurrence.  Zero
    model cost; acceptance is high exactly when decode is predictable
    (repeated boilerplate, code, the argmax cycles greedy decoding
    falls into) — which is when the bandwidth win matters most."""

    def __init__(self, order: int = 3):
        if order < 1:
            raise ValueError(f"order must be >= 1, got {order}")
        self.order = order
        self._hist: dict = {}

    def observe(self, stream, tokens) -> None:
        self._hist.setdefault(stream, []).extend(int(t) for t in tokens)

    def draft(self, stream, n: int) -> list:
        h = self._hist.get(stream)
        if n <= 0 or h is None or len(h) < 2:
            return []
        arr = np.asarray(h, np.int64)
        for k in range(min(self.order, arr.size - 1), 0, -1):
            pat = arr[-k:]
            # windows over arr[:-1]: every match ends before the final
            # token, so it has >= 1 continuation token and can never be
            # the query suffix itself
            win = np.lib.stride_tricks.sliding_window_view(arr[:-1], k)
            hits = np.nonzero((win == pat).all(axis=1))[0]
            if hits.size:
                # most recent occurrence with a FULL n-token
                # continuation, else the longest available — a match
                # near the history end (the common case in a periodic
                # tail) would otherwise truncate the draft to a token
                # or two and cap the acceptance run length
                cont = np.minimum(n, arr.size - (hits + k))
                full = hits[cont >= n]
                i = int(full[-1]) if full.size else int(
                    hits[len(hits) - 1 - np.argmax(cont[::-1])]
                )
                return arr[i + k : i + k + n].tolist()
        return []

    def forget(self, stream) -> None:
        self._hist.pop(stream, None)


class ModelDrafter(Drafter):
    """Companion-model drafting: a small LM (its own params + config —
    pure-SSM, so its decode state is O(1)) shadows each stream through
    the same ``lm_step`` the big model uses, and drafts are its greedy
    rollout from the stream's last committed token.  The rollout runs as
    ONE jitted scan (never mutating the stored per-stream state), so a
    draft costs K tiny-model steps against the big model's one saved
    full-width launch per accepted token."""

    def __init__(self, params: dict, cfg: ModelConfig):
        if cfg.attn_layer_idx:
            raise ValueError(
                "ModelDrafter companions are pure-SSM (an O(1)-state "
                "shadow per stream); hybrid draft configs would need "
                "their own paged KV plumbing"
            )
        self.cfg = cfg
        self.params = cast_decode_params(params, cfg=cfg)
        self._streams: dict = {}
        self._rollout_steps = 1

    def observe(self, stream, tokens) -> None:
        toks = [int(t) for t in tokens]
        if not toks:
            return
        st = self._streams.get(stream)
        if st is None:
            # first observation is the prompt (plus anything already
            # emitted): one bucketed prefill instead of len(toks) steps
            ids = jnp.asarray(toks, jnp.int32)[None, :]
            padded, mask = pad_to_bucket(ids, next_pow2_bucket(len(toks)))
            logits, state = _draft_prefill(self.params, padded, mask,
                                           cfg=self.cfg)
            self._streams[stream] = {"state": state, "logits": logits}
            return
        for t in toks:
            logits, state = _draft_step(
                self.params, st["state"], jnp.full((1,), t, jnp.int32),
                cfg=self.cfg,
            )
            st["state"], st["logits"] = state, logits

    def draft(self, stream, n: int) -> list:
        st = self._streams.get(stream)
        if st is None or n <= 0:
            return []
        # fixed rollout width (grown lazily to the largest request) so
        # repeated drafting never retraces; the prefix is what's used
        self._rollout_steps = max(self._rollout_steps, n)
        toks = _draft_rollout(self.params, st["state"], st["logits"],
                              cfg=self.cfg, steps=self._rollout_steps)
        return np.asarray(toks)[:n].tolist()

    def forget(self, stream) -> None:
        self._streams.pop(stream, None)


@functools.partial(jax.jit, static_argnames=("cfg",))
def _draft_prefill(params: dict, ids: jax.Array, mask: jax.Array,
                   cfg: ModelConfig):
    TRACE_COUNTS["draft_prefill"] += 1
    return lm_prefill(params, cfg, ids, token_mask=mask)


@functools.partial(jax.jit, static_argnames=("cfg",))
def _draft_step(params: dict, state, token: jax.Array, cfg: ModelConfig):
    TRACE_COUNTS["draft_step"] += 1
    return lm_step(params, cfg, state, token)


@functools.partial(jax.jit, static_argnames=("cfg", "steps"))
def _draft_rollout(params: dict, state, logits: jax.Array,
                   cfg: ModelConfig, steps: int):
    """Greedy ``steps``-token rollout from (state, logits) WITHOUT
    consuming them (nothing is donated — the stored stream state lives
    on; drafting must never commit)."""
    TRACE_COUNTS["draft_rollout"] += 1
    pad_mask = vocab_pad_mask(cfg)

    def one(carry, _):
        state, logits = carry
        tok = jnp.argmax(logits + pad_mask[None, :], axis=-1).astype(
            jnp.int32
        )
        logits, state = lm_step(params, cfg, state, tok)
        return (state, logits), tok

    (_, _), toks = jax.lax.scan(one, (state, logits), None, length=steps)
    return toks[:, 0]


def make_drafter(cfg: ModelConfig) -> Drafter:
    """The drafter ``cfg`` asks for, when none was passed explicitly.
    ``"model"`` cannot be built from the config alone (the companion's
    params aren't derivable from it) — callers must pass a
    ``ModelDrafter`` instance; the error says so."""
    if cfg.spec_drafter == "model":
        raise ValueError(
            "spec_drafter='model' needs an explicit drafter instance — "
            "the companion model's params are not derivable from the "
            "config; pass drafter=ModelDrafter(draft_params, draft_cfg) "
            "or set spec_drafter='ngram'"
        )
    return NGramDrafter(cfg.spec_ngram_order)


# ------------------------------------------------------- generate() spec path


def spec_generate(
    params: dict,
    cfg: ModelConfig,
    prompt_ids,
    max_new_tokens: int = 32,
    eos_id: int | None = None,
    mesh=None,
    prefix_cache=None,
    drafter: Drafter | None = None,
):
    """The solo-``generate()`` speculative path (batch-1, greedy): the
    IDENTICAL draft -> verify -> accept/rollback loop the serving
    engine's spec tick runs — same prefill layouts, same
    ``spec_verify`` step, same ``verify_greedy`` decision — so
    engine==generate() token parity holds by construction when both use
    the same (deterministic) drafter.  ``inference.generate`` routes
    here when ``cfg.spec_tokens > 0`` and the request is greedy.

    Returns (1, t + max_new_tokens) int32, the ``generate()`` contract:
    with ``eos_id`` set, the suffix past a sampled eos deterministically
    repeats it."""
    prompt = np.asarray(prompt_ids, np.int32)
    if prompt.ndim == 1:
        prompt = prompt[None, :]
    if prompt.shape[0] != 1:
        raise ValueError("spec_generate is batch-1 (the serving engine "
                         "is the batched speculative path)")
    t = prompt.shape[1]
    hybrid = bool(cfg.attn_layer_idx)
    W = cfg.spec_tokens + 1
    dparams = cast_decode_params(params, cfg=cfg)
    plan = plan_chunks(t, cfg.effective_prefill_chunk_tokens, force=hybrid)
    from_cache = prefix_cache is not None and not hybrid
    if hybrid:
        # page capacity covers prompt + budget + the verify overshoot
        # (the last tick may feed up to W tokens past the budget; they
        # must land in allocated-but-dead pages, never clamp onto a
        # live one)
        logits, state = chunked_prefill(
            params, cfg, prompt, max_len=t + max_new_tokens + W, mesh=mesh,
        )
    elif plan is not None:
        logits, state = chunked_prefill(
            params, cfg, prompt, mesh=mesh, prefix_cache=prefix_cache,
        )
    else:
        hit = (prefix_cache.lookup(prompt[0], None)
               if from_cache else None)
        if hit is not None:
            entry = hit[0]
            logits, state = entry.logits, {"blocks": entry.state["blocks"]}
        else:
            padded, mask = pad_to_bucket(
                jnp.asarray(prompt), next_pow2_bucket(t)
            )
            logits, state = _spec_prefill(dparams, padded, mask, cfg=cfg,
                                          mesh=mesh)
    if from_cache:
        # the verify step DONATES its state; a cache-sourced carry must
        # not be destroyed (the entry lives on) — copy the tiny blocks
        state = {**state, "blocks": jax.tree.map(jnp.copy, state["blocks"])}

    if drafter is None:
        drafter = make_drafter(cfg)
    sid = object()  # private stream key; never collides across calls

    pending = [greedy_token(np.asarray(logits)[0], cfg.vocab_size)]
    pending_emitted = 0
    emitted: list[int] = []
    observed = 0
    finished = False
    while not finished:
        # the drafter sees prompt + emitted + unemitted pending — the
        # IDENTICAL observation rule (and therefore identical drafts,
        # accept patterns and verify-chunk splits) as the engine's
        # _spec_tick, which is what "parity by construction" rests on
        hist = (prompt[0].tolist() + emitted
                + pending[pending_emitted:])
        if len(hist) > observed:
            drafter.observe(sid, hist[observed:])
            observed = len(hist)
        n = W - len(pending)
        drafts = list(drafter.draft(sid, n))[:n] if n > 0 else []
        fed = build_feed(pending, drafts, W)
        greedy_d, final_logits, new_state, old = spec_verify(
            dparams, state, jnp.asarray(fed, jnp.int32)[None, :],
            jnp.ones((1, W), jnp.float32), cfg=cfg, mesh=mesh,
        )
        a, advance, nxt = verify_greedy(
            fed, np.asarray(greedy_d)[0], len(pending)
        )
        stream = (pending[pending_emitted:]
                  + fed[len(pending):len(pending) + a] + [nxt])
        for tok in stream:
            emitted.append(tok)
            if eos_id is not None and tok == eos_id:
                finished = True
                break
            if len(emitted) >= max_new_tokens:
                finished = True
                break
        if finished:
            break
        if advance:
            state = new_state
            pending = [nxt]
            pending_emitted = 1
        else:
            state = {**new_state, "blocks": old["blocks"]}
            if "attn_meta" in old:
                state["attn_meta"] = old["attn_meta"]
            pending = pending + fed[len(pending):len(pending) + a] + [nxt]
            pending_emitted = len(pending)
    drafter.forget(sid)
    if eos_id is not None:
        emitted += [eos_id] * (max_new_tokens - len(emitted))
    out = np.concatenate(
        [prompt[0], np.asarray(emitted[:max_new_tokens], np.int32)]
    )
    return jnp.asarray(out, jnp.int32)[None, :]
