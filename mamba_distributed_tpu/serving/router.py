"""Request router: the front end of the data-parallel serving fabric.

The router owns global admission and places requests onto N engine
replicas (serving/replica.py) — each a full ServingEngine whose slot
pool may itself shard over a ``serving_mesh``'s data axis — so one
serving endpoint spans many engines and, through the sharded pool,
every device in a pod slice.  The host side of the layout TPU serving
systems put in front of ragged paged decode ("Ragged Paged Attention",
PAPERS.md), with the device side a pjit-style sharding-annotation
problem ("Scalable Training of Language Models using JAX pjit and
TPUv4").

Placement is ROLE-FILTERED least-loaded: candidates are first
restricted to the request's tier when the fabric is disaggregated
(``roles=`` + ``cfg.disagg_prompt_threshold`` — long prompts to the
prefill tier, shorts to decode/mixed replicas; all-"mixed" roles are
the exact pre-disagg status quo), then each submit picks the replica
with the lowest ``place_cost`` (queued + resident work per slot, plus
KV page-pool pressure for hybrids, minus prefix-cache AFFINITY — the
fraction of the prompt a replica's prefix cache could skip, so
shared-preamble traffic converges on warm caches;
serving/prefix_cache.py), stamped as a ``serving_route`` span.
``drain(replica_id)`` retires a replica gracefully — no new
placements, in-flight requests finish.  ``fail(replica_id)`` is
failover: the dead replica's unfinished requests REQUEUE onto the
survivors.

Disaggregated tiers (docs/SERVING.md "Disaggregated tiers"): a
prefill-role replica runs a long prompt's chunked prefill, then its
engine's ``migrate_hook`` (installed here) hands the finished O(1)
carry snapshot — plus hybrid KV page contents — to ``_migrate_from``,
which re-places it on the least-loaded decode replica
(``submit_migrated`` -> ``state_cache.restore``): the resumed stream
is bit-exact, no re-prefill, no replayed token, one ``serving_migrate``
span on the SAME trace id so the exported timeline draws the handoff
as one flow chain.  When no decode replica accepts, the hook declines
and the prefill replica decodes locally — mixed-mode fallback, never
a stall.

Failover preserves the token contract — no request lost, no duplicate
tokens — by leaning on the engine parity invariant: a request's stream
is a pure function of (prompt, key), so the restarted stream on the
new replica re-derives bit-identical tokens, and the router suppresses
the indices it already delivered (``_Routed.emitted``).  The consumer
sees one contiguous stream per request, indistinguishable from a
failure-free run.

The streaming interface is the engine's own: ``serve()`` yields
TokenEvents (with ROUTER-global request ids), ``run()`` drains to
GenerationResults, and per-request streams stay token-for-token
identical to solo ``generate()`` (tests/test_router.py pins this
across mamba1/mamba2/hybrid mixes, drain, and failover).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mamba_distributed_tpu.obs import NULL_TRACER, mint_trace_id
from mamba_distributed_tpu.serving.replica import EngineReplica
from mamba_distributed_tpu.serving.scheduler import (
    GenerationRequest,
    GenerationResult,
    TokenEvent,
)
from mamba_distributed_tpu.utils.metrics import ServingMetrics


@dataclasses.dataclass
class _Routed:
    """Router-side record of one request: where it lives now and how
    much of its stream the consumer has already seen (the failover
    replay cursor)."""

    # None only for a stream re-attached AFTER it finished on the
    # worker (attach_resumed replays its tail and never registers it
    # for stepping, so nothing downstream reads the request)
    request: GenerationRequest | None
    global_id: int
    # fabric-wide trace id (obs/context.py), minted ONCE at first
    # placement and kept HERE (request.trace_id is only stamped for
    # the duration of each replica submit) — a failover re-placement
    # continues the same trace on the new replica, while resubmitting
    # the same request object starts a new one
    trace_id: str = ""
    replica_id: int | None = None
    local_id: int | None = None
    emitted: int = 0  # tokens already streamed to the consumer
    done: bool = False
    finish_reason: str | None = None
    tokens: list = dataclasses.field(default_factory=list)


class RequestRouter:
    """Admission + placement over N engine replicas.

    Args:
      params: trained fp32 params, shared read-only by every replica.
      cfg: ModelConfig.  ``cfg.serving_replicas`` is the default replica
        count; ``cfg.serving_data_shards`` > 1 additionally shards each
        replica's slot pool over a ``serving_mesh`` (engine arg).
      num_replicas: overrides ``cfg.serving_replicas``.
      capacity: slots PER replica.
      jsonl_path: one shared telemetry stream for the whole fabric —
        every replica's serving_tick/request records land here stamped
        with their replica id (``scripts/obs_report.py`` renders the
        per-replica table).  The router truncates it once at
        construction; the replicas append.
      tracer: obs.SpanTracer shared by the router (``serving_route``
        placement spans) and — unless ``replica_tracers`` is given —
        every replica's engine.
      replica_tracers: optional per-replica SpanTracer list (len ==
        num_replicas): each replica writes its OWN span stream while
        the router keeps ``tracer`` — the multi-stream layout
        ``scripts/trace_export.py`` merges into one Perfetto timeline
        with per-replica process tracks and per-request flow arrows
        (trace ids minted here at placement link them).
      slo: pass an ``obs.SLOMonitor`` via engine kwargs to watch
        rolling-window latency SLOs — ONE monitor shared by every
        replica, so the window and breach events are fabric-wide.
      retain_results: keep finished GenerationResults in ``.results``
        (what ``run()`` reads); a long-lived streaming server should
        pass False and consume TokenEvents.
      roles: per-replica tier assignment (len == num_replicas; each
        "mixed" | "prefill" | "decode" — serving/replica.REPLICA_ROLES).
        None (default) = all "mixed", the exact pre-disagg fabric.
        With prefill/decode roles AND a positive threshold, placement
        is role-filtered and prefill replicas migrate finished carries
        to the decode tier (the module docstring's handoff).
      disagg_prompt_threshold: prompt-token cutoff above which a
        request routes to the prefill tier; None (default) takes
        ``cfg.disagg_prompt_threshold`` (0 = role-blind routing even
        if roles were assigned).
      admission: an ``serving.autoscale.AdmissionController`` gating
        the front door: ``submit()`` runs its queue-deadline/queue-cap
        check BEFORE placement and raises the named
        ``AdmissionRejected`` on shed (HTTP 429 + Retry-After on the
        service front end).  Only ``submit`` is gated — failover
        re-placement, drain requeue, migration and parked-session
        resume bypass it, so an admitted request is never shed
        mid-flight.  None (default) is the byte-stable status quo.
      session_store: a ``serving.sessions.SessionStore`` backing the
        durable-session surface (docs/SERVING.md "Durable sessions"):
        ``park()``/``resume_parked()`` move whole streams between the
        fabric and the store, and a drain with no accepting survivor
        parks its displaced queue instead of stranding it.  Locally
        constructed replicas additionally share the store as their
        engines' pressure-park sink (the PR-9 valve).  None (default)
        keeps every path byte-identical to the store-less fabric.
      engine_kw: forwarded to every ServingEngine (max_top_k,
        tokens_per_tick, prefill_tokens_per_tick, mesh, ...).
    """

    def __init__(self, params: dict, cfg, num_replicas: int | None = None,
                 capacity: int = 8, *, jsonl_path: str | None = None,
                 tracer=NULL_TRACER, replica_tracers=None,
                 retain_results: bool = True, roles=None,
                 disagg_prompt_threshold: int | None = None,
                 replicas=None, admission=None, session_store=None,
                 **engine_kw):
        if replicas is not None:
            # pre-built placement units — the cross-host service path
            # (serving/service/remote.RemoteReplica duck-types
            # EngineReplica), or any caller owning replica construction.
            # Per-replica knobs live with the replicas themselves, so
            # the local-construction arguments must not also be given.
            clashing = [name for name, val in [
                ("roles", roles), ("replica_tracers", replica_tracers),
                ("jsonl_path", jsonl_path),
            ] if val] + list(engine_kw)
            if clashing:
                raise ValueError(
                    f"replicas= supplies pre-built replicas; {clashing} "
                    f"configure local replica construction and cannot "
                    f"be combined with it"
                )
            if num_replicas is not None and num_replicas != len(replicas):
                raise ValueError(
                    f"num_replicas={num_replicas} != len(replicas)="
                    f"{len(replicas)}"
                )
            num_replicas = len(replicas)
        elif num_replicas is None:
            num_replicas = cfg.serving_replicas
        if num_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {num_replicas}")
        if replica_tracers is not None and len(replica_tracers) != num_replicas:
            raise ValueError(
                f"replica_tracers has {len(replica_tracers)} tracer(s) "
                f"for {num_replicas} replica(s) — need one per replica"
            )
        if roles is not None and len(roles) != num_replicas:
            raise ValueError(
                f"roles has {len(roles)} entr(ies) for {num_replicas} "
                f"replica(s) — need one per replica"
            )
        self.cfg = cfg
        self.tracer = tracer
        self.retain_results = retain_results
        self.admission = admission
        self.session_store = session_store
        self.disagg_prompt_threshold = (
            cfg.disagg_prompt_threshold if disagg_prompt_threshold is None
            else disagg_prompt_threshold
        )
        if replicas is not None:
            self.replicas: list[EngineReplica] = list(replicas)
            ids = [r.replica_id for r in self.replicas]
            if ids != list(range(num_replicas)):
                raise ValueError(
                    f"injected replica ids must be 0..{num_replicas - 1} "
                    f"in order (the router indexes replicas by id), got "
                    f"{ids}"
                )
        else:
            if jsonl_path:
                open(jsonl_path, "w").close()  # one fresh stream
            self.replicas = []
            for i in range(num_replicas):
                metrics = ServingMetrics(capacity, jsonl_path=jsonl_path,
                                         replica=i)
                if jsonl_path:
                    metrics.preserve_history()  # router already truncated
                self.replicas.append(EngineReplica(
                    i, params, cfg, metrics=metrics,
                    tracer=(replica_tracers[i] if replica_tracers
                            else tracer),
                    role=(roles[i] if roles else "mixed"),
                    capacity=capacity, retain_results=False,
                    **({} if session_store is None
                       else {"session_store": session_store}),
                    **engine_kw,
                ))
        if self.disagg_prompt_threshold > 0:
            # threshold 0 keeps roles inert — no role filter AND no
            # migration, the exact pre-disagg fabric
            for rep in self.replicas:
                if rep.role == "prefill":
                    # the disaggregated handoff: at each prefill-
                    # complete the engine offers the request here
                    # before decoding
                    rep.engine.migrate_hook = (
                        lambda tracked, package, _src=rep:
                        self._migrate_from(_src, tracked, package)
                    )
        self.migrations = 0  # successful cross-replica handoffs
        # durable sessions: global id -> session id for streams a
        # no-survivor drain parked instead of stranding (the caller's
        # map from its in-flight ids to resumable sessions)
        self.drain_parked: dict[int, str] = {}
        self._routed: dict[int, _Routed] = {}
        self._by_local: dict[tuple[int, int], _Routed] = {}
        self._next_id = 0
        self.results: dict[int, GenerationResult] = {}

    # ------------------------------------------------------------ admission

    def submit(self, request: GenerationRequest) -> int:
        """Admit a request: place it on the least-loaded accepting
        replica.  Returns the ROUTER-global request id (TokenEvents and
        ``results`` use it).  Raises if the request is invalid (any
        replica would reject it), no replica is accepting, or — with an
        admission controller installed — the fabric sheds it
        (``AdmissionRejected``, BEFORE any queue is touched)."""
        if self.admission is not None:
            self.admission.check(request, self.replicas)
        # the trace context is minted HERE, at the fabric's front door,
        # and lives on the _Routed entry — NOT written back onto the
        # caller's object — so a failover re-placement (same entry)
        # continues the same trace while resubmitting the same
        # GenerationRequest object later starts a fresh one (one
        # request journey = one trace)
        routed = _Routed(request=request, global_id=self._next_id,
                         trace_id=request.trace_id or mint_trace_id())
        self._place(routed)  # raises before the id is ever registered
        self._next_id += 1
        self._routed[routed.global_id] = routed
        return routed.global_id

    def _role_filter(self, cands: list[EngineReplica],
                     request: GenerationRequest) -> list[EngineReplica]:
        """Restrict placement candidates to the request's tier when the
        fabric is disaggregated: long prompts (above
        ``disagg_prompt_threshold`` tokens) go to prefill-role replicas
        (mixed next), shorts to decode/mixed replicas — a decode
        replica never admits a long prompt's prefill through the
        normal path.  Falls back to the unfiltered candidates when the
        preferred tier has nothing accepting (graceful degradation:
        a missing tier must never strand a request), and is the
        identity with threshold 0 or an all-mixed fabric."""
        thr = self.disagg_prompt_threshold
        if thr <= 0 or all(r.role == "mixed" for r in self.replicas):
            return cands
        if len(request.prompt_ids) > thr:
            tier = ([r for r in cands if r.role == "prefill"]
                    or [r for r in cands if r.role == "mixed"])
        else:
            tier = [r for r in cands if r.role in ("decode", "mixed")]
        return tier or cands

    def _place(self, routed: _Routed) -> None:
        """Role-filtered least-loaded placement (one ``serving_route``
        span): lowest ``place_cost`` among the accepting replicas of
        the request's tier, ties to the lowest id.

        A replica that rejects the request's LoRA ADAPTER (its
        registry lacks the name — possible when only some workers
        preloaded it and the front end has no factors to push) is
        skipped and the next-cheapest candidate tried: one replica's
        missing registration must neither 404 a servable request nor
        abort a ``fail()`` replay mid-loop (the half-failed-over
        state that method's contract forbids).  Only when EVERY
        candidate rejects does the adapter error surface.

        Trainer-role replicas (serving/tuning — online LoRA lanes) are
        never candidates: they hold no slot pool, and unlike the
        disagg tiers there is no graceful-degradation fallback INTO
        them — a generation request lands on serving roles or fails."""
        cands = [r for r in self.replicas
                 if r.accepting and r.role != "trainer"]
        if not cands:
            raise RuntimeError(
                "no accepting replicas (all draining or dead); request "
                "not placed"
            )
        cands = self._role_filter(cands, routed.request)
        ranked = sorted(((r.place_cost(routed.request), r) for r in cands),
                        key=lambda cr: (cr[0], cr[1].replica_id))
        adapter_err = None
        for cost, rep in ranked:
            attrs = dict(request_id=routed.global_id,
                         replica=rep.replica_id,
                         trace=routed.trace_id, cost=round(cost, 4),
                         queue_depth=rep.engine.scheduler.depth)
            if rep.role != "mixed" and self.disagg_prompt_threshold > 0:
                # disagg fabrics only: with threshold 0 roles are inert
                # and spans stay byte-stable vs a role-less router
                attrs["role"] = rep.role
            if rep.engine.hybrid:
                attrs["free_pages"] = rep.engine.page_pool.free_pages
            # propagate the entry's trace id through the request object
            # only for the duration of the submit (the scheduler copies
            # it onto its tracker), then restore the caller's value
            prev_trace = routed.request.trace_id
            routed.request.trace_id = routed.trace_id
            try:
                with self.tracer.span("serving_route", **attrs):
                    local_id = rep.submit(routed.request)
            except ValueError as e:
                if "UnknownAdapterError" not in (
                        f"{type(e).__name__}: {e}"):
                    raise  # a per-request validation error: uniform
                    # across replicas, retrying elsewhere can't help
                adapter_err = e
                continue
            finally:
                routed.request.trace_id = prev_trace
            routed.replica_id, routed.local_id = rep.replica_id, local_id
            self._by_local[(rep.replica_id, local_id)] = routed
            return
        raise adapter_err

    # --------------------------------------------------- SSE resume attach

    def stream_location(self, global_id: int) -> tuple[int, int] | None:
        """Where one in-flight stream lives right now: (replica_id,
        engine-local request id), or None once finished/unknown.  The
        front end stamps this — as an opaque ``wire.encode_resume_token``
        cursor — on every SSE event, so a client holding the last
        cursor can re-attach through a RESTARTED front end
        (``attach_resumed``).  Failover re-placement updates the
        location, and the cursor refreshes with the next event."""
        routed = self._routed.get(global_id)
        if routed is None or routed.done:
            return None
        return routed.replica_id, routed.local_id

    def attach_resumed(self, replica_id: int, local_id: int,
                       from_index: int = 0, boot_id: str | None = None):
        """Re-attach to a stream a PREVIOUS front end placed (the SSE
        resume path, docs/SERVING.md "Deploying as a service"): the
        worker kept the request and its emitted tokens across the
        controller gap — nothing steps while no controller is connected
        — so this router adopts the stream under a fresh global id,
        replays ``[from_index:]`` from the replica's ``replay`` view,
        and (for still-running streams) registers the routing entry so
        subsequent ``step()`` events flow like any other request's.

        Returns ``(global_id, replayed TokenEvents)``.  Raises KeyError
        when the replica doesn't know the stream (evicted past the
        worker's finished ring, or a bogus cursor) — or when the cursor
        names a replica this fabric doesn't have (a redeploy shrank the
        fleet; negative ids must not wrap around to the tail replica) —
        and ValueError when the stream is already attached here (one
        consumer per stream)."""
        if not 0 <= replica_id < len(self.replicas):
            raise KeyError(
                f"no replica {replica_id} in this fabric "
                f"({len(self.replicas)} replicas) — the cursor predates "
                f"a redeploy; resubmit the request (same seed => same "
                f"tokens)"
            )
        rep = self.replicas[replica_id]
        rep_boot = getattr(rep, "boot_id", None)
        if boot_id is not None and rep_boot is not None \
                and boot_id != rep_boot:
            # the worker PROCESS restarted since the cursor was minted:
            # its engine-local request ids restarted at 0, so the same
            # local id may now name a DIFFERENT request — replaying it
            # would leak another stream's tokens.  410, never a guess.
            raise KeyError(
                f"replica {replica_id} restarted since this cursor was "
                f"minted (boot {boot_id} != {rep_boot}); resubmit the "
                f"request (same seed => same tokens)"
            )
        if (replica_id, local_id) in self._by_local:
            raise ValueError(
                f"stream {local_id} on replica {replica_id} is already "
                f"attached to this router"
            )
        # replay the FULL history and slice locally: the router needs
        # the true token count to validate the cursor (an inflated
        # index would park `emitted` ahead of reality and the step()
        # dedup guard would then silently drop every real token) and
        # to seed `routed.tokens` so a retain_results router's final
        # GenerationResult holds the whole stream, not just the
        # post-attach tail
        info = rep.replay(local_id, 0)
        if info is None:
            raise KeyError(
                f"replica {replica_id} has no replayable stream "
                f"{local_id} — finished beyond its replay ring, failed "
                f"over, or never placed; resubmit the request (same "
                f"seed => same tokens)"
            )
        toks_all = info["tokens"]
        if not info["done"] and from_index > len(toks_all):
            raise KeyError(
                f"resume index {from_index} is ahead of stream "
                f"{local_id} on replica {replica_id} "
                f"({len(toks_all)} tokens generated) — no honest cursor "
                f"points there; resubmit the request (same seed => "
                f"same tokens)"
            )
        request = info.get("request")
        routed = _Routed(
            request=request, global_id=self._next_id,
            trace_id=(getattr(request, "trace_id", None)
                      or mint_trace_id()),
        )
        self._next_id += 1
        toks = toks_all[from_index:]
        if self.retain_results:
            routed.tokens = [int(t) for t in toks_all]
        events = []
        for k, tok in enumerate(toks):
            last = info["done"] and k == len(toks) - 1
            events.append(TokenEvent(
                routed.global_id, int(tok), from_index + k, last,
                info["finish_reason"] if last else None,
            ))
        routed.emitted = from_index + len(toks)
        routed.replica_id, routed.local_id = replica_id, local_id
        if info["done"]:
            routed.done = True
            routed.finish_reason = info["finish_reason"]
            return routed.global_id, events  # nothing more will come
        self._routed[routed.global_id] = routed
        self._by_local[(replica_id, local_id)] = routed
        return routed.global_id, events

    # ------------------------------------------------ disaggregated handoff

    def _migrate_from(self, source: EngineReplica, tracked, package) -> bool:
        """The migration hook installed on prefill-tier replicas'
        engines (``ServingEngine.migrate_hook``): called at each
        prefill-complete with the engine's tracked request and a
        zero-arg packager.  Picks the least-loaded accepting
        decode-role replica (mixed replicas next; never the source),
        serializes the O(1) carry (+ hybrid KV pages) snapshot, and
        re-places the request there via ``submit_migrated`` — one
        ``serving_migrate`` span carrying the SAME trace id as the
        rest of the request's journey, so ``scripts/trace_export.py``
        draws the cross-replica handoff as a flow arrow in the chain
        prefill replica -> migration -> decode replica.  Returns False
        (the prefill replica decodes locally — mixed-mode fallback,
        never a stall) when no tier-compatible replica accepts or
        every candidate rejects the artifact's page reservation."""
        routed = self._by_local.get((source.replica_id, tracked.request_id))
        if routed is None:
            return False  # not a router-managed request
        cands = [r for r in self.replicas
                 if r.accepting and r is not source and r.role == "decode"]
        if not cands:
            cands = [r for r in self.replicas
                     if r.accepting and r is not source
                     and r.role == "mixed"]
        if not cands:
            return False
        # place_cost WITHOUT the request: a migration artifact runs no
        # prefill, so the prefix-cache affinity discount (an
        # O(prompt_len) probe per candidate) would both waste host time
        # and skew the restore toward cache-warm-but-busier replicas —
        # plain load + page pressure is the cost a restore actually has
        cands.sort(key=lambda r: (r.place_cost(), r.replica_id))
        snap = package()
        for rep in cands:
            attrs = dict(request_id=routed.global_id,
                         trace=routed.trace_id,
                         source=source.replica_id,
                         target=rep.replica_id,
                         package_ms=round(snap["package_ms"], 3))
            if "kv_len" in snap:
                attrs["kv_pages"] = snap["n_live"]
            # propagate the entry's trace id for the duration of the
            # submit, exactly like _place — one request journey, one
            # trace, however many replicas it visits
            prev_trace = routed.request.trace_id
            routed.request.trace_id = routed.trace_id
            try:
                with self.tracer.span("serving_migrate", **attrs):
                    local_id = rep.engine.submit_migrated(
                        routed.request, snap,
                        source_replica=source.replica_id,
                    )
            except ValueError:
                # this replica can never hold the reservation (e.g. a
                # sharded page pool narrower than the request) — try
                # the next candidate
                continue
            finally:
                routed.request.trace_id = prev_trace
            self._by_local.pop((source.replica_id, routed.local_id), None)
            routed.replica_id, routed.local_id = rep.replica_id, local_id
            self._by_local[(rep.replica_id, local_id)] = routed
            self.migrations += 1
            return True
        return False

    # ------------------------------------------------------- durable sessions

    def park(self, global_id: int, *, ttl_s: float | None = None) -> str:
        """Park one in-flight stream into the session store
        (docs/SERVING.md "Durable sessions"): the stream's replica
        serializes it into the replica-unbound PARK artifact (the
        migration artifact + the tokens already emitted), the router
        forgets it, and the returned session id is the client's handle
        to ``resume_parked`` — on ANY replica, later, bit-exactly.

        Raises KeyError for an unknown/finished id, ValueError
        (retriable) when the stream is not yet DECODE-resident on its
        replica (still queued/prefilling — re-ask after a step), and
        RuntimeError when the router has no session store."""
        if self.session_store is None:
            raise RuntimeError(
                "this fabric has no session store (pass session_store= "
                "or --state-dir); park/resume is off"
            )
        routed = self._routed.get(global_id)
        if routed is None or routed.done:
            raise KeyError(
                f"no in-flight stream {global_id} to park (finished or "
                f"never admitted)"
            )
        from mamba_distributed_tpu.serving.service import wire

        rep = self.replicas[routed.replica_id]
        with self.tracer.span("serving_park", request_id=global_id,
                              trace=routed.trace_id,
                              replica=routed.replica_id):
            request, snap = rep.engine.park(routed.local_id)
        sid = self.session_store.park({
            "request": wire.encode_request_tree(request),
            "snapshot": snap,
            "emitted": routed.emitted,
            "trace_id": routed.trace_id,
        }, ttl_s=ttl_s)
        self._by_local.pop((routed.replica_id, routed.local_id), None)
        del self._routed[global_id]
        return sid

    def resume_parked(self, session_id: str) -> int:
        """Re-admit a parked session under a FRESH global id: pops the
        artifact from the store, places it on the lowest-``place_cost``
        accepting replica (the normal cost — adapter affinity included;
        any replica works, the artifact is replica-unbound) and
        restores via ``submit_migrated``/the wire v4 ``resume_parked``
        RPC.  The stream CONTINUES: its emitted-token prefix rides the
        artifact, so subsequent TokenEvents carry the post-park
        indices.  A queue-only session (a no-survivor drain parked it
        before any prefill) re-places through normal admission.

        KeyError = unknown/expired session, ``SessionStoreError`` =
        corrupt frame (the store already skipped it); when every
        accepting replica rejects the artifact the session is re-parked
        under the SAME id before the error surfaces — a failed resume
        never loses the session."""
        if self.session_store is None:
            raise RuntimeError(
                "this fabric has no session store (pass session_store= "
                "or --state-dir); park/resume is off"
            )
        payload = self.session_store.resume(session_id)
        from mamba_distributed_tpu.serving.service import wire

        request = wire.decode_request_tree(payload["request"])
        snap = payload.get("snapshot")
        routed = _Routed(request=request, global_id=self._next_id,
                         trace_id=(payload.get("trace_id")
                                   or mint_trace_id()))
        routed.emitted = int(payload.get("emitted") or 0)
        if self.retain_results and snap is not None:
            routed.tokens = [int(t) for t in snap.get("new_tokens") or []]
        try:
            if snap is None:
                # drain-parked before any prefill: a plain re-placement
                self._place(routed)
            else:
                self._place_parked(routed, snap, session_id)
        except Exception:
            # the artifact is already OUT of the store — put it back
            # under the same id so the caller can retry; a failed
            # resume must never lose the session
            self.session_store.park(payload, session_id=session_id)
            raise
        self._next_id += 1
        self._routed[routed.global_id] = routed
        return routed.global_id

    def _place_parked(self, routed: _Routed, snap: dict,
                      session_id: str) -> None:
        """Least-``place_cost`` placement of a PARK artifact — the
        normal cost WITH the request (a parked adapter-bound stream
        converges back on workers holding its factors), restore via
        the replica's parked-resume entry point (``resume_parked`` over
        the wire, ``submit_migrated`` in process — same path).  Trainer
        lanes are excluded exactly as in ``_place`` — a park artifact
        is generation state."""
        cands = [r for r in self.replicas
                 if r.accepting and r.role != "trainer"]
        if not cands:
            raise RuntimeError(
                f"no accepting replicas (all draining or dead); session "
                f"{session_id} stays parked"
            )
        ranked = sorted(((r.place_cost(routed.request), r) for r in cands),
                        key=lambda cr: (cr[0], cr[1].replica_id))
        last_err: Exception | None = None
        for cost, rep in ranked:
            attrs = dict(request_id=routed.global_id,
                         trace=routed.trace_id, session=session_id,
                         replica=rep.replica_id, cost=round(cost, 4))
            prev_trace = routed.request.trace_id
            routed.request.trace_id = routed.trace_id
            try:
                resume = getattr(rep.engine, "resume_parked", None)
                if resume is None:
                    resume = rep.engine.submit_migrated
                with self.tracer.span("serving_resume_parked", **attrs):
                    local_id = resume(routed.request, snap)
            except ValueError as e:
                # this replica can never hold the artifact (sharded
                # page pool too narrow, adapter not registered) — try
                # the next candidate
                last_err = e
                continue
            finally:
                routed.request.trace_id = prev_trace
            routed.replica_id, routed.local_id = rep.replica_id, local_id
            self._by_local[(rep.replica_id, local_id)] = routed
            return
        raise last_err if last_err is not None else RuntimeError(
            f"no replica admitted parked session {session_id}"
        )

    # ------------------------------------------------------------ lifecycle

    def add_replica(self, replica) -> None:
        """Live-attach one pre-built replica to a RUNNING fabric — the
        autoscale scale-up path (serving/autoscale/controller.py), and
        the first way the replica set has ever grown after construction
        (``drain``/``fail`` only shrink it).  Nothing pauses: in-flight
        streams keep stepping exactly as before (their routing entries
        are untouched, so live-attach parity is structural — pinned by
        tests/test_autoscale.py), and the next ``submit`` simply sees
        one more placement candidate.

        The replica's id must be ``len(self.replicas)`` — ids stay
        0..n-1 in order because the router indexes replicas by id
        (``attach_resumed``, ``drain``, ``fail``); retired replicas
        keep their slot in the list as DEAD entries, they are never
        popped.  A prefill-role replica on a disaggregated fabric gets
        the same ``migrate_hook`` construction installs, so a scaled-up
        prefill tier hands carries off exactly like a seed one."""
        if replica.replica_id != len(self.replicas):
            raise ValueError(
                f"live-attached replica id must be {len(self.replicas)} "
                f"(ids are the router's list index, 0..n-1 in order), "
                f"got {replica.replica_id}"
            )
        self.replicas.append(replica)
        if self.disagg_prompt_threshold > 0 and replica.role == "prefill":
            replica.engine.migrate_hook = (
                lambda tracked, package, _src=replica:
                self._migrate_from(_src, tracked, package)
            )

    def drain(self, replica_id: int, *,
              requeue_queued: bool = False) -> list[int]:
        """Gracefully retire a replica: no new placements; everything it
        already holds finishes through normal stepping.

        ``requeue_queued`` additionally withdraws the replica's
        queued-but-UNSTARTED requests (no slot, no resume snapshot) and
        re-places them on the surviving replicas — the rolling-restart
        shutdown path: without it, a drain initiated from outside
        ``serve()`` strands the retiring replica's queue until someone
        keeps stepping it.  Started work (resident slots, preemption
        snapshots, migrated-in artifacts) always finishes in place.
        Returns the re-placed global ids.  When no OTHER replica is
        accepting: with a session store attached the displaced queue is
        PARKED instead of stranded — each withdrawn request lands in
        the store (``drain_parked`` maps its global id to the session
        id, resumable on whatever replica comes back); without one,
        nothing is withdrawn (the drain still finishes its queue
        locally — graceful degradation, never a stranded request)."""
        rep = self.replicas[replica_id]
        survivors = any(r.accepting for r in self.replicas if r is not rep)
        requeue = requeue_queued and (
            survivors or self.session_store is not None
        )
        withdrawn = rep.drain(requeue=requeue)
        if requeue and not survivors:
            # no accepting survivor: park the displaced queue instead
            # of erroring out of _place (the satellite fix) — these
            # requests never started, so the session is queue-only
            # (no snapshot) and resume_parked re-places it fresh
            from mamba_distributed_tpu.serving.service import wire

            for local_id in withdrawn:
                routed = self._by_local.pop((replica_id, local_id), None)
                if routed is None:
                    continue  # not router-managed (direct engine submit)
                sid = self.session_store.park({
                    "request": wire.encode_request_tree(routed.request),
                    "snapshot": None,
                    "emitted": routed.emitted,
                    "trace_id": routed.trace_id,
                })
                self.drain_parked[routed.global_id] = sid
                del self._routed[routed.global_id]
            return []
        moved = []
        for local_id in withdrawn:
            routed = self._by_local.pop((replica_id, local_id), None)
            if routed is None:
                continue  # not router-managed (direct engine submit)
            try:
                self._place(routed)
            except Exception:  # noqa: BLE001 — a withdrawn request is
                # already OUT of the retiring queue; if the survivors
                # vanished mid-drain (wire death, concurrent failure)
                # it must go BACK rather than be lost.  force bypasses
                # the draining replica's accepting check; its queue
                # then finishes locally, exactly as a no-survivor
                # drain would have.
                prev_trace = routed.request.trace_id
                routed.request.trace_id = routed.trace_id
                try:
                    new_local = rep.submit(routed.request, force=True)
                finally:
                    routed.request.trace_id = prev_trace
                routed.replica_id, routed.local_id = replica_id, new_local
                self._by_local[(replica_id, new_local)] = routed
                continue
            moved.append(routed.global_id)
        return moved

    def fail(self, replica_id: int) -> list[int]:
        """Failover: mark the replica dead and requeue its unfinished
        requests onto the survivors.  Each restarted stream re-derives
        the same tokens from the same key (the parity contract), and
        ``step()`` suppresses the indices already delivered — the
        consumer loses nothing and sees nothing twice.  Returns the
        requeued global ids.  Raises BEFORE any request is moved when
        nothing is accepting — no half-failed-over state."""
        self.replicas[replica_id].mark_dead()
        victims = [r for r in self._routed.values()
                   if not r.done and r.replica_id == replica_id]
        if victims and not any(r.accepting for r in self.replicas):
            raise RuntimeError(
                f"replica {replica_id} died holding "
                f"{len(victims)} unfinished request(s) "
                f"{sorted(v.global_id for v in victims)} but no replica "
                f"is accepting (all draining or dead) — nothing to fail "
                f"over to"
            )
        moved = []
        for routed in victims:
            self._by_local.pop((routed.replica_id, routed.local_id), None)
            self._place(routed)
            moved.append(routed.global_id)
        return moved

    # ------------------------------------------------------------- serving

    @property
    def pending(self) -> int:
        """Requests admitted but not yet finished, fabric-wide."""
        return sum(1 for r in self._routed.values() if not r.done)

    def step(self) -> list[TokenEvent]:
        """One fabric iteration: step every live replica with work,
        translate its events to global ids, advance replay cursors.
        Finished requests are pruned from the routing tables (and their
        token buffers only ever exist under ``retain_results``), so a
        long-lived streaming server's memory stays bounded by in-flight
        work, not by everything ever served."""
        events: list[TokenEvent] = []
        for rep in self.replicas:
            if not rep.alive or rep.pending == 0:
                continue
            for ev in rep.step():
                routed = self._by_local.get((rep.replica_id, ev.request_id))
                if routed is None or routed.done:
                    continue
                if ev.index < routed.emitted:
                    # failover replay of a token the consumer already
                    # has — identical by the parity contract; drop it
                    continue
                if self.retain_results:
                    routed.tokens.append(ev.token)
                routed.emitted += 1
                if ev.done:
                    routed.done = True
                    routed.finish_reason = ev.finish_reason
                    if self.retain_results:
                        self.results[routed.global_id] = GenerationResult(
                            request_id=routed.global_id,
                            prompt_ids=routed.request.prompt_ids,
                            new_tokens=np.asarray(routed.tokens, np.int32),
                            finish_reason=ev.finish_reason,
                        )
                    self._by_local.pop((rep.replica_id, ev.request_id),
                                       None)
                    del self._routed[routed.global_id]
                events.append(TokenEvent(
                    routed.global_id, ev.token, routed.emitted - 1,
                    routed.done, routed.finish_reason,
                ))
        if not events and self.pending and not any(
            rep.alive and rep.pending for rep in self.replicas
        ):
            # every pending request is stranded on a dead replica (a
            # swallowed fail() error) — serve() would busy-loop forever
            raise RuntimeError(
                f"{self.pending} pending request(s) are stranded on dead "
                f"replicas and can never finish; fail() the dead "
                f"replica(s) while survivors are still accepting"
            )
        return events

    def serve(self, requests=()):  # -> Iterator[TokenEvent]
        """Stream TokenEvents (global ids) until the fabric drains; more
        requests may be submitted between yields."""
        for r in requests:
            self.submit(r)
        while self.pending:
            yield from self.step()

    def run(self, requests=()) -> list[GenerationResult]:
        """Submit ``requests``, drain the fabric, return results in
        submission order."""
        if not self.retain_results:
            raise ValueError("run() needs retain_results=True; stream "
                             "via serve() instead")
        ids = [self.submit(r) for r in requests]
        for _ in self.serve():
            pass
        return [self.results[i] for i in ids]

    def summary(self) -> dict:
        """Per-replica metrics summaries keyed by replica id."""
        return {r.replica_id: r.engine.metrics.summary()
                for r in self.replicas}
