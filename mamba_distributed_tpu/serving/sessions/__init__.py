"""Durable session fabric: tiered park/resume store (docs/SERVING.md
"Durable sessions").  The migration artifact is the canonical PARK
format; parked sessions cost zero device memory and resume bit-exactly
on any replica."""

from .store import (
    SESSION_FORMAT_VERSION,
    DiskSessionStore,
    SessionStore,
    SessionStoreError,
    decode_session_frame,
    encode_session_frame,
)

__all__ = [
    "SESSION_FORMAT_VERSION",
    "DiskSessionStore",
    "SessionStore",
    "SessionStoreError",
    "decode_session_frame",
    "encode_session_frame",
]
