"""Durable session store: tiered park/resume for idle conversations.

At real user scale most sessions are idle between turns, yet a live
stream pins a slot, KV pages and (hybrid) page-pool budget on device —
the fabric's user population is hard-capped by its slot count.  This
module is the capacity multiplier: a PARKED session is the existing
migration artifact (O(1) conv/SSM carry + last logits + serialized KV
page contents + the emitted tokens) moved off-device into a tiered
store, so it costs ZERO device memory and resumes bit-exactly — on the
same replica, a different one, or after a worker restart (physical
page ids never appear in the artifact, which is what makes it
replica-unbound by construction).

Tiers:

  device slot   -> live stream (status quo; not this module's concern)
  host RAM      -> ``SessionStore``'s LRU dict of encoded frames
  disk          -> ``DiskSessionStore``: one wire-encoded frame per
                   session under ``--state-dir``, CRC + format-version
                   checked on load, atomic tmp+rename writes

The PARK FRAME is ``wire.encode_tree`` of the payload (the same codec
every cross-host message rides on — treedef-, dtype- and bit-exact,
bf16/int8 included) behind a small binary header::

    magic 'MDSF' | u16 format version | u32 crc32(body) | u32 len | body

A frame that fails the magic/version/CRC/length check surfaces the
NAMED ``SessionStoreError`` — resume callers map it to a client error
and the sweeper skips (and drops) the frame instead of crashing.

TTL: ``ttl_s > 0`` stamps an absolute wall-clock deadline into each
frame (wall clock, not ``perf_counter`` — deadlines must survive a
process restart); ``sweep()`` expires past-deadline sessions in both
tiers.  Pressure-parked engine streams park with ``ttl_s=0`` (their
tracker, still queued, owns their lifetime).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import uuid
import zlib
from collections import OrderedDict

from ..service import wire

__all__ = [
    "SessionStoreError",
    "DiskSessionStore",
    "SessionStore",
    "SESSION_FORMAT_VERSION",
    "encode_session_frame",
    "decode_session_frame",
]


class SessionStoreError(RuntimeError):
    """A session frame failed its integrity/version check (corrupted,
    truncated, or written by an unknown store generation).  NAMED so
    callers can skip the one bad session instead of crashing the
    sweep, and so the HTTP front end maps it to a client error."""


SESSION_MAGIC = b"MDSF"
SESSION_FORMAT_VERSION = 1
_HEADER = struct.Struct(">4sHII")  # magic, version, crc32, body length


def encode_session_frame(payload: dict) -> bytes:
    """One self-verifying session frame: header + the wire-codec body
    (``wire.encode_tree`` — the bit-exact tree codec the migration
    artifact already rides, so a disk round-trip can never perturb a
    resumed stream)."""
    body = json.dumps(wire.encode_tree(payload)).encode("utf-8")
    return _HEADER.pack(
        SESSION_MAGIC, SESSION_FORMAT_VERSION,
        zlib.crc32(body) & 0xFFFFFFFF, len(body),
    ) + body


def decode_session_frame(frame: bytes) -> dict:
    """Verify + decode one frame; raises the NAMED ``SessionStoreError``
    on any corruption (bad magic, unknown version, short body, CRC
    mismatch) — never a misparse."""
    if len(frame) < _HEADER.size:
        raise SessionStoreError(
            f"session frame truncated: {len(frame)} bytes < "
            f"{_HEADER.size}-byte header"
        )
    magic, version, crc, length = _HEADER.unpack(frame[:_HEADER.size])
    if magic != SESSION_MAGIC:
        raise SessionStoreError(
            f"bad session frame magic {magic!r} (want {SESSION_MAGIC!r})"
        )
    if version != SESSION_FORMAT_VERSION:
        raise SessionStoreError(
            f"unknown session frame version {version} (this store "
            f"speaks {SESSION_FORMAT_VERSION})"
        )
    body = frame[_HEADER.size:]
    if len(body) != length:
        raise SessionStoreError(
            f"session frame truncated: body {len(body)} bytes, header "
            f"promised {length}"
        )
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise SessionStoreError("session frame CRC mismatch (corrupted)")
    try:
        return wire.decode_tree(json.loads(body.decode("utf-8")))
    except (ValueError, wire.WireError) as e:
        raise SessionStoreError(f"session frame body undecodable: {e}")


class DiskSessionStore:
    """The disk tier: one frame file per session id under
    ``state_dir`` (created if missing).  Writes are atomic
    (tmp + rename), so a crash mid-park never leaves a half frame
    under a live session id.  Construction rescans the directory —
    sessions parked by a previous process incarnation are immediately
    resumable (the worker-restart durability half of the tentpole)."""

    SUFFIX = ".session"

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        # sid -> frame bytes on disk (sizes from the rescan; content
        # is only read back — and only then CRC-checked — on get())
        self._sizes: dict[str, int] = {}
        for name in os.listdir(state_dir):
            if name.endswith(self.SUFFIX):
                sid = name[: -len(self.SUFFIX)]
                self._sizes[sid] = os.path.getsize(
                    os.path.join(state_dir, name))

    def _path(self, sid: str) -> str:
        return os.path.join(self.state_dir, sid + self.SUFFIX)

    def put(self, sid: str, frame: bytes) -> None:
        tmp = self._path(sid) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
        os.replace(tmp, self._path(sid))
        self._sizes[sid] = len(frame)

    def get(self, sid: str) -> bytes:
        """Raw frame bytes; ``KeyError`` for an unknown session."""
        if sid not in self._sizes:
            raise KeyError(sid)
        try:
            with open(self._path(sid), "rb") as f:
                return f.read()
        except OSError:
            self._sizes.pop(sid, None)
            raise KeyError(sid)

    def delete(self, sid: str) -> None:
        self._sizes.pop(sid, None)
        try:
            os.unlink(self._path(sid))
        except OSError:
            pass

    def ids(self) -> list[str]:
        return list(self._sizes)

    def __contains__(self, sid: str) -> bool:
        return sid in self._sizes

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def nbytes(self) -> int:
        return sum(self._sizes.values())


class SessionStore:
    """The tiered park/resume store: a host-RAM LRU of encoded frames
    in front of an optional ``DiskSessionStore``.

    * ``host_bytes > 0`` caps the RAM tier: parks land hot, and the
      least-recently-touched frames DEMOTE to disk when the cap is
      exceeded (no disk -> the oldest frames simply stay resident;
      a byte cap without a disk tier would have to drop sessions).
    * ``host_bytes == 0`` with a disk tier is write-through: every
      park goes straight to disk (the durable default for workers).
    * neither -> a plain in-memory dict (tests, single-process use).

    ``park`` -> session id; ``resume`` removes and returns the payload
    (a parked session is single-resume by design: the resuming engine
    owns the stream again).  ``sweep`` expires TTL-past sessions in
    both tiers, SKIPPING corrupted disk frames (dropped + counted, per
    the ``SessionStoreError`` contract).  All methods are
    thread-safe — the HTTP front end parks from handler threads while
    the controller thread resumes.
    """

    def __init__(self, *, ttl_s: float = 0.0, host_bytes: int = 0,
                 disk: DiskSessionStore | None = None, clock=time.time):
        if ttl_s < 0:
            raise ValueError(f"ttl_s must be >= 0, got {ttl_s}")
        if host_bytes < 0:
            raise ValueError(f"host_bytes must be >= 0, got {host_bytes}")
        self.ttl_s = float(ttl_s)
        self.host_bytes = int(host_bytes)
        self.disk = disk
        self._clock = clock
        self._lock = threading.Lock()
        # sid -> frame bytes, LRU order (last = most recently touched)
        self._host: OrderedDict[str, bytes] = OrderedDict()
        # sid -> absolute wall-clock deadline (0 = never), both tiers;
        # disk frames parked by a PREVIOUS incarnation are absent here
        # and carry their deadline inside the frame instead
        self._deadlines: dict[str, float] = {}
        self._host_nbytes = 0
        self._next_sweep = 0.0
        self.parks = 0
        self.resumes = 0
        self.expires = 0
        self.corrupt_skipped = 0

    # ------------------------------------------------------------ tiers

    def _demote_lru(self) -> None:
        """Move least-recently-touched host frames to disk until the
        RAM tier fits its byte budget (lock held)."""
        if self.disk is None:
            return
        while self._host and (
            self._host_nbytes > self.host_bytes or self.host_bytes == 0
        ):
            sid, frame = self._host.popitem(last=False)
            self._host_nbytes -= len(frame)
            self.disk.put(sid, frame)

    def park(self, payload: dict, *, session_id: str | None = None,
             ttl_s: float | None = None) -> str:
        """Store one session payload; returns its session id.  The
        frame carries its own absolute expiry deadline (``ttl_s``
        overrides the store default; 0 = never expire — what the
        engine's pressure valve uses, since the queued tracker owns
        that session's lifetime)."""
        sid = session_id or uuid.uuid4().hex
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        deadline = self._clock() + ttl if ttl > 0 else 0.0
        frame = encode_session_frame(
            {"expires_at": deadline or None, "data": payload})
        with self._lock:
            self._drop_locked(sid)  # re-park under the same id replaces
            self._host[sid] = frame
            self._host_nbytes += len(frame)
            self._deadlines[sid] = deadline
            self._demote_lru()
            self.parks += 1
        return sid

    def resume(self, sid: str) -> dict:
        """Remove + return one parked payload.  ``KeyError`` for an
        unknown/expired session; the NAMED ``SessionStoreError`` (with
        the bad frame dropped, so retries don't re-hit it) for a frame
        that fails its integrity check."""
        with self._lock:
            frame = self._host.pop(sid, None)
            if frame is not None:
                self._host_nbytes -= len(frame)
            elif self.disk is not None and sid in self.disk:
                try:
                    frame = self.disk.get(sid)
                finally:
                    self.disk.delete(sid)
            self._deadlines.pop(sid, None)
            if frame is None:
                raise KeyError(f"unknown session {sid!r}")
            try:
                record = decode_session_frame(frame)
            except SessionStoreError:
                self.corrupt_skipped += 1
                raise
            deadline = record.get("expires_at")
            if deadline and self._clock() >= deadline:
                self.expires += 1
                raise KeyError(f"session {sid!r} expired")
            self.resumes += 1
            return record["data"]

    def _drop_locked(self, sid: str) -> None:
        frame = self._host.pop(sid, None)
        if frame is not None:
            self._host_nbytes -= len(frame)
        if self.disk is not None and sid in self.disk:
            self.disk.delete(sid)
        self._deadlines.pop(sid, None)

    def drop(self, sid: str) -> None:
        """Discard a parked session (no error if unknown)."""
        with self._lock:
            self._drop_locked(sid)

    def __contains__(self, sid: str) -> bool:
        with self._lock:
            return sid in self._host or (
                self.disk is not None and sid in self.disk)

    def __len__(self) -> int:
        with self._lock:
            return len(self._host) + (
                len(self.disk) if self.disk is not None else 0)

    # ------------------------------------------------------------ sweep

    def sweep(self, now: float | None = None) -> int:
        """Expire every session past its deadline; returns the count.
        Disk frames from a previous incarnation (no in-memory deadline)
        are decoded to read their embedded deadline; a frame that fails
        its integrity check is SKIPPED — dropped and counted in
        ``corrupt_skipped`` — never a crash."""
        now = self._clock() if now is None else now
        expired = 0
        with self._lock:
            for sid, deadline in list(self._deadlines.items()):
                if deadline and now >= deadline:
                    self._drop_locked(sid)
                    expired += 1
            if self.disk is not None:
                for sid in self.disk.ids():
                    if sid in self._deadlines:
                        continue  # handled above
                    try:
                        record = decode_session_frame(self.disk.get(sid))
                    except KeyError:
                        continue
                    except SessionStoreError:
                        self.disk.delete(sid)
                        self.corrupt_skipped += 1
                        continue
                    deadline = record.get("expires_at") or 0.0
                    self._deadlines[sid] = deadline
                    if deadline and now >= deadline:
                        self._drop_locked(sid)
                        expired += 1
            self.expires += expired
        return expired

    def maybe_sweep(self, now: float | None = None,
                    interval_s: float = 1.0) -> int:
        """Rate-limited ``sweep`` for per-tick callers: a no-op (0)
        unless TTL is on and ``interval_s`` has passed since the last
        sweep."""
        if self.ttl_s <= 0:
            return 0
        now = self._clock() if now is None else now
        if now < self._next_sweep:
            return 0
        self._next_sweep = now + interval_s
        return self.sweep(now)

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """Tier gauges + lifetime counters (the ``summary()["sessions"]``
        and tick-record feed)."""
        with self._lock:
            return {
                "parked_host": len(self._host),
                "parked_disk": (len(self.disk)
                                if self.disk is not None else 0),
                "bytes_host": self._host_nbytes,
                "bytes_disk": (self.disk.nbytes
                               if self.disk is not None else 0),
                "parks": self.parks,
                "resumes": self.resumes,
                "expires": self.expires,
                "corrupt_skipped": self.corrupt_skipped,
            }
