"""Tiny stdlib HTTP/SSE client for the fabric front end.

Used by the tests, ``scripts/bench_serving.py --service`` and any
operator tooling that wants to drive the service without pulling in an
HTTP library: ``http.client`` with ``Connection: close`` streaming —
the SSE body is read line-by-line off the socket, so TTFT/ITL stamps
taken here measure the full wire path (HTTP parse + SSE framing + the
worker RPC hop), which is exactly what the ``service_overhead_cpu``
bench row prices.
"""

from __future__ import annotations

import http.client
import json
import time


def http_json(host: str, port: int, method: str, path: str,
              body: dict | None = None, timeout: float = 60.0) -> dict:
    """One non-streaming JSON request/response."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body)
        conn.request(method, path, body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        out = json.loads(data.decode("utf-8")) if data else {}
        out["_status"] = resp.status
        return out
    finally:
        conn.close()


def stream_generate(host: str, port: int, spec: dict,
                    timeout: float = 300.0, on_event=None,
                    path: str = "/v1/generate") -> dict:
    """POST /v1/generate and consume the SSE stream to completion.

    Returns {"tokens": [...], "finish_reason": ..., "events": [...],
    "ttft_ms": ..., "itl_ms": [...]} — client-side latency stamps per
    token.  ``on_event`` (if given) sees each event as it arrives —
    the failover tests use it to know when a stream is mid-flight.
    Raises RuntimeError on an in-stream {"error": ...} event or a
    non-200 status.  Each event carries a ``resume`` cursor while the
    stream is live — feed the last one to ``stream_resume`` to
    re-attach through a restarted front end."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request("POST", path, body=json.dumps(spec),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"{path} -> {resp.status}: "
                f"{resp.read().decode('utf-8', 'replace')[:500]}"
            )
        tokens, events, stamps = [], [], []
        finish_reason, done = None, False
        while True:
            line = resp.fp.readline()
            if not line:
                break
            line = line.decode("utf-8").strip()
            if not line.startswith("data:"):
                continue
            ev = json.loads(line[len("data:"):].strip())
            if "error" in ev:
                raise RuntimeError(f"stream error: {ev['error']}")
            if on_event is not None:
                on_event(ev)
            events.append(ev)
            if "token" in ev:
                # a resumed stream whose cursor already covered every
                # token closes with a bare done marker — no token field
                tokens.append(ev["token"])
                stamps.append(time.perf_counter())
            if ev.get("done"):
                # done is terminal even with finish_reason None — the
                # /v1/resume fully-delivered-cursor close is a bare
                # done marker carrying no reason (server "resumed_empty")
                finish_reason, done = ev.get("finish_reason"), True
                break
        if not done:
            raise RuntimeError(
                f"SSE stream ended without a done event after "
                f"{len(tokens)} token(s)"
            )
        return {
            "tokens": tokens,
            "finish_reason": finish_reason,
            "events": events,
            "ttft_ms": (stamps[0] - t0) * 1000.0 if stamps else None,
            "itl_ms": [(b - a) * 1000.0
                       for a, b in zip(stamps, stamps[1:])],
        }
    finally:
        conn.close()


def stream_resume(host: str, port: int, resume_token: str,
                  timeout: float = 300.0, on_event=None) -> dict:
    """Re-attach an SSE stream from a resume cursor (the ``resume``
    field of the last event a previous connection delivered) through a
    possibly-RESTARTED front end: POST /v1/resume replays everything
    the workers generated past the cursor and keeps streaming to
    completion.  Same return shape as ``stream_generate``."""
    return stream_generate(host, port, {"resume": resume_token},
                           timeout=timeout, on_event=on_event,
                           path="/v1/resume")
