"""Tiny stdlib HTTP/SSE client for the fabric front end.

Used by the tests, ``scripts/bench_serving.py --service`` and any
operator tooling that wants to drive the service without pulling in an
HTTP library: ``http.client`` with ``Connection: close`` streaming —
the SSE body is read line-by-line off the socket, so TTFT/ITL stamps
taken here measure the full wire path (HTTP parse + SSE framing + the
worker RPC hop), which is exactly what the ``service_overhead_cpu``
bench row prices.
"""

from __future__ import annotations

import http.client
import json
import time


def http_json(host: str, port: int, method: str, path: str,
              body: dict | None = None, timeout: float = 60.0) -> dict:
    """One non-streaming JSON request/response."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body)
        conn.request(method, path, body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        out = json.loads(data.decode("utf-8")) if data else {}
        out["_status"] = resp.status
        return out
    finally:
        conn.close()


def stream_generate(host: str, port: int, spec: dict,
                    timeout: float = 300.0, on_event=None) -> dict:
    """POST /v1/generate and consume the SSE stream to completion.

    Returns {"tokens": [...], "finish_reason": ..., "events": [...],
    "ttft_ms": ..., "itl_ms": [...]} — client-side latency stamps per
    token.  ``on_event`` (if given) sees each event as it arrives —
    the failover tests use it to know when a stream is mid-flight.
    Raises RuntimeError on an in-stream {"error": ...} event or a
    non-200 status."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request("POST", "/v1/generate", body=json.dumps(spec),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"/v1/generate -> {resp.status}: "
                f"{resp.read().decode('utf-8', 'replace')[:500]}"
            )
        tokens, events, stamps = [], [], []
        finish_reason = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            line = line.decode("utf-8").strip()
            if not line.startswith("data:"):
                continue
            ev = json.loads(line[len("data:"):].strip())
            if "error" in ev:
                raise RuntimeError(f"stream error: {ev['error']}")
            if on_event is not None:
                on_event(ev)
            events.append(ev)
            tokens.append(ev["token"])
            stamps.append(time.perf_counter())
            if ev.get("done"):
                finish_reason = ev.get("finish_reason")
                break
        if finish_reason is None:
            raise RuntimeError(
                f"SSE stream ended without a done event after "
                f"{len(tokens)} token(s)"
            )
        return {
            "tokens": tokens,
            "finish_reason": finish_reason,
            "events": events,
            "ttft_ms": (stamps[0] - t0) * 1000.0 if stamps else None,
            "itl_ms": [(b - a) * 1000.0
                       for a, b in zip(stamps, stamps[1:])],
        }
    finally:
        conn.close()
