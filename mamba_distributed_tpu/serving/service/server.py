"""Asyncio HTTP/SSE front end + fabric controller for the service.

Two halves:

``FabricController`` — a thread that OWNS the ``RequestRouter`` (the
router is deliberately single-threaded: placement, failover replay and
migration bookkeeping are plain Python state).  Everything else talks
to the fabric through it: HTTP handlers enqueue closures (``call``)
or submissions (``submit_request``) and get ``concurrent.futures``
back; the loop drains commands, runs one ``HeartbeatMonitor`` pass,
steps the router, and fans TokenEvents out to per-request sink queues.
One controller iteration is exactly one fabric iteration — the same
serial order as the in-process ``router.serve()`` the parity tests
pin, which is why remote streams can be token-identical to solo
``generate()``.

``FabricHTTPServer`` — a stdlib-only asyncio HTTP/1.1 server:

  POST /v1/generate      JSON body -> SSE stream, one ``data:`` event
                         per token ({request_id, token, index, done,
                         finish_reason, resume}), connection closes at
                         done.  ``resume`` is an opaque cursor: POST it
                         to /v1/resume to re-attach the stream through
                         a RESTARTED front end (the workers keep the
                         request and its tokens across the gap)
  POST /v1/resume        {"resume": "<cursor>"} -> the same SSE stream,
                         replayed from the cursor and continuing live;
                         version-skewed cursors 400 with the named
                         UnknownWireVersionError, unknown streams 410.
                         {"session": "<id>"} instead resumes a PARKED
                         session (docs/SERVING.md "Durable sessions"):
                         the artifact re-places on any replica and the
                         stream CONTINUES from the park point; unknown/
                         expired sessions 410, corrupt frames 410 with
                         the named SessionStoreError
  POST /v1/park          {"request_id": N, "ttl_s": null} -> park one
                         in-flight stream into the session store (its
                         slot and pages free immediately); replies
                         {"session": "<id>"} — the resume handle.
                         Not-yet-decoding streams 409 (retriable),
                         unknown ids 404, no store configured 503
  POST /v1/tune          {"adapter": "tenant", "examples": [[ids...],
                         ...], "steps": 20} -> 202 with the job status
                         dict: one ONLINE LoRA fine-tune job on the
                         fabric's tuning plane (serving/tuning/, docs/
                         SERVING.md "Online adapter tuning") — trained
                         factors hot-register as the tenant's next
                         version and new requests A/B-route to it, no
                         offline pipeline.  Validation failures 400
                         with the named TuneError; no tuning plane 503
  GET  /v1/tune/<id>     one job's lifecycle snapshot (state queued/
                         running/completed/failed, step, loss,
                         deployed key); unknown/aged-out ids 404
  GET  /healthz          fabric + per-replica health (heartbeat ages,
                         missed beats, lifecycle states); 503 with
                         ``"ready": false`` when ZERO replicas accept
                         work, so a load balancer's readiness probe
                         needs no JSON parsing
  POST /drain/<replica>  graceful retire; queued-but-unplaced work
                         requeues to survivors (rolling restarts)
  GET  /metrics-summary  per-replica engine metrics summaries
  GET  /metrics          the whole fabric as ONE Prometheus scrape
                         target (text format 0.0.4): the controller's
                         fabric gauges + every replica's counters,
                         gauges and latency histograms, labeled by
                         {replica, role} (obs/prom.py holds the schema)

Request JSON: {"prompt_ids": [int, ...], "max_new_tokens": 32,
"top_k": 50, "temperature": 1.0, "eos_id": null, "seed": 0,
"priority": null} — the same knobs ``GenerationRequest`` takes; seed
(not a key) selects the sampling stream, so a request is reproducible
by a solo ``generate()`` call with ``PRNGKey(seed)``.

SSE was chosen over chunked JSON because failover is invisible in it:
the router's replay cursor suppresses re-derived duplicates BEFORE
events reach the sink, so a consumer mid-stream across a worker death
sees one contiguous token sequence — no reconnect, no gap, no dup
(tests/test_service.py kills a worker mid-stream and diffs against
solo ``generate()``).
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import json
import queue
import threading
import time

import numpy as np

from mamba_distributed_tpu.obs import jsonable, prom
from mamba_distributed_tpu.serving.autoscale import AdmissionRejected
from mamba_distributed_tpu.serving.scheduler import GenerationRequest
from mamba_distributed_tpu.serving.service import wire

# a sink item is either a token-event dict or an {"error": ...}
# terminator; an SSE handler waiting longer than this for the next one
# errors its stream out rather than holding the connection forever
_EVENT_POLL_S = 120.0


class FabricController(threading.Thread):
    """Single-threaded owner of the router; see module docstring."""

    def __init__(self, router, *, health=None, poll_s: float = 0.002,
                 adapters: dict | None = None,
                 session_sweep_s: float = 5.0, emit=None,
                 obs_pull_s: float = 0.0, obs_sink=None,
                 obs_limit: int = 4096, obs_keep: int = 65536,
                 autoscale=None, tuning=None):
        super().__init__(daemon=True, name="fabric-controller")
        self.router = router
        self.health = health
        self.poll_s = poll_s
        # elastic fabric (serving/autoscale/): an AutoscaleController
        # evaluated once per loop iteration — on the controller thread,
        # like everything that touches the router, so scale-ups
        # live-attach and scale-downs drain with no lock anywhere.
        # None = fixed fleet, the byte-stable status quo.
        self.autoscale = autoscale
        # online adapter tuning (serving/tuning/): an optional LOCAL
        # TuningService, ticked once per loop iteration on this thread
        # (like autoscale) so train steps interleave with fabric steps
        # and the SLO yield reads fresh p95s.  When the service has no
        # deploy callback the controller wires _deploy_tuned: freshly
        # trained factors land in this front end's adapter store and
        # fan out fabric-wide via ensure_adapter.  Remote trainer-role
        # lanes are stepped by _tick_tuning instead — router.step only
        # runs when GENERATION work is pending, and tune jobs never
        # count there.  None + no trainer replicas = byte-stable.
        self.tuning = tuning
        if tuning is not None and tuning.deploy is None:
            tuning.deploy = self._deploy_tuned
        # job_id -> replica_id for jobs shipped to remote trainer
        # lanes, so GET /v1/tune/<id> polls the lane holding the job
        self._tune_routes: dict[str, int] = {}
        # durable sessions: the background TTL sweeper's cadence over
        # the router's session store (when one is attached) and the
        # jsonl emitter its ``sessions_gc`` records land on (the same
        # sink serve_fabric wires for serving_health records).  No
        # store, or nothing expired, emits nothing — byte-stable.
        self.session_sweep_s = session_sweep_s
        self.emit = emit
        self._next_session_sweep = time.monotonic() + session_sweep_s
        # live telemetry plane (wire v5): at most every ``obs_pull_s``
        # the controller drains each worker's in-memory span/record
        # ring (the ``obs_pull`` RPC) into ONE merged fabric stream —
        # each record stamped ``obs_src`` with its origin replica — so
        # trace_export/obs_report see the whole multi-host fabric with
        # zero remote file access.  Per-replica cursors resume across
        # pulls; a changed worker boot_id resets the cursor (a fresh
        # ring shares no sequence space with its predecessor).  0 = off
        # (no RPCs, no records, byte-stable fabric).
        self.obs_pull_s = obs_pull_s
        self.obs_sink = obs_sink
        self.obs_limit = obs_limit
        self.obs_records: collections.deque = collections.deque(
            maxlen=obs_keep)
        self.obs_records_pulled = 0
        self.obs_records_dropped = 0
        self._obs_cursors: dict = {}
        self._next_obs_pull = time.monotonic() + (obs_pull_s or 0.0)
        # multi-tenant LoRA: the front end's host-side factor store —
        # name -> {"factors": {target: {"A", "B"}}, "alpha": float|None}
        # (scripts/serve_fabric.py --adapter name=path fills it).
        # ensure_adapter() ships entries to workers that have not
        # preloaded them (the load_adapter RPC), so an adapter loaded
        # ANYWHERE in the fabric is servable EVERYWHERE.
        self.adapters = adapters or {}
        # push outcomes memoized per (replica, worker boot, name) so a
        # hot adapter's MB-scale factor payload ships AT MOST ONCE per
        # worker generation — not once per request — and a worker that
        # REJECTED a push (LoRA off, registry full) is never hammered
        # again; a worker restart changes its boot id, naturally
        # invalidating both
        self._adapter_pushes: dict = {}
        self._commands: queue.Queue = queue.Queue()
        self._sinks: dict[int, queue.Queue] = {}
        self._stop_requested = threading.Event()
        self.stepped = 0  # fabric iterations (bench/debug gauge)

    # ------------------------------------------------------- thread-safe API

    def call(self, fn) -> concurrent.futures.Future:
        """Run ``fn()`` on the controller thread; Future of its result."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._commands.put((fn, fut))
        return fut

    def submit_request(self, request: GenerationRequest
                       ) -> concurrent.futures.Future:
        """Admit a request; Future of (global_id, sink queue).  The sink
        receives one dict per token and, on fabric-level failure, an
        {"error": ...} terminator."""

        def _do():
            sink: queue.Queue = queue.Queue()
            gid = self.router.submit(request)
            self._sinks[gid] = sink
            return gid, sink

        return self.call(_do)

    def park_session(self, global_id: int, ttl_s: float | None = None
                     ) -> concurrent.futures.Future:
        """Park one in-flight stream into the fabric's session store;
        Future of the session id.  The stream's open SSE sink (if any)
        ends with a ``finish_reason: "parked"`` marker carrying the id,
        so an attached consumer learns its resume handle as the stream
        closes."""

        def _do():
            sid = self.router.park(global_id, ttl_s=ttl_s)
            sink = self._sinks.pop(global_id, None)
            if sink is not None:
                sink.put({"request_id": global_id, "done": True,
                          "finish_reason": "parked", "session": sid})
            return sid

        return self.call(_do)

    def resume_session(self, session_id: str) -> concurrent.futures.Future:
        """Re-admit a parked session; Future of (global_id, sink
        queue).  The stream CONTINUES from the park point — no replay
        of tokens the client already has (the session id is the
        client's proof it consumed them; the SSE cursor path covers
        mid-stream re-attach)."""

        def _do():
            gid = self.router.resume_parked(session_id)
            sink: queue.Queue = queue.Queue()
            self._sinks[gid] = sink
            return gid, sink

        return self.call(_do)

    def stop(self) -> None:
        self._stop_requested.set()

    # ------------------------------------------------ multi-tenant LoRA

    def ensure_adapter(self, name: str) -> bool:
        """Make ``name`` servable: True once at least one alive replica
        has it registered — pushing this controller's own factor store
        to workers that lack it (the ``load_adapter`` RPC; idempotent).
        False = the adapter is known NOWHERE (no preload, no store
        entry): the HTTP layer answers 404 with the named
        ``UnknownAdapterError`` body, never a hang.  Runs on the
        controller thread (``call``)."""
        ok = False
        local = self.adapters.get(name)
        for rep in self.router.replicas:
            if not rep.alive:
                continue
            if hasattr(rep, "adapters_registered"):  # a RemoteReplica
                if name in rep.adapters_registered():
                    ok = True
                    continue
                push_key = (rep.replica_id,
                            getattr(rep, "boot_id", None), name)
                prior = self._adapter_pushes.get(push_key)
                if prior is not None:
                    ok = ok or prior
                    continue
                if local is not None:
                    try:
                        rep.load_adapter(name, local["factors"],
                                         local.get("alpha"))
                        self._adapter_pushes[push_key] = True
                        ok = True
                    except wire.WireError:
                        pass  # transient socket fault: retry later
                    except Exception:  # noqa: BLE001 — one worker's
                        # failed push must not fail the request, and a
                        # REJECTED push (LoRA off, registry full) must
                        # not re-ship the MB-scale payload per request
                        self._adapter_pushes[push_key] = False
            else:  # in-process EngineReplica: registries may be shared
                reg = getattr(rep.engine, "adapters", None)
                if reg is None:
                    continue
                if name in reg:
                    ok = True
                    continue
                if local is not None:
                    try:
                        reg.register(name, local["factors"],
                                     alpha=local.get("alpha"))
                        ok = True
                    except ValueError:
                        # registry full, or a shared instance another
                        # replica's pass already filled
                        ok = ok or name in reg
        return ok

    # ------------------------------------------------- online tuning

    def _deploy_tuned(self, key: str) -> None:
        """TuningService deploy callback (controller thread — ticks
        run inside the loop): stash the freshly trained version's
        factors in this front end's store, then fan the canonical key
        fabric-wide through the same ``ensure_adapter`` push every
        request-time miss uses.  The registry stores EFFECTIVE factors
        (``alpha / rank`` already folded into B), so the store entry
        carries ``alpha=rank`` — scale 1.0 on every downstream
        re-registration, factors bit-exact on every worker."""
        reg = self.tuning.trainer.registry
        self.adapters[key] = {
            "factors": reg.factors(key), "alpha": float(reg.rank),
        }
        self.ensure_adapter(key)

    def submit_tune(self, adapter: str, examples,
                    steps: int | None = None
                    ) -> concurrent.futures.Future:
        """Enqueue one online fine-tune job (the POST /v1/tune body);
        Future of its status dict.  A local TuningService takes it
        directly; with none, the job ships to the first accepting
        trainer-role RemoteReplica (the wire-v6 ``submit_tune`` RPC)
        and the job id pins to that lane for status polls.  No tuning
        plane at all raises RuntimeError — the HTTP layer's 503."""

        def _do():
            if self.tuning is not None:
                job = self.tuning.submit(adapter, examples, steps)
                return job.status()
            for rep in self.router.replicas:
                if (getattr(rep, "role", None) == "trainer"
                        and rep.accepting
                        and hasattr(rep, "submit_tune")):
                    st = rep.submit_tune(adapter, examples, steps)
                    self._tune_routes[st["job_id"]] = rep.replica_id
                    return st
            raise RuntimeError(
                "no tuning plane: this fabric has neither a local "
                "TuningService nor an accepting trainer-role replica"
            )

        return self.call(_do)

    def tune_status(self, job_id: str) -> concurrent.futures.Future:
        """One tune job's lifecycle snapshot; Future of the status
        dict.  Unknown/aged-out ids raise the named TuneError (the
        HTTP layer's 404)."""

        def _do():
            if self.tuning is not None:
                return self.tuning.status(job_id)
            rid = self._tune_routes.get(job_id)
            if rid is not None and rid < len(self.router.replicas):
                rep = self.router.replicas[rid]
                if rep.alive:
                    return rep.tune_status(job_id)
                raise RuntimeError(
                    f"trainer lane {rid} holding tune job {job_id!r} "
                    f"is dead — resubmit the job"
                )
            from mamba_distributed_tpu.serving.tuning import TuneError

            raise TuneError(f"unknown tune job {job_id!r}")

        return self.call(_do)

    def _tick_tuning(self) -> None:
        """One tuning pass per fabric iteration: step every accepting
        trainer-role replica with queued work (router.step never
        reaches them — ``router.pending`` counts generation requests
        only), then tick a lane-less local service directly so the
        queue keeps moving when no TrainerReplica is attached or the
        lane died mid-job (the docs/SERVING.md failure matrix: jobs
        and trainer state are fabric-owned, the service survives its
        lanes)."""
        lanes = [r for r in self.router.replicas
                 if getattr(r, "role", None) == "trainer"
                 and r.alive and r.accepting]
        for rep in lanes:
            if not rep.pending:
                continue
            try:
                rep.step()
            except Exception as e:  # noqa: BLE001 — one lane's fault
                # must not kill serving (a wire fault already marked
                # the lane dead; the heartbeat monitor reaps it)
                if self.emit is not None:
                    self.emit({
                        "kind": "serving_health", "t": time.time(),
                        "event": "tuning_error",
                        "replica": rep.replica_id,
                        "error": f"{type(e).__name__}: {e}",
                    })
        if self.tuning is not None and not any(
                getattr(r, "service", None) is self.tuning
                for r in lanes):
            try:
                self.tuning.tick()
            except Exception as e:  # noqa: BLE001 — per-job failures
                # fail the JOB inside tick(); anything escaping is a
                # plane-level fault that must not kill serving
                if self.emit is not None:
                    self.emit({
                        "kind": "serving_health", "t": time.time(),
                        "event": "tuning_error",
                        "error": f"{type(e).__name__}: {e}",
                    })

    # ------------------------------------------------------------ the loop

    def run(self) -> None:
        while not self._stop_requested.is_set():
            worked = self._drain_commands()
            self._sweep_sessions()
            self._drain_obs()
            if self.autoscale is not None:
                # one policy evaluation per fabric iteration: pressure
                # counters advance here, scale-ups live-attach through
                # router.add_replica, scale-downs drain + retire —
                # all on this thread, interleaved with stepping
                try:
                    self.autoscale.tick()
                except Exception as e:  # noqa: BLE001
                    # a failed provision (spawn error, resource limit)
                    # must not kill serving: the fixed fleet keeps
                    # stepping and the next pressured tick retries
                    if self.emit is not None:
                        self.emit({
                            "kind": "serving_health", "t": time.time(),
                            "event": "autoscale_error",
                            "error": f"{type(e).__name__}: {e}",
                        })
            self._tick_tuning()
            if self.health is not None:
                try:
                    self.health.tick()
                except RuntimeError as e:
                    # failover with zero survivors: surface to every
                    # waiting stream rather than dying silently
                    self._error_out(str(e))
            if self.router.pending:
                try:
                    events = self.router.step()
                except RuntimeError as e:
                    # stranded requests (dead replicas, no survivors):
                    # terminate the waiting streams, then back off —
                    # pending stays nonzero so without the sleep this
                    # would busy-spin re-raising the same error
                    self._error_out(str(e))
                    time.sleep(max(self.poll_s, 0.05))
                    continue
                self.stepped += 1
                for ev in events:
                    sink = self._sinks.get(ev.request_id)
                    if sink is None:
                        continue
                    sink.put(self._event_dict(ev))
                    if ev.done:
                        del self._sinks[ev.request_id]
            elif not worked:
                time.sleep(self.poll_s)
        # controller exiting with streams open: terminate them cleanly
        self._error_out("fabric controller stopped")

    def _event_dict(self, ev) -> dict:
        """One TokenEvent as an SSE payload, stamped with the resume
        cursor — (replica, local id, next index) as an opaque
        ``wire.encode_resume_token`` — so a client holding the last
        event can re-attach through a RESTARTED front end via
        POST /v1/resume instead of resubmitting.  The location comes
        from the router's live table (it tracks failover moves);
        finished streams carry no cursor — there is nothing left to
        resume."""
        d = {
            "request_id": ev.request_id, "token": int(ev.token),
            "index": int(ev.index), "done": bool(ev.done),
            "finish_reason": ev.finish_reason,
        }
        loc = self.router.stream_location(ev.request_id)
        if loc is not None:
            d["resume"] = wire.encode_resume_token(
                loc[0], loc[1], int(ev.index) + 1,
                boot_id=getattr(self.router.replicas[loc[0]],
                                "boot_id", None),
            )
        return d

    def attach_resumed(self, token: str) -> concurrent.futures.Future:
        """Re-attach a stream from a resume cursor; Future of
        (global_id, sink queue).  The sink is pre-loaded with the
        replayed tokens (everything the worker generated past the
        cursor) and — for a still-running stream — registered for the
        live events that follow; a finished stream's sink ends with its
        final event (or a bare done marker when the cursor already
        covered every token)."""
        rid, lid, index, boot = wire.decode_resume_token(token)

        def _do():
            gid, events = self.router.attach_resumed(
                rid, lid, index, boot_id=boot
            )
            sink: queue.Queue = queue.Queue()
            for ev in events:
                sink.put(self._event_dict(ev))
            still_running = self.router.stream_location(gid) is not None
            if still_running:
                self._sinks[gid] = sink
            elif not events:
                # finished AND fully delivered: close the stream with a
                # token-less done marker so the SSE handler terminates
                sink.put({"request_id": gid, "done": True,
                          "finish_reason": None, "resumed_empty": True})
            return gid, sink

        return self.call(_do)

    def _sweep_sessions(self) -> None:
        """Background TTL GC over the router's session store (when one
        is attached): rate-limited to ``session_sweep_s``, emits one
        ``sessions_gc`` obs record per sweep that reaped anything.  A
        sweep failure (a disk frame going bad under us) is counted by
        the store, never fatal to the fabric loop."""
        store = getattr(self.router, "session_store", None)
        if store is None or time.monotonic() < self._next_session_sweep:
            return
        self._next_session_sweep = time.monotonic() + self.session_sweep_s
        try:
            expired = store.sweep()
        except Exception:  # noqa: BLE001 — GC must never kill serving
            return
        if expired and self.emit is not None:
            st = store.stats()
            self.emit({
                "kind": "sessions_gc", "t": time.time(),
                "expired": expired,
                "parked_host": st["parked_host"],
                "parked_disk": st["parked_disk"],
                "bytes_host": st["bytes_host"],
                "bytes_disk": st["bytes_disk"],
            })

    def _drain_obs(self) -> None:
        """Pull each worker's obs ring into the merged fabric stream
        (rate-limited like ``_sweep_sessions``).  obs_pull is NON-fatal
        on the replica side, so a wedged worker costs one skipped page,
        never a failover; in-process replicas with no ring (or ring-
        less workers) return empty pages and cost nothing."""
        if not self.obs_pull_s or time.monotonic() < self._next_obs_pull:
            return
        self._next_obs_pull = time.monotonic() + self.obs_pull_s
        for rep in self.router.replicas:
            if not rep.alive:
                continue
            pull = getattr(rep, "obs_pull", None)
            if pull is not None:  # a RemoteReplica: the wire-v5 RPC
                state = self._obs_cursors.setdefault(
                    rep.replica_id, {"cursor": 0, "boot_id": None})
                page = pull(state["cursor"], self.obs_limit)
                if page is None:
                    continue  # transient wire fault: same cursor next pull
                boot = page.get("boot_id")
                if (state["boot_id"] is not None
                        and boot != state["boot_id"]):
                    # the worker rebooted under us: its fresh ring shares
                    # no sequence space with the cursor we hold — restart
                    # from 0 rather than silently mis-resuming
                    page = pull(0, self.obs_limit)
                    if page is None:
                        continue
                state["boot_id"] = boot
                state["cursor"] = int(page.get("cursor", state["cursor"]))
                self.obs_records_dropped += int(page.get("dropped", 0))
                records = page.get("records", [])
            else:  # in-process replica: drain its tracer ring directly
                tracer = getattr(rep.engine, "tracer", None)
                ring_pull = getattr(tracer, "ring_pull", None)
                if ring_pull is None:
                    continue
                state = self._obs_cursors.setdefault(
                    rep.replica_id, {"cursor": 0, "boot_id": None})
                page = ring_pull(state["cursor"], self.obs_limit)
                state["cursor"] = int(page["cursor"])
                self.obs_records_dropped += int(page["dropped"])
                records = page["records"]
            src = f"replica{rep.replica_id}"
            for rec in records:
                rec = dict(rec)
                rec["obs_src"] = src
                self.obs_records.append(rec)
                self.obs_records_pulled += 1
                if self.obs_sink is not None:
                    try:
                        self.obs_sink(rec)
                    except Exception:  # noqa: BLE001 — a bad sink (disk
                        # full) must never kill the fabric loop
                        pass

    def _drain_commands(self) -> bool:
        worked = False
        while True:
            try:
                fn, fut = self._commands.get_nowait()
            except queue.Empty:
                return worked
            worked = True
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                fut.set_exception(e)

    def _error_out(self, message: str) -> None:
        for gid, sink in list(self._sinks.items()):
            sink.put({"error": message, "request_id": gid, "done": True})
            del self._sinks[gid]


# ----------------------------------------------------------------- HTTP/SSE


def _fabric_queue_depth(router) -> int:
    """Queued-but-unstarted requests fabric-wide, duck-typed over the
    two replica kinds (RemoteReplica heartbeat stats vs in-process
    engine reads) — the /healthz field and the admission cap's gauge."""
    depth = 0
    for r in router.replicas:
        if not r.alive:
            continue
        stats = getattr(r, "stats", None)
        if stats is not None:
            depth += int(stats.get("depth", 0))
        else:
            depth += int(r.engine.scheduler.depth)
    return depth


def _http_response(status: str, body: bytes,
                   content_type: str = "application/json",
                   extra_headers: dict | None = None) -> bytes:
    headers = "".join(f"{k}: {v}\r\n"
                      for k, v in (extra_headers or {}).items())
    return (
        f"HTTP/1.1 {status}\r\nContent-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n{headers}Connection: close\r\n\r\n"
    ).encode("ascii") + body


def _json_response(status: str, obj,
                   extra_headers: dict | None = None) -> bytes:
    return _http_response(
        status, (json.dumps(obj) + "\n").encode("utf-8"),
        extra_headers=extra_headers,
    )


class FabricHTTPServer:
    """The stdlib asyncio front end; see module docstring."""

    def __init__(self, controller: FabricController,
                 host: str = "127.0.0.1", port: int = 0):
        self.controller = controller
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    def start_background(self) -> int:
        """Run the server on its own thread + loop; returns the bound
        port (tests and the bench drive the fabric this way)."""
        started = threading.Event()

        def _run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def _main():
                await self.start()
                started.set()
                await self._server.serve_forever()

            try:
                loop.run_until_complete(_main())
            except asyncio.CancelledError:
                pass
            finally:
                loop.close()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="fabric-http")
        self._thread.start()
        if not started.wait(30):
            raise RuntimeError("HTTP server failed to start within 30s")
        return self.port

    def stop(self) -> None:
        if self._loop is not None and self._server is not None:
            def _shutdown():
                self._server.close()
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()

            self._loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=10)

    # ------------------------------------------------------------- handling

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = (await reader.readline()).decode("latin-1")
            if not request_line.strip():
                return
            try:
                method, path, _version = request_line.split()
            except ValueError:
                writer.write(_json_response(
                    "400 Bad Request", {"error": "malformed request line"}))
                return
            headers = {}
            while True:
                line = (await reader.readline()).decode("latin-1")
                if line in ("\r\n", "\n", ""):
                    break
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", 0) or 0)
            if n:
                body = await reader.readexactly(n)
            try:
                await self._route(method, path, body, writer)
            except (ConnectionError, asyncio.IncompleteReadError):
                raise
            except Exception as e:  # noqa: BLE001 — a handler bug must
                # surface as a 500, not a silently dropped connection
                writer.write(_json_response(
                    "500 Internal Server Error",
                    {"error": f"{type(e).__name__}: {e}"}))
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _route(self, method: str, path: str, body: bytes,
                     writer: asyncio.StreamWriter) -> None:
        ctrl = self.controller
        if method == "POST" and path == "/v1/generate":
            await self._generate(body, writer)
        elif method == "POST" and path == "/v1/resume":
            await self._resume(body, writer)
        elif method == "POST" and path == "/v1/park":
            await self._park(body, writer)
        elif method == "POST" and path == "/v1/tune":
            await self._tune(body, writer)
        elif method == "GET" and path.startswith("/v1/tune/"):
            await self._tune_status(path[len("/v1/tune/"):], writer)
        elif method == "GET" and path == "/healthz":
            snap = await asyncio.wrap_future(ctrl.call(self._health_payload))
            # a load balancer's readiness probe reads the status line
            # alone: zero accepting replicas is 503, not a JSON field
            status = ("200 OK" if snap.get("ready")
                      else "503 Service Unavailable")
            writer.write(_json_response(status, snap))
        elif method == "GET" and path == "/metrics-summary":
            summary = await asyncio.wrap_future(
                ctrl.call(lambda: jsonable(ctrl.router.summary()))
            )
            writer.write(_json_response("200 OK", summary))
        elif method == "GET" and path == "/metrics":
            text = await asyncio.wrap_future(ctrl.call(self._metrics_text))
            writer.write(_http_response(
                "200 OK", text.encode("utf-8"),
                content_type=prom.CONTENT_TYPE))
        elif method == "POST" and path.startswith("/drain/"):
            try:
                rid = int(path.rsplit("/", 1)[1])
            except ValueError:
                writer.write(_json_response(
                    "400 Bad Request",
                    {"error": f"bad replica id in {path!r}"}))
                return
            try:
                moved = await asyncio.wrap_future(ctrl.call(
                    lambda: ctrl.router.drain(rid, requeue_queued=True)
                ))
            except (IndexError, KeyError):
                writer.write(_json_response(
                    "404 Not Found", {"error": f"no replica {rid}"}))
                return
            except Exception as e:  # noqa: BLE001 — drain hit a wire
                # fault mid-requeue; the router kept the requests (see
                # router.drain's fallback) — report, don't crash
                writer.write(_json_response(
                    "500 Internal Server Error",
                    {"error": f"drain failed: {e}"}))
                return
            writer.write(_json_response(
                "200 OK", {"replica": rid, "requeued": moved}))
        else:
            writer.write(_json_response(
                "404 Not Found",
                {"error": f"no route for {method} {path}"}))
        await writer.drain()

    def _health_payload(self) -> dict:
        router = self.controller.router
        payload = {
            "pending": router.pending,
            "migrations": router.migrations,
            "replicas": {
                str(r.replica_id): {"state": r.state.value, "role": r.role,
                                    "pending": r.pending}
                for r in router.replicas
            },
        }
        store = getattr(router, "session_store", None)
        if store is not None:
            payload["sessions"] = store.stats()
        if self.controller.health is not None:
            for rid, h in self.controller.health.snapshot().items():
                payload["replicas"][str(rid)].update(h)
        # the elastic-fabric signals an EXTERNAL orchestrator needs to
        # make the same decisions the autoscaler does (the ISSUE-18
        # satellite): accepting-replica count and fabric-wide queued
        # work.  Always present — additive keys next to the pinned
        # "ok"/"ready" bools, computed from the same replica reads the
        # payload already does.
        payload["accepting"] = sum(
            1 for r in router.replicas if r.accepting
        )
        payload["queue_depth"] = _fabric_queue_depth(router)
        payload["ok"] = any(
            r.accepting for r in router.replicas
        )
        # "ready" is the load-balancer bit (drives the 503): kept as a
        # separate top-level bool so "ok" stays what PR-6 pinned
        payload["ready"] = payload["ok"]
        return payload

    def _metrics_text(self) -> str:
        """One fabric-wide Prometheus exposition document (runs on the
        controller thread): the controller's own fabric gauges plus a
        per-replica snapshot — RemoteReplicas ship summary + full
        histogram buckets + live stats over the wire-v5 ``summary``
        RPC; in-process replicas read their engine metrics directly."""
        ctrl = self.controller
        router = ctrl.router
        snapshots = []
        for r in router.replicas:
            if not r.alive:
                continue
            snap_rpc = getattr(r, "metrics_snapshot", None)
            if snap_rpc is not None:  # a RemoteReplica
                payload = snap_rpc()
                if payload is None:
                    continue  # transient wire fault: skip this scrape
                snapshots.append({
                    "replica": r.replica_id,
                    "role": payload.get("role", r.role),
                    "summary": payload.get("summary") or {},
                    "histograms": payload.get("histograms") or {},
                    "stats": payload.get("stats") or r.stats,
                })
            else:  # in-process EngineReplica
                m = r.engine.metrics
                snapshots.append({
                    "replica": r.replica_id,
                    "role": r.role,
                    "summary": m.summary(),
                    "histograms": m.histogram_dicts(),
                    "stats": {
                        "depth": int(r.engine.scheduler.depth),
                        "resident": len(r.engine._slots),
                        "capacity": int(r.engine.capacity),
                    },
                })
        reps = router.replicas
        plane_on = bool(ctrl.obs_pull_s)
        # elastic-fabric families are None-gated exactly like the obs
        # counters: no admission controller / no autoscaler => the
        # exposition is byte-identical to the pre-elastic fabric's
        admission = getattr(router, "admission", None)
        return prom.render_fabric(
            snapshots,
            replicas=len(reps),
            accepting=sum(1 for r in reps if r.accepting),
            ready=any(r.accepting for r in reps),
            obs_records_pulled=(
                ctrl.obs_records_pulled if plane_on else None),
            obs_records_dropped=(
                ctrl.obs_records_dropped if plane_on else None),
            queue_depth=(
                _fabric_queue_depth(router)
                if admission is not None or ctrl.autoscale is not None
                else None),
            sheds=(None if admission is None else {
                "queue_cap": admission.sheds_cap,
                "queue_deadline": admission.sheds_deadline,
            }),
            autoscale=(None if ctrl.autoscale is None else {
                "scale_ups": ctrl.autoscale.scale_ups,
                "scale_downs": ctrl.autoscale.scale_downs,
            }),
            tune_queue_depth=(
                None if ctrl.tuning is None else ctrl.tuning.depth),
        )

    async def _generate(self, body: bytes,
                        writer: asyncio.StreamWriter) -> None:
        try:
            spec = json.loads(body.decode("utf-8"))
            request = GenerationRequest(
                prompt_ids=np.asarray(spec["prompt_ids"], np.int32),
                max_new_tokens=int(spec.get("max_new_tokens", 32)),
                top_k=int(spec.get("top_k", 50)),
                temperature=float(spec.get("temperature", 1.0)),
                eos_id=spec.get("eos_id"),
                seed=int(spec.get("seed", 0)),
                priority=spec.get("priority"),
                adapter=spec.get("adapter"),
                queue_deadline_ms=(
                    None if spec.get("queue_deadline_ms") is None
                    else float(spec["queue_deadline_ms"])),
            )
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            # TypeError covers non-dict JSON bodies (`123`, `[1,2]`):
            # json.loads succeeds, the field access doesn't
            writer.write(_json_response(
                "400 Bad Request", {"error": f"bad request body: {e}"}))
            return
        if request.adapter:
            # multi-tenant LoRA: the adapter must be servable SOMEWHERE
            # before placement (ensure_adapter pushes this front end's
            # factors to workers that lack them) — an unknown name is a
            # 404 with the NAMED error body, never a hang or a silent
            # base-model stream
            known = await asyncio.wrap_future(self.controller.call(
                lambda: self.controller.ensure_adapter(request.adapter)
            ))
            if not known:
                writer.write(_json_response("404 Not Found", {
                    "error": f"unknown adapter {request.adapter!r}: not "
                             f"preloaded on any worker and not in this "
                             f"front end's factor store",
                    "error_type": "UnknownAdapterError",
                }))
                return
        try:
            gid, sink = await asyncio.wrap_future(
                self.controller.submit_request(request)
            )
        except AdmissionRejected as e:
            # shed at the front door (queue cap / deadline estimate):
            # 429 with a whole-second Retry-After hint — reject-fast
            # beats timeout for goodput, and the client learns when the
            # queue should have drained enough to try again
            retry_s = max(1, int(-(-e.retry_after_s // 1)))
            writer.write(_json_response(
                "429 Too Many Requests",
                {"error": str(e), "error_type": "AdmissionRejected",
                 "reason": e.reason, "retry_after_s": e.retry_after_s},
                extra_headers={"Retry-After": str(retry_s)},
            ))
            return
        except (ValueError, RuntimeError) as e:
            # invalid request, or nothing accepting (all draining/dead)
            if "UnknownAdapterError" in f"{type(e).__name__}: {e}":
                # an engine-level rejection that slipped past the gate
                # (e.g. a race with a registry eviction): same 404 body
                writer.write(_json_response("404 Not Found", {
                    "error": str(e),
                    "error_type": "UnknownAdapterError",
                }))
                return
            status = ("400 Bad Request" if isinstance(e, ValueError)
                      else "503 Service Unavailable")
            writer.write(_json_response(status, {"error": str(e)}))
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        await self._stream_sse(writer, gid, sink)

    async def _park(self, body: bytes,
                    writer: asyncio.StreamWriter) -> None:
        """POST /v1/park {"request_id": N, "ttl_s": null} — park one
        in-flight stream into the session store (docs/SERVING.md
        "Durable sessions"): its slot and pages free immediately, the
        reply carries the session id, and ``POST /v1/resume
        {"session": "<id>"}`` continues the stream later on ANY
        replica.  Unknown ids 404; a stream still queued/prefilling
        409s (retriable — re-ask after a tick); no store 503."""
        try:
            spec = json.loads(body.decode("utf-8"))
            gid = int(spec["request_id"])
            ttl_s = spec.get("ttl_s")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            writer.write(_json_response(
                "400 Bad Request", {"error": f"bad park body: {e}"}))
            return
        try:
            sid = await asyncio.wrap_future(
                self.controller.park_session(
                    gid, None if ttl_s is None else float(ttl_s))
            )
        except KeyError as e:
            writer.write(_json_response(
                "404 Not Found", {"error": str(e).strip("'\"")}))
            return
        except ValueError as e:
            # not yet DECODE-resident: the client may retry
            writer.write(_json_response(
                "409 Conflict", {"error": str(e), "retriable": True}))
            return
        except RuntimeError as e:
            writer.write(_json_response(
                "503 Service Unavailable", {"error": str(e)}))
            return
        writer.write(_json_response(
            "200 OK", {"request_id": gid, "session": sid}))

    async def _tune(self, body: bytes,
                    writer: asyncio.StreamWriter) -> None:
        """POST /v1/tune — enqueue one online LoRA fine-tune job
        (docs/SERVING.md "Online adapter tuning").  202 with the job's
        status dict (poll GET /v1/tune/<job_id>); malformed bodies and
        TuneError validations 400; no tuning plane 503."""
        from mamba_distributed_tpu.serving.tuning import TuneError

        try:
            spec = json.loads(body.decode("utf-8"))
            adapter = str(spec["adapter"])
            examples = spec["examples"]
            steps = spec.get("steps")
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            writer.write(_json_response(
                "400 Bad Request", {"error": f"bad tune body: {e}"}))
            return
        try:
            status = await asyncio.wrap_future(
                self.controller.submit_tune(
                    adapter, examples,
                    None if steps is None else int(steps))
            )
        except TuneError as e:
            writer.write(_json_response(
                "400 Bad Request",
                {"error": str(e), "error_type": "TuneError"}))
            return
        except (ValueError, RuntimeError, wire.WireError) as e:
            # a remote lane's rejection arrives as a wrapped
            # RuntimeError carrying the worker-side error_type — map
            # its TuneError back to the same 400 the local path gives
            if "TuneError" in str(e):
                writer.write(_json_response(
                    "400 Bad Request",
                    {"error": str(e), "error_type": "TuneError"}))
                return
            writer.write(_json_response(
                "503 Service Unavailable", {"error": str(e)}))
            return
        writer.write(_json_response("202 Accepted", status))

    async def _tune_status(self, job_id: str,
                           writer: asyncio.StreamWriter) -> None:
        """GET /v1/tune/<job_id> — one job's lifecycle snapshot.
        Unknown/aged-out ids 404 with the named TuneError; a dead or
        wire-faulted trainer lane 503."""
        from mamba_distributed_tpu.serving.tuning import TuneError

        try:
            status = await asyncio.wrap_future(
                self.controller.tune_status(job_id))
        except TuneError as e:
            writer.write(_json_response(
                "404 Not Found",
                {"error": str(e), "error_type": "TuneError"}))
            return
        except (ValueError, RuntimeError, wire.WireError) as e:
            if "TuneError" in str(e):  # remote lane's unknown-id path
                writer.write(_json_response(
                    "404 Not Found",
                    {"error": str(e), "error_type": "TuneError"}))
                return
            writer.write(_json_response(
                "503 Service Unavailable", {"error": str(e)}))
            return
        writer.write(_json_response("200 OK", status))

    async def _resume(self, body: bytes,
                      writer: asyncio.StreamWriter) -> None:
        """POST /v1/resume {"resume": "<cursor>"} — re-attach an SSE
        stream through a restarted front end (docs/SERVING.md "SSE
        resume tokens").  The worker kept the request and every emitted
        token across the controller gap; the new fabric adopts the
        stream, replays everything past the cursor, and keeps
        streaming.  A version-skewed cursor 400s with the NAMED
        ``UnknownWireVersionError``; an unknown stream 410s (resubmit —
        same seed, same tokens).

        {"session": "<id>"} instead resumes a PARKED session: the
        artifact re-places on any accepting replica and the SSE stream
        CONTINUES from the park point.  Unknown/expired sessions 410;
        a corrupt frame 410s with the NAMED ``SessionStoreError`` (the
        store already skipped the session)."""
        try:
            spec = json.loads(body.decode("utf-8"))
            token = spec.get("resume")
            session = spec.get("session")
            if (token is None) == (session is None):
                raise KeyError(
                    "exactly one of 'resume' (an SSE cursor) or "
                    "'session' (a park id) is required"
                )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            writer.write(_json_response(
                "400 Bad Request", {"error": f"bad resume body: {e}"}))
            return
        if session is not None:
            await self._resume_session(str(session), writer)
            return
        try:
            gid, sink = await asyncio.wrap_future(
                self.controller.attach_resumed(token)
            )
        except wire.UnknownWireVersionError as e:
            writer.write(_json_response(
                "400 Bad Request",
                {"error": str(e), "error_type": type(e).__name__}))
            return
        except wire.WireError as e:
            writer.write(_json_response(
                "400 Bad Request", {"error": f"bad resume token: {e}"}))
            return
        except KeyError as e:
            writer.write(_json_response(
                "410 Gone", {"error": str(e).strip("'\"")}))
            return
        except (ValueError, RuntimeError) as e:
            writer.write(_json_response(
                "409 Conflict" if isinstance(e, ValueError)
                else "503 Service Unavailable", {"error": str(e)}))
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        await self._stream_sse(writer, gid, sink)

    async def _resume_session(self, session_id: str,
                              writer: asyncio.StreamWriter) -> None:
        """The parked-session half of POST /v1/resume: re-admit the
        artifact and stream the continuation."""
        from mamba_distributed_tpu.serving.sessions import SessionStoreError

        try:
            gid, sink = await asyncio.wrap_future(
                self.controller.resume_session(session_id)
            )
        except SessionStoreError as e:
            # corrupt/truncated frame: the store skipped the session;
            # the NAMED error reaches the client, never a crash
            writer.write(_json_response(
                "410 Gone",
                {"error": str(e), "error_type": type(e).__name__}))
            return
        except KeyError as e:
            writer.write(_json_response(
                "410 Gone", {"error": str(e).strip("'\"")}))
            return
        except (ValueError, RuntimeError) as e:
            writer.write(_json_response(
                "409 Conflict" if isinstance(e, ValueError)
                else "503 Service Unavailable", {"error": str(e)}))
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        await self._stream_sse(writer, gid, sink)

    async def _stream_sse(self, writer: asyncio.StreamWriter, gid: int,
                          sink) -> None:
        """Drain one request's sink queue onto the wire as SSE events
        (shared by /v1/generate and /v1/resume — one copy of the pump
        protocol)."""
        # one dedicated pump thread per stream, bridging the blocking
        # sink queue into the loop: the shared default executor would
        # cap concurrent streams at its thread count (each blocked in
        # sink.get), head-of-line-starving every stream beyond it
        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()

        def _pump():
            while True:
                try:
                    ev = sink.get(timeout=_EVENT_POLL_S)
                except queue.Empty:
                    ev = {"error": f"no token within {_EVENT_POLL_S}s",
                          "request_id": gid, "done": True}
                try:
                    loop.call_soon_threadsafe(aq.put_nowait, ev)
                except RuntimeError:
                    # the loop closed under us: the front end is
                    # shutting down with this stream open.  The
                    # consumer is gone but the stream survives on its
                    # worker — a resume cursor re-attaches it through
                    # the next front end (POST /v1/resume)
                    return
                if ev.get("done") or "error" in ev:
                    return

        threading.Thread(target=_pump, daemon=True,
                         name=f"sse-pump-{gid}").start()
        while True:
            ev = await aq.get()
            writer.write(f"data: {json.dumps(ev)}\n\n".encode("utf-8"))
            await writer.drain()
            if ev.get("done") or "error" in ev:
                return
