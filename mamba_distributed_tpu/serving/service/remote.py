"""RemoteReplica: an EngineReplica duck-type backed by a worker socket.

The whole point of the service layer is that ``RequestRouter`` — the
placement, drain, failover-replay and tier-migration logic PRs 5–10
built and pinned — runs UNCHANGED in front of worker processes.  A
``RemoteReplica`` exposes the exact surface the router reads off an
in-process ``EngineReplica``:

  replica_id / role / state / accepting / alive / pending
  place_cost(request=None)      from the worker's last-reported stats
                                (load + page pressure; the prefix-cache
                                affinity probe is an O(prompt) host walk
                                that does not cross the wire — remote
                                placement is load-only)
  submit / step / drain / mark_dead
  engine.scheduler.depth, engine.hybrid, engine.page_pool.free_pages
  engine.metrics.summary(), engine.submit_migrated(...)
  engine.migrate_hook = hook    the router installs its in-process
                                migration closure here; the proxy's
                                setter rewires it as the wire callback
                                ``step()`` invokes when the worker
                                sends a migrate_offer

so ``RequestRouter(params=None, cfg, replicas=[RemoteReplica(...),
...])`` IS the cross-host fabric.

Failure semantics: a wire failure during ``submit``/``step`` marks the
replica wire-dead — ``alive`` flips False, the router stops stepping
it, and the heartbeat monitor (service/health.py) drives
``router.fail`` so every unfinished request replays on a survivor
(replay-cursor dedup keeps the merged stream no-loss/no-dup).  A
failed heartbeat ``ping`` only closes the socket — the next probe
reconnects (workers keep state across controller sessions), and only
``miss_threshold`` consecutive failures escalate to failover.  A step
TIMEOUT is treated as death, not slowness: resyncing a half-finished
step RPC could drop already-emitted tokens, and failover replay is the
path that provably loses nothing.
"""

from __future__ import annotations

import socket
import time

from mamba_distributed_tpu.serving.replica import REPLICA_ROLES, ReplicaState
from mamba_distributed_tpu.serving.service import wire


class _Shim:
    """Minimal tracked-request stand-in for router._migrate_from."""

    def __init__(self, request_id: int):
        self.request_id = request_id


class _SchedulerProxy:
    def __init__(self, rep: "RemoteReplica"):
        self._rep = rep

    @property
    def depth(self) -> int:
        return int(self._rep.stats.get("depth", 0))


class _PagePoolProxy:
    def __init__(self, rep: "RemoteReplica"):
        self._rep = rep

    @property
    def free_pages(self) -> int:
        return int(self._rep.stats.get("free_pages", 0))

    @property
    def num_pages(self) -> int:
        return int(self._rep.stats.get("num_pages", 0))

    @property
    def pages_in_use(self) -> int:
        return int(self._rep.stats.get("pages_in_use", 0))


class _MetricsProxy:
    def __init__(self, rep: "RemoteReplica"):
        self._rep = rep

    def summary(self) -> dict:
        return self._rep.summary()


class _EngineProxy:
    """The slice of ``ServingEngine`` the router touches, by RPC."""

    def __init__(self, rep: "RemoteReplica"):
        self._rep = rep
        self.scheduler = _SchedulerProxy(rep)
        self.page_pool = _PagePoolProxy(rep)
        self.metrics = _MetricsProxy(rep)

    @property
    def hybrid(self) -> bool:
        return bool(self._rep.info.get("hybrid"))

    @property
    def migrate_hook(self):
        return self._rep.on_migrate_offer

    @migrate_hook.setter
    def migrate_hook(self, hook) -> None:
        # the router's in-process closure is hook(tracked, package);
        # the wire callback receives (local_id, decoded snapshot) —
        # adapt so router._migrate_from runs verbatim
        rep = self._rep
        if hook is None:
            rep.on_migrate_offer = None
        else:
            rep.on_migrate_offer = (
                lambda local_id, snap: hook(_Shim(local_id), lambda: snap)
            )

    def submit_migrated(self, request, snapshot: dict, *,
                        source_replica: int | None = None) -> int:
        payload = self._rep._rpc("submit_migrated", {
            "request": wire.encode_request(request),
            "snapshot": wire.encode_tree(snapshot),
            "source_replica": source_replica,
        }, expect="submit_ack")
        return int(payload["request_id"])

    def park(self, request_id: int) -> tuple:
        """Wire v4: serialize one DECODE-resident stream on the worker
        into the replica-unbound PARK artifact (docs/SERVING.md
        "Durable sessions").  ValueError from the worker (not resident,
        speculative verify pending) is retriable; the returned
        ``(request, snapshot)`` is exactly the in-process
        ``engine.park`` pair after a wire round-trip."""
        payload = self._rep._rpc("park", {
            "request_id": int(request_id),
        }, expect="park_result")
        return (wire.decode_request(payload["request"]),
                wire.decode_tree(payload["snapshot"]))

    def resume_parked(self, request, snapshot: dict, *,
                      source_replica: int | None = None) -> int:
        """Wire v4: re-admit a PARK artifact on this worker (any
        replica works — the artifact is replica-unbound)."""
        payload = self._rep._rpc("resume_parked", {
            "request": wire.encode_request(request),
            "snapshot": wire.encode_tree(snapshot),
            "source_replica": source_replica,
        }, expect="submit_ack")
        return int(payload["request_id"])


class RemoteReplica:
    """One worker process, as the router's placement unit."""

    def __init__(self, replica_id: int, address: tuple[str, int], *,
                 role: str = "mixed", connect_timeout_s: float = 30.0,
                 rpc_timeout_s: float = 300.0, ping_timeout_s: float = 2.0):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        self.replica_id = replica_id
        self.address = address
        self.role = role
        self.rpc_timeout_s = rpc_timeout_s
        self.ping_timeout_s = ping_timeout_s
        self.state = ReplicaState.ACTIVE
        self.wire_dead = False
        self.stats: dict = {}
        self.info: dict = {}
        self.last_wire_error: str | None = None
        self.on_migrate_offer = None
        self.engine = _EngineProxy(self)
        self._offer_exc: Exception | None = None
        self._sock: socket.socket | None = None
        self._connect(deadline=time.monotonic() + connect_timeout_s)
        if self.role != self.info.get("role", self.role):
            raise ValueError(
                f"replica {replica_id}: connected worker reports role "
                f"{self.info.get('role')!r}, expected {role!r} — fabric "
                f"and worker disagree on the tier layout"
            )

    # ------------------------------------------------------------ transport

    def _connect(self, deadline: float | None = None) -> None:
        """(Re)connect and re-hello.  Workers keep replica state across
        controller sessions, so reconnecting resumes, not restarts."""
        last_err: Exception | None = None
        while True:
            try:
                self._sock = socket.create_connection(
                    self.address, timeout=self.ping_timeout_s
                )
                break
            except OSError as e:
                last_err = e
                self._sock = None
                if deadline is None or time.monotonic() >= deadline:
                    raise wire.WireError(
                        f"replica {self.replica_id}: cannot connect to "
                        f"worker at {self.address}: {last_err}"
                    ) from last_err
                time.sleep(0.05)
        # the hello is bounded tightly and NON-fatal: a wedged worker
        # mid-reconnect must neither freeze the controller loop for a
        # full rpc_timeout nor bypass the heartbeat miss threshold —
        # the OUTER call's fatality decides what a failure here means
        self.info = self._rpc("hello", {}, expect="hello",
                              timeout=min(self.rpc_timeout_s, 10.0),
                              fatal=False)
        self._update_stats(self.info.get("stats"))

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _wire_died(self, err: Exception) -> None:
        self.wire_dead = True
        self._close()
        self.last_wire_error = str(err)

    def _update_stats(self, stats: dict | None) -> None:
        if not stats:
            return
        self.stats = stats
        # the worker's lifecycle is authoritative for ACTIVE/DRAINING
        # (a SIGTERM'd worker self-drains); DEAD is the router's call
        if self.state is not ReplicaState.DEAD and stats.get("state"):
            self.state = ReplicaState(stats["state"])

    def _rpc(self, mtype: str, payload: dict, *, expect: str,
             timeout: float | None = None, fatal: bool = True) -> dict:
        """One request/response exchange.  ``migrate_offer`` sub-
        messages (only ever during ``step``) are dispatched inline.  On
        wire failure: ``fatal`` marks the replica wire-dead (failover
        replays everything — the no-loss path); non-fatal (heartbeat
        probes) just closes so the next probe reconnects."""
        if self.wire_dead or self.state is ReplicaState.DEAD:
            raise wire.WireError(
                f"replica {self.replica_id} is "
                f"{'wire-dead' if self.wire_dead else 'dead'}"
            )
        offer_exc: Exception | None = None
        try:
            if self._sock is None:
                self._connect()
            self._sock.settimeout(timeout or self.rpc_timeout_s)
            wire.send_msg(self._sock, mtype, payload)
            while True:
                rtype, rpayload = wire.recv_msg(self._sock)
                if rtype == "migrate_offer":
                    accepted = False
                    if self.on_migrate_offer is not None:
                        snap = wire.decode_tree(rpayload["snapshot"])
                        try:
                            accepted = bool(self.on_migrate_offer(
                                int(rpayload["request_id"]), snap))
                        except Exception as e:  # noqa: BLE001
                            # ack False FIRST — the worker is blocked on
                            # it and an unacked offer would wedge the
                            # step RPC; surface after the step closes
                            # (NOT raised here: a CANDIDATE replica's
                            # failure must not mark THIS socket dead)
                            offer_exc = e
                    wire.send_msg(self._sock, "migrate_ack",
                                  {"accepted": accepted})
                    continue
                if rtype == "error":
                    err_cls = (ValueError if rpayload.get("retriable")
                               else RuntimeError)
                    raise err_cls(
                        f"replica {self.replica_id} "
                        f"{rpayload.get('error_type', 'error')}: "
                        f"{rpayload.get('error')}"
                    )
                if rtype != expect:
                    raise wire.WireError(
                        f"replica {self.replica_id}: expected {expect!r} "
                        f"reply to {mtype!r}, got {rtype!r}"
                    )
                self._update_stats(rpayload.get("stats"))
                self._offer_exc = offer_exc
                return rpayload
        except (wire.WireError, socket.timeout, OSError) as e:
            if fatal:
                self._wire_died(e)
            else:
                self._close()
            raise wire.WireError(
                f"replica {self.replica_id} wire failure during "
                f"{mtype}: {e}"
            ) from e

    # --------------------------------------------------- EngineReplica face

    @property
    def accepting(self) -> bool:
        return self.state is ReplicaState.ACTIVE and not self.wire_dead

    @property
    def alive(self) -> bool:
        return self.state is not ReplicaState.DEAD and not self.wire_dead

    @property
    def pending(self) -> int:
        return int(self.stats.get("pending", 0)) if self.alive else 0

    def place_cost(self, request=None) -> float:
        """Load + hybrid page pressure from the last-reported stats —
        the in-process cost minus the prefix-affinity probe (an
        O(prompt) engine-side walk the wire deliberately skips) —
        minus the LoRA adapter-affinity discount when the worker's
        last stats report the request's adapter device-RESIDENT
        (serving/replica.ADAPTER_AFFINITY: one tenant's traffic
        converges on the workers already holding its factors)."""
        s = self.stats
        cap = max(1, int(s.get("capacity", 1)))
        load = (int(s.get("depth", 0)) + int(s.get("resident", 0))) / cap
        if s.get("hybrid") and s.get("num_pages"):
            load += int(s.get("pages_in_use", 0)) / int(s["num_pages"])
        adapter = (getattr(request, "adapter", None)
                   if request is not None else None)
        if adapter and adapter in (s.get("adapters_resident") or ()):
            from mamba_distributed_tpu.serving.replica import (
                ADAPTER_AFFINITY,
            )

            load -= ADAPTER_AFFINITY
        return load

    def adapters_registered(self) -> list:
        """Adapter names this worker can serve (from its last stats) —
        the front end's 404 gate reads it."""
        return list(self.stats.get("adapters_registered") or [])

    def load_adapter(self, name: str, factors: dict,
                     alpha: float | None = None) -> None:
        """Ship one adapter's (unscaled) factors to the worker
        (idempotent on an already-registered name).  NON-fatal on wire
        failure, like ping: a transient socket fault on a factor push
        must not condemn a healthy replica to failover — the caller
        sees the WireError and can retry or place elsewhere."""
        self._rpc("load_adapter", {
            "name": name,
            "factors": wire.encode_tree(factors),
            "alpha": alpha,
        }, expect="load_adapter_ack", fatal=False)

    def submit_tune(self, adapter: str, examples, steps: int | None = None
                    ) -> dict:
        """Ship one tenant's fine-tune job to this TRAINER-role worker
        (wire v6; serving/tuning/).  FATAL on wire failure, like
        ``submit``: an unacked tune job is in an unknown state, so the
        lane fails over rather than risking a silent double-train.
        Returns the job's status dict (``job_id`` included)."""
        payload = self._rpc("submit_tune", {
            "adapter": adapter,
            "examples": [[int(t) for t in ex] for ex in examples],
            "steps": steps,
        }, expect="tune_ack")
        return payload["status"]

    def tune_status(self, job_id: str) -> dict:
        """One tune job's lifecycle snapshot (wire v6).  NON-fatal,
        like ping: a status poll must not condemn a healthy lane."""
        payload = self._rpc("tune_status", {"job_id": job_id},
                            expect="tune_status_result", fatal=False)
        return payload["status"]

    def submit(self, request, force: bool = False) -> int:
        if not self.accepting and not force:
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state.value}, not "
                f"accepting placements"
            )
        payload = self._rpc("submit", {
            "request": wire.encode_request(request),
            "force": force,
        }, expect="submit_ack")
        return int(payload["request_id"])

    def step(self) -> list:
        """One remote engine iteration.  Wire failure mid-step returns
        the empty list with the replica marked wire-dead — the
        heartbeat monitor escalates to router.fail, and failover replay
        re-derives anything the lost step_result held."""
        if not self.alive:
            return []
        try:
            payload = self._rpc("step", {}, expect="step_result")
        except wire.WireError:
            return []
        exc, self._offer_exc = self._offer_exc, None
        if exc is not None:
            raise exc  # a migrate-offer callback bug, not a wire fault
        return [wire.decode_event(d) for d in payload["events"]]

    def drain(self, requeue: bool = False) -> list[int]:
        """Graceful retire; with ``requeue`` the worker withdraws its
        queued-but-unstarted requests and returns their local ids for
        the router to re-place (the rolling-restart path)."""
        if self.state is ReplicaState.ACTIVE:
            self.state = ReplicaState.DRAINING
        if not self.alive:
            return []
        try:
            payload = self._rpc("drain", {"requeue": requeue},
                                expect="drain_ack")
        except wire.WireError:
            return []
        return [int(i) for i in payload.get("withdrawn", [])]

    def mark_dead(self) -> None:
        self.state = ReplicaState.DEAD
        self._close()

    @property
    def boot_id(self) -> str | None:
        """The worker PROCESS's per-boot nonce (from hello): stamped
        into resume cursors so a cursor minted against a restarted
        worker's previous generation 410s instead of replaying
        whichever new request reused the same local id."""
        return self.info.get("boot_id")

    def replay(self, local_id: int, from_index: int = 0) -> dict | None:
        """SSE-resume replay across the wire (``EngineReplica.replay``
        shape): the worker's tokens-so-far for one stream, or None when
        the id is unknown there.  A wire failure reads as unknown — the
        front end then tells the client to resubmit rather than hang.
        NON-fatal (like ping, unlike submit/step): this is a read-only
        idempotent query a CLIENT triggers, so one transient socket
        failure must not condemn a healthy replica to failover — the
        socket just closes and the next RPC reconnects."""
        if not self.alive:
            return None
        try:
            payload = self._rpc("replay", {
                "request_id": int(local_id),
                "from_index": int(from_index),
            }, expect="replay_result", fatal=False)
        except wire.WireError:
            return None
        if not payload.get("found"):
            return None
        req = payload.get("request")
        return {
            "tokens": [int(t) for t in payload.get("tokens", [])],
            "done": bool(payload.get("done")),
            "finish_reason": payload.get("finish_reason"),
            "request": (wire.decode_request(req)
                        if req is not None else None),
        }

    # ----------------------------------------------------------- telemetry

    def ping(self) -> tuple[float, dict]:
        """Heartbeat probe: round-trip ms + fresh stats.  Non-fatal on
        failure (closes the socket; the next probe reconnects) — only
        the monitor's miss threshold escalates to failover."""
        t0 = time.perf_counter()
        payload = self._rpc("ping", {}, expect="pong",
                            timeout=self.ping_timeout_s, fatal=False)
        return (time.perf_counter() - t0) * 1000.0, payload.get("stats", {})

    def summary(self) -> dict:
        if not self.alive:
            return {}
        try:
            payload = self._rpc("summary", {}, expect="summary_result")
        except wire.WireError:
            return {}
        return payload.get("summary", {})

    def metrics_snapshot(self) -> dict | None:
        """The full wire-v5 ``summary_result`` payload — roll-up summary
        PLUS the raw latency-histogram bucket dicts and live stats the
        controller's ``GET /metrics`` Prometheus exposition renders.
        NON-fatal like ping: a scrape must never condemn a replica."""
        if not self.alive:
            return None
        try:
            return self._rpc("summary", {}, expect="summary_result",
                             fatal=False)
        except wire.WireError:
            return None

    def obs_pull(self, cursor: int = 0, limit: int = 4096) -> dict | None:
        """Wire v5: drain one page of the worker's in-memory span/record
        ring from ``cursor`` (see ``SpanTracer.ring_pull``).  Returns
        ``{records, cursor, dropped, boot_id}`` or None on wire failure.
        NON-fatal (the ping/replay pattern): telemetry collection must
        never mark a healthy replica wire-dead — a missed pull just
        resumes from the same cursor next interval, and the ring absorbs
        the gap (``dropped`` counts anything that aged out meanwhile)."""
        if not self.alive:
            return None
        try:
            return self._rpc("obs_pull", {
                "cursor": int(cursor),
                "limit": int(limit),
            }, expect="obs_pull_result", fatal=False)
        except wire.WireError:
            return None

    def shutdown(self) -> None:
        """Best-effort worker process exit (post-drain)."""
        try:
            self._rpc("shutdown", {}, expect="bye", fatal=False)
        except (wire.WireError, RuntimeError):
            pass
        self._close()
