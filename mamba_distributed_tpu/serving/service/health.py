"""Heartbeat-driven lifecycle for the cross-host fabric.

The in-process fabric already has the full ACTIVE/DRAINING/DEAD state
machine and the failover replay that keeps streams no-loss/no-dup
(serving/replica.py, serving/router.py) — what a multi-process fabric
adds is DETECTION: a worker process can die without anyone calling
``router.fail``.  The ``HeartbeatMonitor`` closes that loop:

  * every ``interval_ms`` it pings each remote replica (a ``ping``
    RPC); a reply stamps ``heartbeat_ms`` (round-trip) and refreshes
    the replica's load stats,
  * a failed probe counts a MISSED beat; ``miss_threshold`` consecutive
    misses — or a wire death already observed by ``submit``/``step`` —
    escalates to ``router.fail(replica_id)``, which requeues the dead
    worker's unfinished requests onto survivors where replay-cursor
    dedup keeps every consumer stream contiguous and duplicate-free,
  * every beat, miss, and lifecycle transition is emitted as a
    ``kind="serving_health"`` record on the obs stream
    (docs/OBSERVABILITY.md "Fabric health") — the records
    ``scripts/obs_report.py`` renders as the fabric-health table.

``rolling_drain`` is the rolling-restart primitive (docs/SERVING.md
runbook): drain one replica — queued-but-unstarted work requeues to
the survivors immediately, resident work finishes in place — wait for
it to empty, and only then move to the next, so a fleet restarts with
zero dropped requests and at most one replica's capacity offline.
"""

from __future__ import annotations

import time

from mamba_distributed_tpu.serving.replica import ReplicaState
from mamba_distributed_tpu.serving.service import wire


class HeartbeatMonitor:
    """Probe remote replicas; drive lifecycle transitions + records.

    Args:
      router: the ``RequestRouter`` owning the replicas — ``fail`` is
        called here so failover uses the exact replay path the
        in-process tests pin.
      interval_ms: per-replica probe spacing (``tick()`` itself can be
        called as often as the controller loop likes — probes are
        rate-limited internally).
      miss_threshold: consecutive missed beats before failover.
      emit: callback taking one record dict (already stamped with
        ``kind="serving_health"``); None drops records.  Wire it to
        ``obs.append_jsonl`` for the reportable stream.
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, router, *, interval_ms: float = 200.0,
                 miss_threshold: int = 3, emit=None, clock=time.monotonic):
        if miss_threshold < 1:
            raise ValueError("miss_threshold must be >= 1")
        self.router = router
        self.interval_s = interval_ms / 1000.0
        self.miss_threshold = miss_threshold
        self.emit = emit
        self.clock = clock
        self.missed: dict[int, int] = {}
        self.last_beat_at: dict[int, float] = {}
        self.last_rtt_ms: dict[int, float] = {}
        self._last_probe: dict[int, float] = {}
        self._last_state: dict[int, str] = {}
        self._failed: set[int] = set()

    # ------------------------------------------------------------- records

    def _emit(self, event: str, rep, **fields) -> None:
        if self.emit is None:
            return
        rec = {"kind": "serving_health", "t": time.time(), "event": event,
               "replica": rep.replica_id, "state": rep.state.value,
               "missed": self.missed.get(rep.replica_id, 0), **fields}
        self.emit(rec)

    # -------------------------------------------------------------- probing

    def tick(self) -> list[int]:
        """One monitor pass: observe lifecycle transitions, probe due
        replicas, escalate wire deaths / missed-beat thresholds to
        ``router.fail``.  Returns the replica ids failed over in this
        pass.  Safe to call every controller iteration."""
        failed = []
        now = self.clock()
        for rep in self.router.replicas:
            rid = rep.replica_id
            state = rep.state.value
            prev = self._last_state.get(rid)
            if prev is not None and prev != state:
                self._emit("lifecycle", rep, transition=f"{prev}->{state}")
            self._last_state[rid] = state
            if rep.state is ReplicaState.DEAD:
                continue
            if getattr(rep, "wire_dead", False):
                if self._fail(rep, reason="wire_dead"):
                    failed.append(rid)
                continue
            if not hasattr(rep, "ping"):
                continue  # in-process replica: no probe needed
            if now - self._last_probe.get(rid, -1e9) < self.interval_s:
                continue
            self._last_probe[rid] = now
            try:
                rtt_ms, _stats = rep.ping()
            except wire.WireError as e:
                self.missed[rid] = self.missed.get(rid, 0) + 1
                self._emit("missed", rep, error=str(e))
                if self.missed[rid] >= self.miss_threshold:
                    if self._fail(rep, reason="missed_beats"):
                        failed.append(rid)
                continue
            self.missed[rid] = 0
            self.last_beat_at[rid] = now
            self.last_rtt_ms[rid] = round(rtt_ms, 3)
            self._emit("beat", rep, heartbeat_ms=round(rtt_ms, 3))
        return failed

    def _fail(self, rep, *, reason: str) -> bool:
        """Escalate one dead worker to router failover (once)."""
        rid = rep.replica_id
        if rid in self._failed:
            return False
        try:
            moved = self.router.fail(rid)
        except RuntimeError as e:
            # no accepting survivor: record it loudly; the router's
            # stranded-request check surfaces the stall to the caller
            self._emit("failover_error", rep, reason=reason, error=str(e))
            self._failed.add(rid)
            return False
        self._failed.add(rid)
        self._emit("failover", rep, reason=reason, requeued=moved)
        return True

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """Per-replica health view (the /healthz payload)."""
        now = self.clock()
        out = {}
        for rep in self.router.replicas:
            rid = rep.replica_id
            out[rid] = {
                "state": rep.state.value,
                "role": rep.role,
                "pending": rep.pending,
                "missed": self.missed.get(rid, 0),
                "heartbeat_ms": self.last_rtt_ms.get(rid),
                "last_beat_s_ago": (
                    round(now - self.last_beat_at[rid], 3)
                    if rid in self.last_beat_at else None
                ),
                "wire_dead": bool(getattr(rep, "wire_dead", False)),
            }
        return out


def rolling_drain(router, controller=None, *, requeue_queued: bool = True,
                  poll_s: float = 0.02, timeout_s: float = 300.0):
    """Rolling-restart drain: one replica at a time — drain it (its
    queued-but-unstarted work requeues to the survivors), wait until it
    holds nothing, yield its id so the operator can restart it, then
    continue.  ``controller`` (service/server.FabricController) keeps
    the fabric stepping while we wait; without one the caller must be
    stepping the router itself."""
    for rep in list(router.replicas):
        if rep.state is ReplicaState.DEAD:
            continue
        if controller is not None:
            controller.call(
                lambda rid=rep.replica_id: router.drain(
                    rid, requeue_queued=requeue_queued)
            ).result(timeout_s)
        else:
            router.drain(rep.replica_id, requeue_queued=requeue_queued)
        deadline = time.monotonic() + timeout_s
        while rep.pending:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replica {rep.replica_id} still holds "
                    f"{rep.pending} request(s) after {timeout_s}s drain"
                )
            time.sleep(poll_s)
        yield rep.replica_id
