"""Versioned wire codec for the cross-host serving service.

One frame = a 4-byte big-endian length prefix + one UTF-8 JSON object:

  {"v": WIRE_VERSION, "type": "<message type>", "payload": {...}}

Everything the fabric ships between processes rides this one codec —
``GenerationRequest`` submissions (trace_id and priority preserved, so
a request's journey keeps one trace id and one admission class across
host boundaries), ``TokenEvent`` streams, heartbeat pings, replay
cursors, and the PR-10 migration artifact (the O(1) conv/SSM carry +
last logits + hybrid KV page contents + their int8 scales) — so the
schema has exactly one version number to negotiate and exactly one
place to evolve.  Strictly stdlib + numpy: no protobuf, no msgpack,
nothing the container doesn't already have.

Arrays are tagged dicts (``{"__nd__": dtype, "shape": [...], "data":
base64(tobytes)}``) and tuples are tagged (``{"__tuple__": [...]}``)
so an arbitrary carry pytree — nested dicts/lists/tuples of ndarrays,
bf16 and int8 included — survives JSON with its treedef AND its bytes
intact: ``decode_tree(encode_tree(x))`` is structurally identical to
``jax.device_get(x)``, which is what makes the wire-crossed migration
artifact bit-exact (tests/test_wire.py pins the round trip per layer
family).

Version policy: a decoder raises ``UnknownWireVersionError`` — a NAMED
error, never a hang or a silent misparse — for any frame whose ``v``
it does not speak; the worker replies with an ``error`` message carrying
the exception name before closing, so a version-skewed peer fails fast
with a readable reason (docs/SERVING.md "Deploying as a service").
"""

from __future__ import annotations

import base64
import json
import socket
import struct

import numpy as np

# bump ONLY on incompatible schema changes; additive payload fields are
# compatible (decoders ignore unknown keys).  v2: the worker RPC
# surface grew the ``replay`` op and token events grew SSE resume
# cursors (encode/decode_resume_token) — a v1 front end cannot drive
# the re-attach protocol, so the version negotiation (and every resume
# cursor, which embeds its schema version) fails the skew loudly
# through UnknownWireVersionError instead of half-working.  v3:
# multi-tenant LoRA — requests carry an ``adapter`` identity the
# engine VALIDATES (an older worker would silently serve the base
# model for an adapter request: wrong tokens, not a missing feature),
# and the worker RPC surface grew ``load_adapter`` (factor shipping
# host->worker); skew fails through the same named error.  v4: durable
# sessions — the worker RPC surface grew ``park`` (serialize a live
# stream into the replica-unbound park artifact and free its slot)
# and ``resume_parked`` (re-admit one, emitted tokens included); an
# older peer would drop the request's parked continuation on the
# floor, so park/resume against a v3 worker fails loudly through
# UnknownWireVersionError instead of replaying tokens the client
# already has.  v5: the live telemetry plane — the worker RPC surface
# grew ``obs_pull`` (cursor-resumable drain of the worker's in-memory
# span/record ring, sequence-numbered like the PR-5 replay cursors and
# invalidated across restarts by the same per-boot nonce), and the
# ``summary`` reply ships the full latency-histogram buckets + live
# stats the controller's GET /metrics renders; an older peer cannot
# ship its telemetry, so a mixed-version fabric would silently present
# a PARTIAL observability picture — exactly the failure a telemetry
# plane exists to prevent — and the skew fails loudly through
# UnknownWireVersionError instead.  v6: online per-tenant LoRA tuning
# (serving/tuning/) — the worker RPC surface grew ``submit_tune``
# (ship a tenant's token-id examples to a trainer-role worker; the
# trainer fine-tunes {A, B} against the frozen base and hot-registers
# the next adapter version) and ``tune_status`` (poll one job's
# lifecycle for the ``/v1/tune/<id>`` surface), and ``hello`` may
# advertise the new ``trainer`` role; a v5 peer would accept the
# tenant's examples and then never train — a silently dropped fine-
# tune, the worst kind of "success" — so tune RPCs against an older
# worker fail loudly through UnknownWireVersionError.
WIRE_VERSION = 6

# one frame's hard ceiling (a hybrid migration artifact is page-count
# sized — MBs, not GBs; anything bigger is a corrupt length prefix)
MAX_FRAME_BYTES = 1 << 30

_LEN = struct.Struct(">I")

# tag keys for the tree codec — reserved in payload dicts
_ND = "__nd__"
_TUPLE = "__tuple__"


class WireError(RuntimeError):
    """Transport-level failure: framing, EOF, or a malformed message."""


class WireClosedError(WireError):
    """The peer closed the connection (EOF mid-frame or between
    frames) — the worker-death signal failover keys on."""


class UnknownWireVersionError(WireError):
    """The frame's schema version is not one this codec speaks.  Named
    (never a hang): a version-skewed peer gets this back as an
    ``error`` message and the connection closes."""


# --------------------------------------------------------------- tree codec


def encode_array(a) -> dict:
    """One ndarray (or jax array — materialized via np.asarray) as a
    tagged JSON-safe dict; dtype string round-trips bf16/int8 via the
    ml_dtypes registry numpy already carries under jax."""
    a = np.asarray(a)
    return {_ND: str(a.dtype), "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16, float8_*) register with numpy on
        # import; jax depends on ml_dtypes so this is always present
        import ml_dtypes  # noqa: F401

        return np.dtype(name)


def decode_array(d: dict) -> np.ndarray:
    dtype = _np_dtype(d[_ND])
    raw = base64.b64decode(d["data"])
    return np.frombuffer(raw, dtype=dtype).reshape(d["shape"]).copy()


def encode_tree(obj):
    """Recursively encode a pytree of dicts/lists/tuples/ndarrays/
    scalars into JSON-safe form, preserving treedef (tuples tagged) and
    array bytes (``encode_array``)."""
    if isinstance(obj, dict):
        bad = [k for k in obj if k in (_ND, _TUPLE)]
        if bad:
            raise WireError(f"dict keys {bad} collide with codec tags")
        return {k: encode_tree(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {_TUPLE: [encode_tree(v) for v in obj]}
    if isinstance(obj, list):
        return [encode_tree(v) for v in obj]
    if isinstance(obj, np.ndarray) or type(obj).__name__ == "ArrayImpl":
        return encode_array(obj)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    # anything array-like that slipped through (jax tracer-free arrays,
    # memoryviews): materialize
    return encode_array(obj)


def decode_tree(obj):
    if isinstance(obj, dict):
        if _ND in obj:
            return decode_array(obj)
        if _TUPLE in obj:
            return tuple(decode_tree(v) for v in obj[_TUPLE])
        return {k: decode_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_tree(v) for v in obj]
    return obj


# ----------------------------------------------------- request/event codecs


def encode_request(request) -> dict:
    """A ``serving.GenerationRequest`` as a wire payload.  The sampling
    key is shipped RESOLVED (the raw uint32 pair ``resolve_key``
    derives) so seed-vs-key requests serialize identically to how the
    slot pool will store them; ``trace_id`` and ``priority`` ride along
    — the router's trace context and admission class survive the
    process boundary."""
    d = {
        "prompt_ids": encode_array(np.asarray(request.prompt_ids, np.int32)),
        "max_new_tokens": int(request.max_new_tokens),
        "top_k": int(request.top_k),
        "temperature": float(request.temperature),
        "eos_id": None if request.eos_id is None else int(request.eos_id),
        "seed": int(request.seed),
        "trace_id": request.trace_id,
        "priority": request.priority,
        "adapter": getattr(request, "adapter", None),
    }
    if getattr(request, "queue_deadline_ms", None) is not None:
        # stamped only when set: deadline-less requests serialize
        # byte-identically to the pre-admission wire
        d["queue_deadline_ms"] = float(request.queue_deadline_ms)
    if request.key is not None:
        d["key"] = encode_array(np.asarray(request.resolve_key()))
    return d


def decode_request(d: dict):
    from mamba_distributed_tpu.serving.scheduler import GenerationRequest

    key = decode_array(d["key"]) if d.get("key") is not None else None
    return GenerationRequest(
        prompt_ids=decode_array(d["prompt_ids"]),
        max_new_tokens=d["max_new_tokens"],
        top_k=d["top_k"],
        temperature=d["temperature"],
        eos_id=d.get("eos_id"),
        seed=d.get("seed", 0),
        key=key,
        trace_id=d.get("trace_id"),
        priority=d.get("priority"),
        adapter=d.get("adapter"),
        queue_deadline_ms=d.get("queue_deadline_ms"),
    )


def encode_request_tree(request) -> dict:
    """A ``GenerationRequest`` as a PLAIN pytree — raw ndarrays, no
    codec tags — the form that nests INSIDE a larger ``encode_tree``
    payload (the durable-session PARK frame stores the request next to
    its snapshot this way; ``encode_request`` output cannot nest there,
    its tagged arrays collide with the tree codec's own tags)."""
    d = {
        "prompt_ids": np.asarray(request.prompt_ids, np.int32),
        "max_new_tokens": int(request.max_new_tokens),
        "top_k": int(request.top_k),
        "temperature": float(request.temperature),
        "eos_id": None if request.eos_id is None else int(request.eos_id),
        "seed": int(request.seed),
        "trace_id": request.trace_id,
        "priority": request.priority,
        "adapter": getattr(request, "adapter", None),
    }
    if getattr(request, "queue_deadline_ms", None) is not None:
        # same conditional stamp as encode_request: park frames of
        # deadline-less requests stay byte-identical
        d["queue_deadline_ms"] = float(request.queue_deadline_ms)
    if request.key is not None:
        d["key"] = np.asarray(request.resolve_key())
    return d


def decode_request_tree(d: dict):
    """Invert ``encode_request_tree`` AFTER the tree codec has already
    restored the arrays (a session frame's ``decode_session_frame`` /
    a payload's ``decode_tree``)."""
    from mamba_distributed_tpu.serving.scheduler import GenerationRequest

    key = d.get("key")
    return GenerationRequest(
        prompt_ids=np.asarray(d["prompt_ids"], np.int32),
        max_new_tokens=d["max_new_tokens"],
        top_k=d["top_k"],
        temperature=d["temperature"],
        eos_id=d.get("eos_id"),
        seed=d.get("seed", 0),
        key=None if key is None else np.asarray(key),
        trace_id=d.get("trace_id"),
        priority=d.get("priority"),
        adapter=d.get("adapter"),
        queue_deadline_ms=d.get("queue_deadline_ms"),
    )


def encode_event(ev) -> dict:
    return {"request_id": int(ev.request_id), "token": int(ev.token),
            "index": int(ev.index), "done": bool(ev.done),
            "finish_reason": ev.finish_reason}


def decode_event(d: dict):
    from mamba_distributed_tpu.serving.scheduler import TokenEvent

    return TokenEvent(d["request_id"], d["token"], d["index"], d["done"],
                      d.get("finish_reason"))


# ------------------------------------------------------ SSE resume cursors


def encode_resume_token(replica_id: int, request_id: int,
                        index: int, boot_id: str | None = None) -> str:
    """Opaque SSE resume cursor (docs/SERVING.md "Deploying as a
    service"): enough for a RESTARTED front end to re-attach an
    in-flight stream — which worker holds it (``replica_id``), the
    worker-local request id, the next token index the client expects,
    and the worker's per-boot nonce (``boot_id``, from its hello).
    Carries the wire schema version so a cursor minted by a different
    service generation fails decoding with the NAMED
    ``UnknownWireVersionError`` instead of replaying garbage; the boot
    nonce catches the subtler skew — a RESTARTED worker reuses local
    request ids from 0, and without the nonce a stale cursor would
    silently replay a DIFFERENT request's stream."""
    body = json.dumps(
        {"v": WIRE_VERSION, "replica": int(replica_id),
         "request": int(request_id), "index": int(index),
         **({"boot": str(boot_id)} if boot_id else {})},
        separators=(",", ":"),
    ).encode("utf-8")
    return base64.urlsafe_b64encode(body).decode("ascii")


def decode_resume_token(token: str) -> tuple[int, int, int, str | None]:
    """Inverse of ``encode_resume_token`` -> (replica_id, request_id,
    next_index, boot_id-or-None).  Raises ``UnknownWireVersionError``
    on a version-skewed cursor and ``WireError`` on anything malformed
    — never a silent misparse."""
    try:
        obj = json.loads(base64.urlsafe_b64decode(token.encode("ascii")))
    except Exception as e:  # noqa: BLE001 — any decode failure is one error
        raise WireError(f"malformed resume token: {e}") from e
    if not isinstance(obj, dict):
        raise WireError(f"malformed resume token payload: {obj!r}")
    v = obj.get("v")
    if v != WIRE_VERSION:
        raise UnknownWireVersionError(
            f"resume token schema version {v!r} is not supported (this "
            f"service speaks version {WIRE_VERSION}); resubmit the "
            f"request instead (same seed => same tokens)"
        )
    try:
        out = int(obj["replica"]), int(obj["request"]), int(obj["index"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed resume token fields: {e}") from e
    if any(v < 0 for v in out):
        # negative ids/indices must never reach Python indexing (a -1
        # replica would silently wrap to the LAST replica's streams)
        raise WireError(f"malformed resume token fields: negative {out}")
    boot = obj.get("boot")
    if boot is not None and not isinstance(boot, str):
        raise WireError(f"malformed resume token boot id: {boot!r}")
    return out + (boot,)


# ------------------------------------------------------------------ framing


def encode_msg(mtype: str, payload: dict | None = None) -> bytes:
    body = json.dumps(
        {"v": WIRE_VERSION, "type": mtype, "payload": payload or {}},
        separators=(",", ":"),
    ).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise WireError(f"frame of {len(body)} bytes exceeds "
                        f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return _LEN.pack(len(body)) + body


def decode_msg(body: bytes) -> tuple[str, dict]:
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed wire frame: {e}") from e
    v = obj.get("v")
    if v != WIRE_VERSION:
        raise UnknownWireVersionError(
            f"wire schema version {v!r} is not supported (this codec "
            f"speaks version {WIRE_VERSION}); upgrade the older peer"
        )
    mtype = obj.get("type")
    if not isinstance(mtype, str):
        raise WireError(f"wire frame has no message type: {obj!r}")
    return mtype, obj.get("payload") or {}


# hard cap on waiting out a half-received frame (a peer frozen mid-send):
# long enough for any loopback/TCP burst, short enough that a wedged
# peer reads as dead rather than hanging the caller forever
MID_FRAME_STALL_S = 30.0


def _recv_exact(sock: socket.socket, n: int,
                mid_frame: bool = False) -> bytes:
    """Read exactly n bytes; WireClosedError on EOF.  socket.timeout
    propagates ONLY between frames (heartbeat probes and the worker's
    poll loop use it as the no-message signal) — once a frame's first
    bytes have arrived the rest is in flight, so a mid-frame timeout
    keeps reading instead of tearing the stream out of sync (a large
    migration artifact easily straddles a short poll timeout)."""
    import time as _time

    buf = bytearray()
    stall_deadline = None
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
            stall_deadline = None
        except socket.timeout:
            if not buf and not mid_frame:
                raise
            now = _time.monotonic()
            if stall_deadline is None:
                stall_deadline = now + MID_FRAME_STALL_S
            elif now >= stall_deadline:
                raise WireClosedError(
                    f"peer stalled mid-frame for {MID_FRAME_STALL_S}s "
                    f"({len(buf)}/{n} bytes)"
                )
            continue
        if not chunk:
            raise WireClosedError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock: socket.socket, mtype: str,
             payload: dict | None = None) -> None:
    try:
        sock.sendall(encode_msg(mtype, payload))
    except OSError as e:
        raise WireClosedError(f"send failed: {e}") from e


def recv_msg(sock: socket.socket) -> tuple[str, dict]:
    try:
        (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
        if n > MAX_FRAME_BYTES:
            raise WireError(f"frame length {n} exceeds MAX_FRAME_BYTES")
        # the header is consumed: the body read is mid-frame by
        # definition, however many bytes of it have arrived yet
        return decode_msg(_recv_exact(sock, n, mid_frame=True))
    except socket.timeout:
        raise
    except OSError as e:
        raise WireClosedError(f"recv failed: {e}") from e
