"""Replica worker: one ``EngineReplica`` behind a loopback/TCP listener.

One worker process = one replica of the serving fabric (docs/SERVING.md
"Deploying as a service").  The fabric front end (service/server.py)
connects one control socket and drives the replica by RPC — every
message is a request/response pair of wire.py frames, so the whole
fabric stays as deterministic as the in-process router the tests pin
parity against:

  hello            -> hello {replica_id, role, capacity, hybrid, ...}
  submit           -> submit_ack {request_id, stats} | error
  submit_migrated  -> submit_ack | error {retriable}  (wire-crossed
                      PR-10 migration artifact -> engine.submit_migrated)
  park             -> park_result {request, snapshot} | error (wire v4:
                      evict one DECODE-resident stream into the PARK
                      artifact — the migration artifact + emitted
                      tokens; docs/SERVING.md "Durable sessions")
  resume_parked    -> submit_ack | error {retriable}  (wire v4: re-admit
                      a PARK artifact on ANY replica; the emitted-token
                      prefix rides the artifact so the stream CONTINUES)
  step             -> migrate_offer* -> step_result {events, stats}
  ping             -> pong {stats}              (heartbeat probe)
  drain            -> drain_ack {withdrawn, stats}
  summary          -> summary_result {summary, histograms, stats, role}
                      (histograms: full latency bucket dicts — what the
                      controller's GET /metrics renders)
  obs_pull         -> obs_pull_result {records, cursor, dropped,
                      boot_id}  (wire v5: cursor-resumable drain of the
                      engine tracer's in-memory span/record ring — the
                      controller merges every worker's into one fabric
                      stream with zero remote file access; a cursor
                      from a previous worker boot is detected via
                      boot_id and restarted at 0)
  submit_tune      -> tune_ack {job_id, status} | error  (wire v6: a
                      tenant's fine-tune job for a TRAINER-role worker
                      — token-id examples in, online LoRA training on
                      the frozen base; serving/tuning/)
  tune_status      -> tune_status_result {status} | error  (wire v6:
                      poll one tune job's lifecycle for /v1/tune/<id>)
  shutdown         -> bye (process exits)

``step`` is the one RPC with sub-messages: while the engine steps, a
prefill-role replica's ``migrate_hook`` may fire — the worker sends a
``migrate_offer`` carrying the serialized artifact and BLOCKS for the
controller's ``migrate_ack`` (the controller places the artifact on a
decode worker over that worker's own socket meanwhile), then the step
finishes and ``step_result`` closes the RPC.  True ack => this engine
frees the slot and pages (serving/engine._migrate_ready); False =>
mixed-mode fallback, decode here.

Lifecycle: SIGTERM (scripts/serve_worker.py installs the handler)
marks the replica DRAINING — no new placements; queued-but-unstarted
work is the controller's to withdraw — and the process exits once
nothing is resident.  If no controller is connected at SIGTERM the
worker self-steps to drain (tokens go nowhere; it is a shutdown, not a
stream).  A controller vanishing mid-run is NOT fatal: the worker
keeps its state and re-accepts, so a restarted front end finds the
replica where it left it.

Every serving_tick/request record the engine emits lands in the
worker's OWN jsonl stream (``--jsonl``), stamped with its replica id;
span streams (``--spans``) merge with the server's via
``scripts/trace_export.py`` into one cross-process timeline.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import time

from mamba_distributed_tpu.serving.service import wire

# message types the session dispatcher understands (anything else is a
# named error back to the peer, never a hang)
_HANDLED = ("hello", "submit", "submit_migrated", "park", "resume_parked",
            "step", "ping", "drain", "replay", "load_adapter", "summary",
            "obs_pull", "submit_tune", "tune_status", "shutdown")


# ------------------------------------------------------------- config I/O


def config_to_json(cfg, path: str) -> None:
    """Serialize a ModelConfig for a worker process to rebuild —
    identical config in every process is half the parity contract (the
    other half is the shared param seed)."""
    d = {f.name: getattr(cfg, f.name)
         for f in dataclasses.fields(cfg) if f.init}
    d = {k: (list(v) if isinstance(v, tuple) else v) for k, v in d.items()}
    with open(path, "w") as f:
        json.dump(d, f)


def config_from_json(path: str):
    from mamba_distributed_tpu.config import ModelConfig

    with open(path) as f:
        d = json.load(f)
    # JSON has no tuples; every sequence-valued config field is a tuple
    # (attn_layer_idx, ...) so the coercion is lossless
    d = {k: (tuple(v) if isinstance(v, list) else v) for k, v in d.items()}
    return ModelConfig(**d)


# ---------------------------------------------------------------- worker


class WorkerServer:
    """One replica behind a TCP listener (see module docstring).

    Args:
      replica: the ``serving.EngineReplica`` to serve.  If its role is
        "prefill" and the config's ``disagg_prompt_threshold`` > 0 the
        worker installs the wire-level migration hook on its engine —
        prefill-complete slots are offered to the controller instead of
        decoded here (the cross-host version of the hook
        serving/router.py installs in-process).
      host/port: listen address; port 0 binds an ephemeral port (read
        ``.port`` after construction — scripts/serve_worker.py prints
        it in its READY line).
      poll_s: accept/recv poll granularity — how often the loop checks
        the SIGTERM flag between frames.
    """

    def __init__(self, replica, host: str = "127.0.0.1", port: int = 0,
                 *, poll_s: float = 0.05, tuning=None):
        self.replica = replica
        # online-tuning plane (wire v6): a trainer-role worker serves
        # submit_tune/tune_status out of its TuningService — passed
        # explicitly or found on the replica (TrainerReplica.service)
        self.tuning = (tuning if tuning is not None
                       else getattr(replica, "service", None))
        self.poll_s = poll_s
        self._term = False
        self._shutdown = False
        self._conn: socket.socket | None = None
        self._lsock = socket.create_server((host, port))
        self._lsock.settimeout(poll_s)
        self.host, self.port = self._lsock.getsockname()[:2]
        # per-PROCESS boot nonce, advertised in hello and embedded in
        # every SSE resume cursor: engine-local request ids restart at
        # 0 when the worker process restarts, so a cursor minted
        # against a previous worker generation must 410 ("resubmit")
        # at re-attach instead of silently replaying whichever NEW
        # request landed on the same local id (a cross-stream token
        # leak).  uuid4 — uniqueness per boot, not secrecy.
        import uuid

        self.boot_id = uuid.uuid4().hex[:16]
        eng = replica.engine
        if replica.role == "prefill" and eng.cfg.disagg_prompt_threshold > 0:
            eng.migrate_hook = self._offer_migration

    # ------------------------------------------------------------- control

    def request_term(self) -> None:
        """SIGTERM path: stop accepting (DRAINING), exit once empty.
        Queued-but-unstarted work stays withdrawable by the controller
        until the engine admits it."""
        self._term = True
        self.replica.drain()

    def _stats(self) -> dict:
        eng = self.replica.engine
        s = {
            "depth": eng.scheduler.depth,
            "resident": len(eng._slots),
            "capacity": eng.capacity,
            "pending": self.replica.pending,
            "state": self.replica.state.value,
            "hybrid": eng.hybrid,
        }
        if eng.hybrid:
            s["free_pages"] = eng.page_pool.free_pages
            s["num_pages"] = eng.page_pool.num_pages
            s["pages_in_use"] = eng.page_pool.pages_in_use
        if getattr(eng, "lora", False):
            # multi-tenant LoRA (serving/adapters.py): which adapters
            # this worker can serve at all (registered) and which are
            # device-RESIDENT right now (the controller's adapter-
            # affinity placement term and 404 gate read these)
            s["adapters_registered"] = eng.adapters.names()
            s["adapters_resident"] = eng.adapter_cache.resident_names()
        return s

    # ------------------------------------------------------------ migration

    # how long a prefill-complete slot waits for the controller's
    # migrate_ack: the controller is re-placing the artifact on a
    # decode worker over ANOTHER socket (encode + submit_migrated RPC)
    # — ms on loopback, but it must never race the session's short
    # poll timeout: a falsely-timed-out decline would both
    # double-execute the request AND leave the late ack frame in the
    # stream to desync the next RPC
    MIGRATE_ACK_TIMEOUT_S = 60.0

    def _offer_migration(self, tracked, package) -> bool:
        """The engine's ``migrate_hook``, wire edition: serialize the
        artifact, offer it to the controller, block for the ack (the
        session timeout is RAISED to ``MIGRATE_ACK_TIMEOUT_S`` for the
        wait — see above — and restored after).  No controller
        connected (or a wire failure mid-offer) declines — mixed-mode
        fallback, the slot decodes here; never a stall."""
        if self._conn is None:
            return False
        snap = package()
        try:
            wire.send_msg(self._conn, "migrate_offer", {
                "request_id": tracked.request_id,
                "snapshot": wire.encode_tree(snap),
                "stats": self._stats(),
            })
            # the controller replies migrate_ack before anything else
            # on this socket (the step RPC is still open)
            self._conn.settimeout(self.MIGRATE_ACK_TIMEOUT_S)
            try:
                mtype, payload = wire.recv_msg(self._conn)
            finally:
                self._conn.settimeout(self.poll_s)
        except (wire.WireError, socket.timeout, OSError):
            return False
        if mtype != "migrate_ack":
            return False
        return bool(payload.get("accepted"))

    # ------------------------------------------------------------- serving

    def serve_forever(self) -> None:
        """Accept loop: one control session at a time; SIGTERM drains
        and exits once nothing is resident."""
        try:
            while not self._shutdown:
                try:
                    conn, _ = self._lsock.accept()
                except socket.timeout:
                    self._idle_tick()
                    continue
                try:
                    self._session(conn)
                finally:
                    self._conn = None
                    conn.close()
        finally:
            self._lsock.close()

    def _idle_tick(self) -> None:
        """No controller connected: honor SIGTERM by self-draining
        (resident work steps to completion; its tokens have no
        consumer — this is shutdown, not serving)."""
        if not self._term:
            return
        if self.replica.pending:
            self.replica.step()
        if self.replica.pending == 0:
            self._shutdown = True

    def _session(self, conn: socket.socket) -> None:
        conn.settimeout(self.poll_s)
        self._conn = conn
        while not self._shutdown:
            try:
                mtype, payload = wire.recv_msg(conn)
            except socket.timeout:
                if self._term and self.replica.pending == 0:
                    self._shutdown = True
                continue
            except wire.UnknownWireVersionError as e:
                # the NAMED version error: reply and close, never hang
                try:
                    wire.send_msg(conn, "error", {
                        "error": str(e),
                        "error_type": type(e).__name__,
                        "retriable": False,
                    })
                except wire.WireError:
                    pass
                return
            except wire.WireError:
                return  # controller went away; re-accept
            try:
                self._dispatch(conn, mtype, payload)
            except wire.WireError:
                return

    def _dispatch(self, conn: socket.socket, mtype: str,
                  payload: dict) -> None:
        rep = self.replica
        if mtype == "hello":
            eng = rep.engine
            wire.send_msg(conn, "hello", {
                "v": wire.WIRE_VERSION,
                "replica_id": rep.replica_id,
                "role": rep.role,
                "capacity": eng.capacity,
                "hybrid": eng.hybrid,
                "boot_id": self.boot_id,
                "stats": self._stats(),
            })
        elif mtype == "submit":
            try:
                request = wire.decode_request(payload["request"])
                local_id = rep.submit(request,
                                      force=bool(payload.get("force")))
            except Exception as e:  # noqa: BLE001 — serialized back
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "submit_ack", {
                "request_id": local_id, "stats": self._stats(),
            })
        elif mtype == "submit_migrated":
            try:
                request = wire.decode_request(payload["request"])
                snap = wire.decode_tree(payload["snapshot"])
                local_id = rep.engine.submit_migrated(
                    request, snap,
                    source_replica=payload.get("source_replica"),
                )
            except Exception as e:  # noqa: BLE001
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "submit_ack", {
                "request_id": local_id, "stats": self._stats(),
            })
        elif mtype == "park":
            # wire v4: serialize one DECODE-resident stream into the
            # replica-unbound PARK artifact and free its slot/pages.
            # ValueError (not resident / verify pending) is retriable —
            # the controller may re-ask after the next step.
            try:
                request, snap = rep.engine.park(
                    int(payload.get("request_id", -1))
                )
            except Exception as e:  # noqa: BLE001 — serialized back
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "park_result", {
                "request": wire.encode_request(request),
                "snapshot": wire.encode_tree(snap),
                "stats": self._stats(),
            })
        elif mtype == "resume_parked":
            # wire v4: re-admit a PARK artifact here — same restore
            # path as a migration (zero prefill compute), plus the
            # artifact's emitted-token prefix so the stream CONTINUES
            try:
                request = wire.decode_request(payload["request"])
                snap = wire.decode_tree(payload["snapshot"])
                local_id = rep.engine.submit_migrated(
                    request, snap,
                    source_replica=payload.get("source_replica"),
                )
            except Exception as e:  # noqa: BLE001
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "submit_ack", {
                "request_id": local_id, "stats": self._stats(),
            })
        elif mtype == "step":
            events = rep.step()  # may emit migrate_offer sub-messages
            wire.send_msg(conn, "step_result", {
                "events": [wire.encode_event(ev) for ev in events],
                "stats": self._stats(),
            })
        elif mtype == "ping":
            wire.send_msg(conn, "pong", {
                "stats": self._stats(), "t": time.time(),
            })
        elif mtype == "drain":
            withdrawn = rep.drain(requeue=bool(payload.get("requeue")))
            wire.send_msg(conn, "drain_ack", {
                "withdrawn": withdrawn, "stats": self._stats(),
            })
        elif mtype == "replay":
            # SSE resume (docs/SERVING.md "Deploying as a service"): a
            # restarted front end re-attaches an in-flight stream.  The
            # worker kept the request and its emitted tokens across the
            # controller gap (nothing steps while no controller is
            # connected, so nothing is ever lost in between).
            info = rep.replay(int(payload.get("request_id", -1)),
                              int(payload.get("from_index", 0)))
            if info is None:
                wire.send_msg(conn, "replay_result", {"found": False})
            else:
                out = {
                    "found": True,
                    "tokens": [int(t) for t in info["tokens"]],
                    "done": bool(info["done"]),
                    "finish_reason": info["finish_reason"],
                }
                if info.get("request") is not None:
                    out["request"] = wire.encode_request(info["request"])
                wire.send_msg(conn, "replay_result", out)
        elif mtype == "load_adapter":
            # multi-tenant LoRA factor shipping (host -> worker): the
            # controller pushes a named adapter's (unscaled) factors so
            # a worker that never preloaded it can serve its requests
            # (and a migration target can re-pin them).  Idempotent on
            # an already-registered name — re-shipping the same
            # identity is a no-op ack, never an error (every submit
            # may race a concurrent load of the same adapter).
            try:
                eng = rep.engine
                if not getattr(eng, "lora", False):
                    raise ValueError(
                        "this worker serves the base model only "
                        "(cfg.lora_max_adapters=0); re-deploy with "
                        "LoRA serving on to load adapters"
                    )
                name = payload["name"]
                if name not in eng.adapters:
                    eng.adapters.register(
                        name, wire.decode_tree(payload["factors"]),
                        alpha=payload.get("alpha"),
                    )
            except Exception as e:  # noqa: BLE001 — serialized back
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "load_adapter_ack", {
                "stats": self._stats(),
            })
        elif mtype == "summary":
            from mamba_distributed_tpu.obs import jsonable

            # the full latency-histogram bucket dicts + live stats ride
            # next to the roll-up (wire v5): the controller's
            # GET /metrics needs bucket counts, not p95 point estimates
            wire.send_msg(conn, "summary_result", {
                "summary": jsonable(rep.engine.metrics.summary()),
                "histograms": rep.engine.metrics.histogram_dicts(),
                "stats": self._stats(),
                "role": rep.role,
            })
        elif mtype == "obs_pull":
            # wire v5: cursor-resumable drain of the engine tracer's
            # in-memory span/record ring (obs/tracer.py ring_pull) —
            # the controller's background drain merges every worker's
            # page into ONE fabric stream, so trace_export/obs_report
            # see a live multi-host fabric with zero remote file
            # access.  boot_id rides every reply: a controller holding
            # a cursor from a previous worker boot restarts at 0
            # instead of silently mis-resuming into a fresh ring.
            page = rep.engine.tracer.ring_pull(
                int(payload.get("cursor", 0)),
                int(payload.get("limit", 4096)),
            )
            wire.send_msg(conn, "obs_pull_result", {
                "records": page["records"],
                "cursor": page["cursor"],
                "dropped": page["dropped"],
                "boot_id": self.boot_id,
            })
        elif mtype == "submit_tune":
            # wire v6: one tenant's fine-tune job lands on this
            # TRAINER-role worker (serving/tuning/) — token-id examples
            # ride as plain JSON lists.  Validation fails loudly at
            # this boundary (TuneError — not retriable: the payload
            # itself is wrong), and a worker without a tuning service
            # refuses rather than silently dropping the fine-tune.
            try:
                if self.tuning is None:
                    raise ValueError(
                        f"this worker has no tuning service (role "
                        f"{rep.role!r}); submit tune jobs to a "
                        f"trainer-role worker"
                    )
                job = self.tuning.submit(
                    payload["adapter"], payload["examples"],
                    payload.get("steps"),
                )
            except Exception as e:  # noqa: BLE001 — serialized back
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "tune_ack", {
                "job_id": job.job_id, "status": job.status(),
                "stats": self._stats(),
            })
        elif mtype == "tune_status":
            # wire v6: one job's lifecycle snapshot (the /v1/tune/<id>
            # poll surface).  Unknown ids are a named TuneError.
            try:
                if self.tuning is None:
                    raise ValueError(
                        f"this worker has no tuning service (role "
                        f"{rep.role!r})"
                    )
                status = self.tuning.status(payload["job_id"])
            except Exception as e:  # noqa: BLE001 — serialized back
                wire.send_msg(conn, "error", {
                    "error": str(e), "error_type": type(e).__name__,
                    "retriable": isinstance(e, ValueError),
                })
                return
            wire.send_msg(conn, "tune_status_result", {
                "status": status, "stats": self._stats(),
            })
        elif mtype == "shutdown":
            wire.send_msg(conn, "bye", {})
            self._shutdown = True
        else:
            wire.send_msg(conn, "error", {
                "error": f"unknown message type {mtype!r} (this worker "
                         f"handles {_HANDLED})",
                "error_type": "UnknownMessageType",
                "retriable": False,
            })
