"""Cross-host serving service: the deployable shape of the fabric.

Everything below ``serving/`` up to PR 12 is a LIBRARY — router,
replica lifecycle, failover replay, tier migration — entered by a
Python call in one process.  This package deploys it (docs/SERVING.md
"Deploying as a service"):

  wire      versioned stdlib wire codec: requests, token events,
            replay cursors, the PR-10 migration artifact (carry +
            logits + KV pages + int8 scales) across host boundaries
  worker    one EngineReplica behind a TCP listener; one process per
            replica (scripts/serve_worker.py), SIGTERM -> drain
  remote    RemoteReplica: the EngineReplica duck-type that lets
            RequestRouter run UNCHANGED over worker processes
  server    FabricController (the router's thread) + the asyncio
            HTTP/SSE front end: POST /v1/generate streams tokens,
            /healthz, /drain/<replica>, /metrics-summary
            (scripts/serve_fabric.py)
  health    HeartbeatMonitor: probes drive the existing ACTIVE/
            DRAINING/DEAD lifecycle — a dead worker triggers the PR-5
            failover replay over the wire; rolling_drain is the
            restart runbook primitive
  client    stdlib HTTP/SSE client (tests + bench --service)

The engine/tick/kernel layers are untouched: a remote stream is the
same pure function of (prompt, key) as a local one, which is why the
service keeps the bit-parity pins (tests/test_service.py diffs
wire-served streams — including across a worker SIGKILL and a
wire-crossed migration — against solo ``generate()``).
"""

from mamba_distributed_tpu.serving.service.wire import (
    MAX_FRAME_BYTES,
    WIRE_VERSION,
    UnknownWireVersionError,
    WireClosedError,
    WireError,
    decode_array,
    decode_event,
    decode_msg,
    decode_request,
    decode_tree,
    encode_array,
    encode_event,
    encode_msg,
    encode_request,
    encode_tree,
    recv_msg,
    send_msg,
)

__all__ = [
    "MAX_FRAME_BYTES",
    "WIRE_VERSION",
    "UnknownWireVersionError",
    "WireClosedError",
    "WireError",
    "decode_array",
    "decode_event",
    "decode_msg",
    "decode_request",
    "decode_tree",
    "encode_array",
    "encode_event",
    "encode_msg",
    "encode_request",
    "encode_tree",
    "recv_msg",
    "send_msg",
]
