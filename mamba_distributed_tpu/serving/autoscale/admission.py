"""Admission control: router-level load shedding for the serving fabric.

Under sustained overload an infinite-patience queue destroys goodput
twice: every queued request eventually blows its latency target (the
tokens still get generated — they are just worthless by the time they
arrive), and the work spent on those doomed requests starves the
requests that could still have met theirs.  The fix is old queueing
theory: reject FAST at the front door once the queue implies a wait the
request will not tolerate, so capacity is spent only on requests that
can still attain the SLO (the ``overload_shed_cpu`` bench row measures
exactly this — shedding-on goodput strictly above shedding-off at 2x
offered load).

``AdmissionController`` gates ``RequestRouter.submit`` (the fabric's
ONE front door — failover re-placement, drain requeue, migration and
parked-session resume all bypass it by construction, so an admitted
request is never shed mid-flight):

  * **queue-depth cap**: fabric-wide queued-but-unstarted requests at
    or above ``queue_cap`` reject immediately — the coarse valve that
    bounds queue memory and worst-case drain time no matter what the
    per-request deadlines say;
  * **queue-deadline**: the request's ``queue_deadline_ms`` (or the
    fabric default) against the estimated wait-for-a-slot; a request
    that would blow its deadline is rejected NOW rather than timed out
    later.

Rejections raise the named ``AdmissionRejected`` carrying a
``retry_after_s`` hint (HTTP 429 + Retry-After on the front end —
serving/service/server.py) — never a silent drop, never a hang.

The wait estimate is deliberately simple and host-only: requests ahead
of this one admit in waves of ``capacity``, each wave holding a slot
for ``service_ms`` (an EWMA the owner feeds via ``observe_service_ms``
— the bench calibrates it from a closed-loop pass, the service from
finished-request records — with a configured prior before any
observation).  An estimator that is wrong by 2x still sheds the right
requests under real overload, because at 2x offered load the queue
grows without bound and every estimate crosses every deadline soon.
"""

from __future__ import annotations


class AdmissionRejected(RuntimeError):
    """A request the fabric refused at the front door (shed, not
    failed): the queue-depth cap is hit or the estimated queue wait
    blows the request's deadline.  Carries the machine-readable shed
    ``reason`` ("queue_cap" | "queue_deadline") and a ``retry_after_s``
    back-off hint the HTTP front end surfaces as 429 + Retry-After."""

    def __init__(self, reason: str, *, retry_after_s: float,
                 queue_depth: int, estimate_ms: float | None = None,
                 deadline_ms: float | None = None):
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        self.estimate_ms = estimate_ms
        self.deadline_ms = deadline_ms
        if reason == "queue_cap":
            msg = (f"admission rejected: fabric queue depth {queue_depth} "
                   f"at cap; retry after {retry_after_s:.3f}s")
        else:
            msg = (f"admission rejected: estimated queue wait "
                   f"{estimate_ms:.0f}ms blows the {deadline_ms:.0f}ms "
                   f"deadline; retry after {retry_after_s:.3f}s")
        super().__init__(msg)


def _load_signals(rep) -> tuple[int, int, int]:
    """(queued, resident, capacity) for one replica, duck-typed across
    the fabric's two replica kinds: a ``RemoteReplica`` reports its
    last heartbeat stats (the same numbers its worker's engine would),
    an in-process ``EngineReplica`` is read directly."""
    stats = getattr(rep, "stats", None)
    if stats is not None:  # RemoteReplica: heartbeat-cached signals
        return (int(stats.get("depth", 0)), int(stats.get("resident", 0)),
                max(1, int(stats.get("capacity", 1))))
    eng = rep.engine
    return eng.scheduler.depth, len(eng._slots), max(1, eng.capacity)


class AdmissionController:
    """Front-door load shedding over a replica set.

    Args:
      queue_cap: fabric-wide queued-request cap (0 = no cap).
      default_deadline_ms: queue deadline applied to requests that
        carry ``queue_deadline_ms=None`` (0 = no default: such requests
        wait forever, the pre-admission behavior).
      service_ms: prior for the per-request slot-hold estimate (ms)
        until ``observe_service_ms`` has fed real observations.
      service_alpha: EWMA weight of each new service-time observation.
      metrics: optional ``utils.metrics.ServingMetrics`` mirror —
        ``configure_admission()`` is called on it and every shed
        recorded, unlocking the summary's ``admission`` section.

    Both knobs at 0 never sheds (but still counts nothing and stamps
    nothing — construct only when admission is ON; the router treats
    ``admission=None`` as the byte-stable status quo).
    """

    def __init__(self, *, queue_cap: int = 0,
                 default_deadline_ms: float = 0.0,
                 service_ms: float = 100.0, service_alpha: float = 0.2,
                 metrics=None):
        if queue_cap < 0:
            raise ValueError(f"queue_cap must be >= 0 (0 = no cap), "
                             f"got {queue_cap}")
        if default_deadline_ms < 0:
            raise ValueError(f"default_deadline_ms must be >= 0 (0 = "
                             f"none), got {default_deadline_ms}")
        if service_ms <= 0:
            raise ValueError(f"service_ms prior must be > 0, "
                             f"got {service_ms}")
        if not 0.0 < service_alpha <= 1.0:
            raise ValueError(f"service_alpha must be in (0, 1], "
                             f"got {service_alpha}")
        self.queue_cap = queue_cap
        self.default_deadline_ms = default_deadline_ms
        self.service_ms = service_ms
        self.service_alpha = service_alpha
        self.metrics = metrics
        if metrics is not None:
            metrics.configure_admission()
        self.admitted = 0
        self.sheds = 0
        self.sheds_cap = 0
        self.sheds_deadline = 0

    # ------------------------------------------------------------- signals

    def observe_service_ms(self, dt_ms: float) -> None:
        """Feed one observed per-request slot-hold time (admit ->
        finish, milliseconds) into the EWMA the wait estimate uses."""
        if dt_ms <= 0:
            return
        a = self.service_alpha
        self.service_ms = (1 - a) * self.service_ms + a * dt_ms

    def queue_depth(self, replicas) -> int:
        """Fabric-wide queued-but-unstarted requests (resident work
        holds slots, not queue positions — the cap bounds WAITING)."""
        return sum(_load_signals(r)[0] for r in replicas if r.accepting)

    def estimate_wait_ms(self, replicas) -> float:
        """Estimated wait for a slot on the BEST accepting replica:
        requests ahead admit in waves of that replica's capacity, each
        wave holding slots for ``service_ms``.  0 when a free slot and
        an empty queue exist anywhere; +inf when nothing accepts."""
        best = None
        for rep in replicas:
            if not rep.accepting:
                continue
            depth, resident, cap = _load_signals(rep)
            free = max(0, cap - resident)
            if free > 0 and depth == 0:
                return 0.0
            waves = max(0, depth - free + cap) // cap
            est = waves * self.service_ms
            if best is None or est < best:
                best = est
        return float("inf") if best is None else best

    # ------------------------------------------------------------ the gate

    def check(self, request, replicas) -> None:
        """Admit or shed one front-door request; raises
        ``AdmissionRejected`` on shed, returns None on admit.  Called
        by ``RequestRouter.submit`` BEFORE placement, so a shed request
        never touches a scheduler queue (nothing to strand)."""
        depth = self.queue_depth(replicas)
        if self.queue_cap and depth >= self.queue_cap:
            self._shed("queue_cap")
            raise AdmissionRejected(
                "queue_cap",
                retry_after_s=round(self.service_ms / 1000.0, 3),
                queue_depth=depth,
            )
        deadline = getattr(request, "queue_deadline_ms", None)
        if deadline is None:
            deadline = self.default_deadline_ms
        if deadline:
            est = self.estimate_wait_ms(replicas)
            if est > deadline:
                self._shed("queue_deadline")
                over_s = ((est - deadline) / 1000.0
                          if est != float("inf")
                          else self.service_ms / 1000.0)
                raise AdmissionRejected(
                    "queue_deadline",
                    retry_after_s=round(max(0.001, over_s), 3),
                    queue_depth=depth, estimate_ms=est,
                    deadline_ms=deadline,
                )
        self.admitted += 1

    def _shed(self, reason: str) -> None:
        self.sheds += 1
        if reason == "queue_cap":
            self.sheds_cap += 1
        else:
            self.sheds_deadline += 1
        if self.metrics is not None:
            self.metrics.record_shed(reason)

    # ------------------------------------------------------------- roll-up

    def summary(self) -> dict:
        return {
            "queue_cap": self.queue_cap,
            "default_deadline_ms": self.default_deadline_ms,
            "service_ms": round(self.service_ms, 3),
            "admitted": self.admitted,
            "sheds": self.sheds,
            "sheds_cap": self.sheds_cap,
            "sheds_deadline": self.sheds_deadline,
        }
