"""Elastic serving fabric: SLO-driven autoscaling + admission control.

The control plane over the fabric's sensors (docs/SERVING.md "Elastic
fabric"): ``AutoscaleController`` sizes the fleet from SLO breach
transitions and queue-depth gauges through a ``ReplicaProvisioner``
(in-process engines or spawned worker processes), and
``AdmissionController`` sheds requests at the front door — per-request
queue deadlines plus a fabric queue-depth cap — raising the named
``AdmissionRejected`` (HTTP 429 + Retry-After on the service front
end) instead of letting overload turn into timeout-collapse.

Everything here is opt-in: a router with ``admission=None`` and no
controller ticking is byte-identical to the pre-autoscale fabric.
"""

from mamba_distributed_tpu.serving.autoscale.admission import (
    AdmissionController,
    AdmissionRejected,
)
from mamba_distributed_tpu.serving.autoscale.controller import (
    AutoscaleController,
    AutoscalePolicy,
)
from mamba_distributed_tpu.serving.autoscale.provisioner import (
    EngineProvisioner,
    ProcessProvisioner,
    ReplicaProvisioner,
)

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "AutoscaleController",
    "AutoscalePolicy",
    "EngineProvisioner",
    "ProcessProvisioner",
    "ReplicaProvisioner",
]
