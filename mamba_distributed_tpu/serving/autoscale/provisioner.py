"""Replica provisioners: how the autoscaler gets (and returns) capacity.

The ``AutoscaleController`` (serving/autoscale/controller.py) decides
WHEN the fleet grows or shrinks; a ``ReplicaProvisioner`` decides HOW a
replica comes into existence — the seam that lets the same policy loop
drive an in-process test fabric and a multi-process service fabric:

  * ``EngineProvisioner`` builds ``EngineReplica``s locally from shared
    params/config — tests and the ``bench_serving --autoscale`` harness,
    where a "replica" costs one slot pool;
  * ``ProcessProvisioner`` wraps a spawn callable (the service path:
    ``scripts/serve_fabric.spawn_worker`` -> ``RemoteReplica``) and owns
    the worker-process lifecycle on retire.

Both honor the replica's tier ``role`` (serving/replica.REPLICA_ROLES),
so a disaggregated fabric's prefill and decode tiers size independently
— the controller asks for capacity IN a role, never a bare replica.
"""

from __future__ import annotations

from mamba_distributed_tpu.obs import NULL_TRACER
from mamba_distributed_tpu.serving.replica import REPLICA_ROLES, EngineReplica
from mamba_distributed_tpu.utils.metrics import ServingMetrics


class ReplicaProvisioner:
    """Interface: mint and retire replicas for the autoscaler.

    ``provision(replica_id, role)`` returns a replica ready for
    ``RequestRouter.add_replica`` (id MUST equal the router's next
    index — the controller passes ``len(router.replicas)``).
    ``retire(replica)`` releases whatever backs it AFTER the router has
    drained it to zero pending — the controller never retires a replica
    still holding streams."""

    def provision(self, replica_id: int, role: str):
        raise NotImplementedError

    def retire(self, replica) -> None:
        raise NotImplementedError


class EngineProvisioner(ReplicaProvisioner):
    """In-process replicas from shared weights: each ``provision`` is a
    fresh ``EngineReplica`` over the SAME read-only params (replicas
    cost slot pools, not param copies — serving/replica.py), with its
    own ``ServingMetrics`` stamped with the new replica id.

    Args:
      params / cfg: the fabric's shared weights and ModelConfig.
      capacity: slots per provisioned replica.
      tracer: SpanTracer each new engine writes to (the fabric-shared
        stream; per-replica streams are a ``spawn`` concern).
      session_store: shared durable-session store, when the fabric has
        one (new replicas must park/resume against the same tiers).
      engine_kw: forwarded to every new ServingEngine (tokens_per_tick,
        max_top_k, ...) — keep these identical to the seed replicas'
        or streams will not be placement-invariant.
    """

    def __init__(self, params, cfg, *, capacity: int = 8,
                 tracer=NULL_TRACER, session_store=None, **engine_kw):
        self.params = params
        self.cfg = cfg
        self.capacity = capacity
        self.tracer = tracer
        self.session_store = session_store
        self.engine_kw = engine_kw
        self.provisioned = 0
        self.retired = 0

    def provision(self, replica_id: int, role: str) -> EngineReplica:
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        metrics = ServingMetrics(self.capacity, replica=replica_id)
        rep = EngineReplica(
            replica_id, self.params, self.cfg, metrics=metrics,
            tracer=self.tracer, role=role, capacity=self.capacity,
            retain_results=False,
            **({} if self.session_store is None
               else {"session_store": self.session_store}),
            **self.engine_kw,
        )
        self.provisioned += 1
        return rep

    def retire(self, replica) -> None:
        """Nothing to release: the engine's device buffers die with the
        last reference once the router drops the replica."""
        self.retired += 1


class ProcessProvisioner(ReplicaProvisioner):
    """Worker-process replicas behind a spawn callable — the service
    fabric's provisioner (scripts/serve_fabric.py builds the callable
    over ``spawn_worker`` + ``RemoteReplica``).

    Args:
      spawn: ``(replica_id, role) -> (proc, replica)`` — starts one
        worker process and returns its handle plus the connected
        ``RemoteReplica``.  ``proc`` may be None (externally-managed
        workers); only non-None procs are reaped on retire.
      shutdown_timeout_s: grace the retired worker process gets to exit
        after its shutdown RPC before being killed.
    """

    def __init__(self, spawn, *, shutdown_timeout_s: float = 30.0):
        self._spawn = spawn
        self.shutdown_timeout_s = shutdown_timeout_s
        self._procs: dict[int, object] = {}
        self.provisioned = 0
        self.retired = 0

    def provision(self, replica_id: int, role: str):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        proc, rep = self._spawn(replica_id, role)
        if proc is not None:
            self._procs[replica_id] = proc
        self.provisioned += 1
        return rep

    def retire(self, replica) -> None:
        """Shut the drained worker down (RPC first, then process reap);
        every step is best-effort — a worker that died on its own is
        already retired."""
        try:
            replica.shutdown()
        except Exception:  # noqa: BLE001 — already-dead worker
            pass
        proc = self._procs.pop(replica.replica_id, None)
        if proc is not None:
            try:
                proc.wait(timeout=self.shutdown_timeout_s)
            except Exception:  # noqa: BLE001 — wedged worker
                proc.kill()
        self.retired += 1
