"""SLO-driven autoscaling: the policy loop that sizes the fleet.

PR 17 built the fabric's sensors — ``obs.SLOMonitor`` breach/recovery
transitions, per-replica queue/occupancy gauges, the ``/metrics``
plane.  This controller is the first thing that ACTS on them: a
host-side evaluate-decide loop (``tick()`` — called from the
``FabricController`` run loop or any bench/step loop; never a thread of
its own, so tests drive it deterministically with an injected clock)
that scales each tier of the fleet between ``min_replicas`` and
``max_replicas``:

  * **scale UP** when the tier is pressured — the shared SLOMonitor is
    in breach on any targeted metric, or mean queued work per accepting
    replica crosses ``queue_depth_high`` — for ``breach_evals_up``
    consecutive evaluations AND the up-cooldown has elapsed: one new
    replica from the ``ReplicaProvisioner`` live-attaches via
    ``RequestRouter.add_replica`` (in-flight streams never pause; the
    next placement simply sees one more candidate);
  * **scale DOWN** when the tier has been healthy — no breach and mean
    queue depth under ``queue_depth_low`` — for ``clear_evals_down``
    consecutive evaluations AND the down-cooldown has elapsed since the
    last scaling action in either direction: the least-loaded accepting
    replica drains through the router's existing path
    (``drain(requeue_queued=True)`` — queued work re-places on the
    survivors, or drain-parks into the session store; PR-16 means no
    stream is ever lost), then retires once its pending count reaches
    zero.

Hysteresis is deliberate and layered: consecutive-evaluation counts
absorb breach FLAPPING (a single noisy p95 window must not buy a
replica), cooldowns absorb oscillation (capacity added needs time to
drain the queue before the signal is trusted again), and the
down-cooldown keys off the last action in EITHER direction so a
scale-up is never immediately clawed back.

Tiers size independently (the PR-10 disaggregation contract): each role
present among the managed replicas gets its own counters, cooldowns and
min/max, so a prefill brownout buys prefill capacity without touching
the decode tier.

Every decision is one ``autoscale_*`` event record through the tracer
(docs/OBSERVABILITY.md) — transitions, never a per-tick flood.
"""

from __future__ import annotations

import dataclasses
import time

from mamba_distributed_tpu.obs import NULL_TRACER


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Sizing policy for ONE tier (applied per role by the controller).

    Args:
      min_replicas / max_replicas: fleet bounds per tier (scale-down
        never drops below min; scale-up never exceeds max).
      scale_up_cooldown_s: wall seconds after any scale-up before the
        next one — new capacity needs time to drain the queue before
        the pressure signal means anything.
      scale_down_cooldown_s: wall seconds after the last scaling action
        in EITHER direction before a scale-down — an up must never be
        immediately clawed back.
      breach_evals_up: consecutive pressured evaluations before a
        scale-up (flap absorption: one noisy p95 window buys nothing).
      clear_evals_down: consecutive healthy evaluations before a
        scale-down (asymmetric on purpose — adding capacity late costs
        goodput, removing it early costs a re-spawn).
      queue_depth_high / queue_depth_low: mean queued-but-unstarted
        requests per accepting replica that count as pressure /
        health; the band between them is dead zone (neither counter
        advances) so depth jitter never oscillates the fleet.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 30.0
    breach_evals_up: int = 3
    clear_evals_down: int = 10
    queue_depth_high: float = 2.0
    queue_depth_low: float = 0.5

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.scale_up_cooldown_s < 0 or self.scale_down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.breach_evals_up < 1 or self.clear_evals_down < 1:
            raise ValueError(
                "breach_evals_up and clear_evals_down must be >= 1 "
                "(1 = act on the first evaluation)"
            )
        if self.queue_depth_low > self.queue_depth_high:
            raise ValueError(
                f"queue_depth_low ({self.queue_depth_low}) must be <= "
                f"queue_depth_high ({self.queue_depth_high}) — the band "
                f"between them is the hysteresis dead zone"
            )


@dataclasses.dataclass
class _TierState:
    """Per-role policy-loop state."""

    pressure_evals: int = 0
    clear_evals: int = 0
    last_up: float = float("-inf")
    last_down: float = float("-inf")


def _queued(rep) -> int:
    """Queued-but-unstarted requests on one replica (duck-typed like
    admission's signals: RemoteReplica stats vs in-process engine)."""
    stats = getattr(rep, "stats", None)
    if stats is not None:
        return int(stats.get("depth", 0))
    return rep.engine.scheduler.depth


class AutoscaleController:
    """The evaluate-decide loop over one router + one provisioner.

    Args:
      router: the ``RequestRouter`` whose fleet this sizes.
      provisioner: where new replicas come from / retired ones go
        (serving/autoscale/provisioner.py).
      policy: ``AutoscalePolicy`` applied to every managed tier.
      slo: optional shared ``obs.SLOMonitor`` — its ``any_breach()``
        is the latency half of the pressure signal (queue depth alone
        drives scaling when None).
      roles: tiers to manage; None = the roles present on the router's
        replicas at construction.
      tracer: ``autoscale_*`` event records land here.
      clock: injected monotonic-seconds source (tests pin cooldowns
        without sleeping; ``tick(now=...)`` overrides per call).
    """

    def __init__(self, router, provisioner, policy: AutoscalePolicy
                 | None = None, *, slo=None, roles=None,
                 tracer=NULL_TRACER, clock=time.monotonic):
        self.router = router
        self.provisioner = provisioner
        self.policy = policy or AutoscalePolicy()
        self.slo = slo
        self.tracer = tracer
        self.clock = clock
        if roles is None:
            roles = []
            for rep in router.replicas:
                if rep.role not in roles:
                    roles.append(rep.role)
        self.roles = tuple(roles)
        self._tiers = {role: _TierState() for role in self.roles}
        # replicas drained by a scale-down, awaiting pending == 0
        self._retiring: list = []
        self.scale_ups = 0
        self.scale_downs = 0
        self.ticks = 0

    # ------------------------------------------------------------- signals

    def _tier_replicas(self, role: str) -> list:
        retiring = set(id(r) for r in self._retiring)
        return [r for r in self.router.replicas
                if r.role == role and r.accepting
                and id(r) not in retiring]

    def _mean_depth(self, reps) -> float:
        if not reps:
            return float("inf")  # an empty accepting tier is pressure
        return sum(_queued(r) for r in reps) / len(reps)

    # ---------------------------------------------------------- the loop

    def tick(self, now: float | None = None) -> None:
        """One policy evaluation: sweep retiring replicas, then judge
        each tier's pressure/health counters against the cooldowns.
        Cheap enough for every fabric iteration (a few int reads per
        replica; no device work, no syncs)."""
        if now is None:
            now = self.clock()
        self.ticks += 1
        self._sweep_retiring()
        breach = self.slo is not None and self.slo.any_breach()
        for role, st in self._tiers.items():
            reps = self._tier_replicas(role)
            depth = self._mean_depth(reps)
            # the trainer tier sizes on its OWN queue (tune jobs) only:
            # a serving-latency breach must not buy training capacity
            # (wrong-direction scaling) nor pin existing lanes up —
            # serving pressure is handled at tick granularity instead
            # (TuningService yields the lane; serving/tuning/service.py)
            tier_breach = breach and role != "trainer"
            pressured = tier_breach or depth >= self.policy.queue_depth_high
            healthy = (not tier_breach
                       and depth <= self.policy.queue_depth_low)
            if pressured:
                st.pressure_evals += 1
                st.clear_evals = 0
                if (st.pressure_evals >= self.policy.breach_evals_up
                        and len(reps) < self.policy.max_replicas
                        and now - st.last_up
                        >= self.policy.scale_up_cooldown_s):
                    self._scale_up(role, st, now,
                                   reason=("slo_breach" if tier_breach
                                           else "queue_depth"),
                                   depth=depth)
            elif healthy:
                st.clear_evals += 1
                st.pressure_evals = 0
                if (st.clear_evals >= self.policy.clear_evals_down
                        and len(reps) > self.policy.min_replicas
                        and now - max(st.last_up, st.last_down)
                        >= self.policy.scale_down_cooldown_s):
                    self._scale_down(role, st, now, reps, depth=depth)
            # in the dead zone between the depth thresholds (and not in
            # breach) neither counter advances: jitter around one
            # threshold can't walk the other counter toward an action

    def _scale_up(self, role: str, st: _TierState, now: float, *,
                  reason: str, depth: float) -> None:
        new_id = len(self.router.replicas)
        rep = self.provisioner.provision(new_id, role)
        self.router.add_replica(rep)
        st.last_up = now
        st.pressure_evals = 0
        self.scale_ups += 1
        self.tracer.event(
            "autoscale_scale_up", role=role, replica=new_id,
            replicas=len(self._tier_replicas(role)), reason=reason,
            mean_queue_depth=round(depth, 3),
        )

    def _scale_down(self, role: str, st: _TierState, now: float,
                    reps: list, *, depth: float) -> None:
        victim = min(reps, key=lambda r: (r.place_cost(), -r.replica_id))
        self.router.drain(victim.replica_id, requeue_queued=True)
        self._retiring.append(victim)
        st.last_down = now
        st.clear_evals = 0
        self.scale_downs += 1
        self.tracer.event(
            "autoscale_scale_down", role=role,
            replica=victim.replica_id,
            replicas=len(self._tier_replicas(role)),
            mean_queue_depth=round(depth, 3),
        )

    def _sweep_retiring(self) -> None:
        """Retire drained replicas once they hold nothing: the drain
        already re-placed (or drain-parked) their queue, so pending
        hitting zero means every stream finished or moved — only THEN
        does the provisioner release the backing resources."""
        still = []
        for rep in self._retiring:
            if rep.alive and rep.pending > 0:
                still.append(rep)
                continue
            self.provisioner.retire(rep)
            rep.mark_dead()
            self.tracer.event("autoscale_retire", role=rep.role,
                              replica=rep.replica_id)
        self._retiring = still

    # ------------------------------------------------------------- roll-up

    def summary(self) -> dict:
        return {
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "retiring": len(self._retiring),
            "tiers": {
                role: {
                    "replicas": len(self._tier_replicas(role)),
                    "pressure_evals": st.pressure_evals,
                    "clear_evals": st.clear_evals,
                }
                for role, st in self._tiers.items()
            },
        }
