"""Request lifecycle + FCFS admission for the serving engine.

A request moves QUEUED -> PREFILL -> DECODE -> FINISHED:

  QUEUED    in the scheduler's FCFS queue, waiting for a free slot
  PREFILL   building its recurrent state: one bucketed forward for short
            prompts, or chunk-by-chunk across ticks for long ones
            (serving/prefill.py) — the slot holds the partial carry
  DECODE    occupying a slot; one token per engine tick
  FINISHED  sampled its ``eos_id`` or exhausted ``max_new_tokens``

The scheduler is deliberately minimal — an arrival-order deque plus the
lifecycle bookkeeping.  Admission happens between compiled decode ticks
(serving/engine.py), so policy changes (priorities, prefill batching,
preemption) are host-side swaps that never touch compiled code.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import deque
from typing import Iterator

import jax
import numpy as np

from mamba_distributed_tpu.obs.context import mint_trace_id
from mamba_distributed_tpu.serving.adapters import split_adapter_version


class TenantQuotaExceeded(RuntimeError):
    """Admitting this request would give one tenant (adapter BASE name
    — versions share the quota) more concurrent resident slots than
    ``cfg.tenant_max_slots`` allows.  The engine treats it exactly like
    a KV-page stall: requeue and retry next step — fairness is
    BACKPRESSURE, never shedding (the request stays queued until a
    sibling stream finishes).  ``tenant_max_slots=0`` (default)
    disables the check entirely."""


def check_tenant_quota(adapter: str | None, resident_adapters,
                       max_slots: int) -> None:
    """Raise the named :class:`TenantQuotaExceeded` when ``adapter``
    already holds ``max_slots`` resident slots.  ``resident_adapters``
    is the engine's view of adapter names currently occupying slots
    (None entries = base-model streams, never counted); versioned names
    (``tenant@v2``) count against their base — a tenant cannot dodge
    its quota by shipping a new version."""
    if max_slots <= 0 or not adapter:
        return
    base, _ = split_adapter_version(adapter)
    held = sum(1 for a in resident_adapters
               if a and split_adapter_version(a)[0] == base)
    if held >= max_slots:
        raise TenantQuotaExceeded(
            f"tenant {base!r} holds {held}/{max_slots} resident slots "
            f"(cfg.tenant_max_slots) — request stays queued until one "
            f"frees"
        )


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


@dataclasses.dataclass
class GenerationRequest:
    """One generation job.  ``seed`` derives the sampling key; passing the
    same key to a solo ``generate()`` call reproduces this request's
    tokens exactly (the engine parity contract, tests/test_serving.py)."""

    prompt_ids: np.ndarray  # (t,) int32
    max_new_tokens: int = 32
    top_k: int = 50
    temperature: float = 1.0
    eos_id: int | None = None
    seed: int = 0
    key: jax.Array | None = None  # overrides seed when given
    # echo of the id the scheduler assigned at the LAST submit of this
    # object (the authoritative id lives on the scheduler's tracker, so
    # resubmission is safe); submit()/TokenEvents carry the real one
    request_id: int | None = None
    # fabric-wide trace id (obs/context.py).  None => the scheduler
    # mints a fresh one per submit; the ROUTER sets it at placement so
    # a failover re-placement continues the SAME trace — one request,
    # one flow chain in the exported timeline, however many replicas
    # it visited.
    trace_id: str | None = None
    # priority class (higher = more important; None takes
    # cfg.serving_default_priority).  Admission pops the highest
    # priority first (FCFS within a class), and the engine PREEMPTS a
    # lower-priority decoding slot — carry swapped to host RAM,
    # resumed later without re-prefill — when a higher-priority
    # request is stuck queued with no free slot (serving/engine.py).
    priority: int | None = None
    # named LoRA adapter this request decodes under (serving/
    # adapters.py; None = the base model).  Validated at submit against
    # the engine's AdapterRegistry — an unknown name raises the named
    # UnknownAdapterError, never a hang — and carried through the
    # service wire, failover replay, SSE resume and tier migration
    # (the target engine re-pins the factors from its own cache).
    adapter: str | None = None
    # admission deadline (serving/autoscale/admission.py): the longest
    # queue wait this request tolerates, in milliseconds — a fabric
    # with an AdmissionController sheds the request FAST (the named
    # AdmissionRejected; HTTP 429 on the service) when the estimated
    # wait exceeds it.  None defers to the fabric's default deadline
    # (which may itself be off); the plain engine path never reads it,
    # so carrying one is byte-stable without admission control.
    queue_deadline_ms: float | None = None

    def resolve_key(self) -> jax.Array:
        key = self.key if self.key is not None else jax.random.PRNGKey(self.seed)
        if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
            # new-style typed keys: unwrap to the raw uint32 pair the slot
            # pool stores (fold_in over raw data draws the same bits)
            key = jax.random.key_data(key)
        return key


@dataclasses.dataclass
class TokenEvent:
    """One streamed token (serve()/step() output, in emission order)."""

    request_id: int
    token: int
    index: int  # 0-based position within the generated suffix
    done: bool
    finish_reason: str | None = None  # "eos" | "length" when done


@dataclasses.dataclass
class GenerationResult:
    request_id: int
    prompt_ids: np.ndarray
    new_tokens: np.ndarray  # generated suffix (includes eos when hit)
    finish_reason: str  # "eos" | "length"

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated suffix, ``generate()``-shaped."""
        return np.concatenate([self.prompt_ids, self.new_tokens])


@dataclasses.dataclass
class _Tracked:
    """Host-side mirror of one in-flight request.  ``request_id`` lives
    here (not on the GenerationRequest) so submitting the same request
    object twice yields two independent streams."""

    request: GenerationRequest
    request_id: int = -1
    # the trace id every span/record of this request's journey carries
    # (request.trace_id when the router propagated one, else minted at
    # submit — see GenerationRequest.trace_id)
    trace_id: str = ""
    status: RequestStatus = RequestStatus.QUEUED
    slot: int | None = None
    new_tokens: list[int] = dataclasses.field(default_factory=list)
    finish_reason: str | None = None
    # --- host-side lifecycle stamps (time.perf_counter seconds) from
    # which the engine derives queue-wait, TTFT and inter-token latency
    # (obs/: per-request serving telemetry; docs/OBSERVABILITY.md) ---
    t_submit: float = 0.0  # stamped by FCFSScheduler.submit
    t_admit: float | None = None  # slot granted, prefill dispatched
    t_first_token: float | None = None  # first decode token on host
    t_last_token: float | None = None  # most recent token on host
    # per-request ITL histogram (StreamingHistogram), created at admit;
    # rides in the request's jsonl record so obs_report.py can merge
    # per-token percentiles across requests without storing samples
    itl_hist: object | None = None
    # --- chunked-prefill progress (serving/prefill.py): the plan this
    # request's prompt splits into (None => one-shot path), how many
    # chunks have run, and the accumulated host dispatch time ---
    plan: object | None = None
    chunks_done: int = 0
    prefill_dt: float = 0.0
    # real prompt tokens a partial prefix-cache hit seeded (skipped
    # chunks) — record_prefill at completion reports only the COMPUTED
    # tokens, so prefill throughput never double-counts what
    # prefix_saved_tokens already claims was skipped
    prefill_seeded_tokens: int = 0
    # consecutive chunk grants this slot was passed over for (the SRPT
    # starvation guard, serving/engine._pick_prefill_slot)
    prefill_skipped: int = 0
    # hybrid paged KV: physical page ids this request holds a ref on
    # (reserved at admission, or shared from a cached prefix and
    # incref'd), decref'd on evict/failure (serving/engine.py page
    # allocator; state_cache.PagePool refcounts)
    pages: list | None = None
    # resolved priority class (request.priority, else the scheduler's
    # default) — admission order + preemption rank
    priority: int = 0
    # preemption swap-out state (serving/engine._preempt): host copies
    # of the slot's carry/logits + the generated-token count, so
    # re-admission restores mid-decode without re-prefill.  Survives
    # requeue — clearing it would silently re-prefill and REPLAY
    # already-delivered tokens.
    snapshot: dict | None = None
    preempted: int = 0  # times this request was swapped out
    # prefix-cache outcome at admission: "full" | "partial" | None
    # (miss / cache off) — stamps the request record + TTFT split
    cache_hit: str | None = None
    # --- disaggregated prefill/decode migration (serving/router.py,
    # serving/engine.py).  no_migrate marks a request the migration
    # hook must skip: it already arrived here VIA migration (or the
    # hook declined once — mixed-mode fallback decodes it locally), so
    # re-offering it every step would ping-pong between tiers.
    no_migrate: bool = False
    migrations: int = 0  # prefill->decode handoffs this request took
    migration_ms: float = 0.0  # host time spent packaging + restoring
    migration_source: int | None = None  # replica id that prefilled
    # --- speculative decoding (serving/spec_decode.py, the pending-
    # token scheme): tokens committed to the stream but not yet folded
    # into the device state, how many of them the consumer has already
    # received, and how much committed history the drafter has
    # observed.  All three survive preemption (the snapshot pairs with
    # them) and are reset by requeue() only when the request will
    # re-prefill from scratch.
    spec_pending: list = dataclasses.field(default_factory=list)
    spec_pending_emitted: int = 0
    spec_observed: int = 0
    # --- multi-tenant LoRA (serving/adapters.py): the device factor-
    # pool row this request's slot multiplies (0 = the zero "no
    # adapter" row; None = no cache ref held).  A ref is acquired at
    # admission (like KV pages) and released at finish/failure; it
    # RIDES a preemption snapshot (resume must not re-miss) and is
    # released when the request migrates out (the target re-pins from
    # its own engine-local cache).
    adapter_slot: int | None = None
    # --- mid-stream adapter hot swap (serving/engine.hot_swap_adapter,
    # the PR-15 residual online tuning needed): the request object as
    # the USER submitted it (None until the first swap — finish records
    # and GenerationResult must echo the original prompt/adapter, not
    # the internal continuation request the swap fabricates), the count
    # of tokens already emitted at the LAST swap (``new_tokens`` keeps
    # growing across a swap, but the re-admitted continuation's device
    # step counter restarts at 0 — preempt/park/migration step stamps
    # subtract this base), and how many swaps the stream took (record
    # stamp, absent when zero).
    orig_request: GenerationRequest | None = None
    swap_base: int = 0
    hot_swaps: int = 0


class FCFSScheduler:
    """First-come-first-served admission queue with priority classes:
    ``pop``/``peek`` take the highest-priority entry, FCFS within a
    class — with every request at the default priority this is exactly
    the arrival-order deque it always was."""

    def __init__(self, default_priority: int = 0) -> None:
        self._queue: deque[_Tracked] = deque()
        self._next_id = 0
        self.default_priority = default_priority

    def submit(self, request: GenerationRequest) -> _Tracked:
        prompt = np.asarray(request.prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.top_k < 1:
            raise ValueError("top_k must be >= 1")
        if request.temperature <= 0.0:
            raise ValueError("temperature must be > 0")
        request.prompt_ids = prompt
        # the scheduler's counter is authoritative: every submit gets a
        # fresh id, so resubmitting an object can't collide two streams
        # (a router-propagated trace_id is deliberately reused though —
        # failover re-placement is the same request's journey)
        tracked = _Tracked(request=request, request_id=self._next_id,
                           trace_id=request.trace_id or mint_trace_id(),
                           priority=(self.default_priority
                                     if request.priority is None
                                     else request.priority),
                           t_submit=time.perf_counter())
        self._next_id += 1
        request.request_id = tracked.request_id  # convenience echo
        self._queue.append(tracked)
        return tracked

    def _best(self) -> int | None:
        """Index of the next request to admit: highest priority,
        earliest arrival (queue position) within a class."""
        if not self._queue:
            return None
        return max(range(len(self._queue)),
                   key=lambda i: (self._queue[i].priority, -i))

    def pop(self) -> _Tracked | None:
        """Next request to admit (priority, then arrival order), or
        None when empty."""
        i = self._best()
        if i is None:
            return None
        tracked = self._queue[i]
        del self._queue[i]
        return tracked

    def peek(self) -> _Tracked | None:
        """What ``pop`` would return, without removing it (the engine's
        preemption check reads the queue's best priority)."""
        i = self._best()
        return None if i is None else self._queue[i]

    def pop_preempted(self) -> _Tracked | None:
        """Next queued PREEMPTED request (one holding a resume
        snapshot), or None.  The engine resumes these even when the
        queue's best request is stalled on KV pages: a swap-in needs no
        pages, and running it is the only way the pages it pins ever
        release (serving/engine._resume_parked).  MIGRATED-in snapshots
        (the disaggregated prefill->decode artifact) are skipped: they
        carry page CONTENTS and re-allocate their full reservation at
        restore, so unlike a preempted swap-in they compete for the
        very pages the stalled head is waiting on."""
        for i, t in enumerate(self._queue):
            if t.snapshot is not None and not t.snapshot.get("migrated"):
                del self._queue[i]
                return t
        return None

    def withdraw_unstarted(self) -> list[_Tracked]:
        """Remove and return every queued request that has NOT started:
        status QUEUED and no resume/migration snapshot.  The drain
        shutdown path (serving/router.drain(requeue_queued=True)) uses
        this to hand queued-but-unplaced work back to the router — a
        draining replica previously stranded its queue unless something
        kept stepping it.  Preempted/migrated entries (snapshot
        holders) stay: their state lives HERE and re-placing them
        elsewhere would either lose it or re-deliver tokens."""
        keep: deque[_Tracked] = deque()
        out: list[_Tracked] = []
        for t in self._queue:
            if t.status is RequestStatus.QUEUED and t.snapshot is None:
                out.append(t)
            else:
                keep.append(t)
        self._queue = keep
        return out

    def requeue(self, tracked: _Tracked) -> None:
        """Put a popped-but-not-admitted request back at the queue head
        (a failed prefill must not drop it; a preempted request resumes
        ahead of its class — it arrived first).  Chunked-prefill
        progress is reset — a prefill retry restarts from chunk 0 with
        a fresh carry — but a preemption ``snapshot`` survives: the
        resume path must restore it, never re-prefill (a re-prefill
        would replay tokens the consumer already has)."""
        tracked.status = RequestStatus.QUEUED
        tracked.slot = None
        tracked.plan = None
        tracked.chunks_done = 0
        tracked.prefill_dt = 0.0
        tracked.prefill_seeded_tokens = 0
        tracked.prefill_skipped = 0
        if tracked.snapshot is None:
            # a re-prefill re-derives the first pending token from the
            # fresh prefill logits; the drafter stream restarts too
            # (spec_observed=0 tells the engine's spec tick to forget
            # it).  A PREEMPTED request keeps all three — its snapshot
            # restores the exact state the pending tokens pair with.
            tracked.spec_pending = []
            tracked.spec_pending_emitted = 0
            tracked.spec_observed = 0
        self._queue.appendleft(tracked)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def __iter__(self) -> Iterator[_Tracked]:
        return iter(self._queue)
