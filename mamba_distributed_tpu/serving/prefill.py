"""Chunked-prefill subsystem: plan, compiled chunk step, shared driver.

A long prompt's prefill is just a resumable scan — Mamba's decode state
is O(1), and the mixers accept ``initial_conv_state``/``initial_ssm_state``
carries — so instead of one pow2-bucketed forward per prompt (a new jit
trace per length class, up to 2x padding waste, and a tick-stalling
monolith in the serving engine), prompts longer than
``cfg.prefill_chunk_tokens`` run as a sequence of fixed-shape chunk
calls:

  * ``plan_chunks`` pads the prompt (LEFT, like the pow2 buckets) to the
    next multiple of the chunk size and splits it into equal chunks —
    the pad lives entirely inside chunk 0, under the usual ``token_mask``;
  * ``prefill_chunk`` is the one compiled step: ids + mask + carried
    state -> (last logits, new state), via ``models/lm.lm_prefill_chunk``.
    ONE trace per (model config, chunk size, batch) no matter how long
    prompts get — ``TRACE_COUNTS["chunk"]`` pins it
    (tests/test_prefill.py);
  * ``chunked_prefill`` drives a whole prompt through the chunk step —
    the solo ``generate()`` path.  The serving engine drives the same
    step itself, chunk by chunk between decode ticks, parking the carry
    in the request's slot (state_cache.stash_prefill) when its per-tick
    token budget runs out.

The chunk carry is also the DISAGGREGATION currency: on a prefill-tier
replica (docs/SERVING.md "Disaggregated tiers") the completed prompt's
carry + last logits — the exact outputs the last chunk step returns —
become the O(1) migration artifact a decode replica restores, so
splitting the phases across replicas costs one host round-trip of the
same snapshot prefix caching and preemption already move.

Parity: the engine and ``generate()`` run the SAME jitted chunk step
over the SAME padded chunk layout with params cast by the SAME jitted
cast, so their prefill states — and therefore token streams — are
bit-identical by construction (the pow2-bucket playbook, extended).
Chunked vs ONE-SHOT prefill over the same layout is exact for the conv
caches (the carry is the literal trailing inputs) and ~1e-6 for the SSM
states (the inter-chunk fp32 state recurrence re-associates; see
lm_prefill_chunk's docstring) — pinned at tolerance by
tests/test_prefill.py.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.inference.bucketing import (
    chunk_aligned_bucket,
    use_chunked_prefill,
)
from mamba_distributed_tpu.inference.generate import _decode_params
from mamba_distributed_tpu.models.lm import init_lm_state, lm_prefill_chunk

# Python-side-effect trace counter: one bump per jit trace of the chunk
# step.  The whole point of the fixed chunk shape is that this stays at
# one per (cfg, chunk, batch) for any prompt-length mix — pinned by
# tests/test_prefill.py::test_chunk_step_traces_once.
TRACE_COUNTS = {"chunk": 0}


@functools.partial(jax.jit, static_argnames=("cfg",))
def cast_decode_params(params: dict, cfg: ModelConfig) -> dict:
    """Decode-layout param cast (inference/generate._decode_params), jitted
    once at module level so the serving engine and ``generate()``'s
    chunked path share one compilation AND produce bit-identical cast
    values — an input to the chunk-step parity argument above."""
    return _decode_params(params, cfg)


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """How one prompt splits into prefill chunks (host-side, static)."""

    prompt_len: int
    chunk: int  # tokens per chunk (cfg.effective_prefill_chunk_tokens)
    bucket: int  # padded length = n_chunks * chunk
    n_chunks: int

    @property
    def pad(self) -> int:
        """Left-pad tokens (all inside chunk 0)."""
        return self.bucket - self.prompt_len

    def real_tokens(self, i: int) -> int:
        """Non-pad prompt tokens in chunk ``i`` — what advances the
        hybrid KV length mirror, and the chunk's share of USEFUL work
        in the goodput accounting (``chunk - real`` lanes are padding
        waste; utils/metrics.record_tick)."""
        return self.chunk - (self.pad if i == 0 else 0)


def plan_chunks(prompt_len: int, chunk_tokens: int,
                force: bool = False) -> ChunkPlan | None:
    """The chunk planner.  None => the prompt takes the one-shot pow2
    path (too short to chunk, or chunking disabled).  ``force`` plans
    even prompts that fit one chunk (>= 1 chunk) — the HYBRID path,
    where every prompt runs through the chunk step because it is the
    one prefill that both masks pad keys (pads are never written to KV
    pages) and writes straight into the paged pool."""
    if not use_chunked_prefill(prompt_len, chunk_tokens):
        if not (force and chunk_tokens > 0):
            return None
    bucket = chunk_aligned_bucket(prompt_len, chunk_tokens)
    return ChunkPlan(
        prompt_len=prompt_len,
        chunk=chunk_tokens,
        bucket=bucket,
        n_chunks=bucket // chunk_tokens,
    )


def chunk_inputs(
    prompt_ids: np.ndarray, plan: ChunkPlan, i: int
) -> tuple[jax.Array, jax.Array]:
    """ids + mask for chunk ``i`` of the left-padded layout.

    prompt_ids (b, t) -> ids (b, chunk) int32, mask (b, chunk) f32 {0,1}.
    Pad positions (chunk 0's first ``plan.pad`` columns) hold token id 0
    and mask 0 — the same contract as ``pad_to_bucket``.
    """
    if not 0 <= i < plan.n_chunks:
        raise ValueError(f"chunk {i} out of range [0, {plan.n_chunks})")
    ids = np.asarray(prompt_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None, :]
    b, t = ids.shape
    if t != plan.prompt_len:
        raise ValueError(f"prompt length {t} != plan.prompt_len {plan.prompt_len}")
    lo, hi = i * plan.chunk, (i + 1) * plan.chunk  # in padded coordinates
    pad = plan.pad
    out = np.zeros((b, plan.chunk), np.int32)
    mask = np.zeros((b, plan.chunk), np.float32)
    # real tokens occupy padded positions [pad, bucket)
    src_lo, src_hi = max(lo, pad) - pad, hi - pad
    dst_lo = max(lo, pad) - lo
    out[:, dst_lo:] = ids[:, src_lo:src_hi]
    mask[:, dst_lo:] = 1.0
    return jnp.asarray(out), jnp.asarray(mask)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"),
                   donate_argnums=(3,))
def prefill_chunk(
    params: dict, ids: jax.Array, mask: jax.Array, state, cfg: ModelConfig,
    mesh=None, adapter_ids: jax.Array | None = None,
):
    """The compiled chunk step: (ids, mask, carry) -> (last logits, carry').

    ``params`` must already be decode-cast (``cast_decode_params``) —
    both drivers pass the same cast output, which is what makes their
    chunk computations bit-identical.  ``state`` is donated: for hybrid
    stacks it carries the (large) paged KV pool through every chunk, and
    the donation lets XLA write pages in place instead of copying the
    pool per chunk.

    ``mesh`` (static; a 2-D ``serving_mesh`` with ``model > 1``, else
    None) re-asserts the tensor-parallel weight layout inside the jit —
    the same constraint the engine's tick applies — so the engine's
    chunk dispatches and ``generate(mesh=)``'s run ONE partitioning and
    the chunk-step parity argument survives weight sharding.  None (the
    default, and everything below ``serving_model_shards=2``) keeps the
    signature — and the trace counts tests pin — byte-identical to the
    pre-TP step.
    """
    TRACE_COUNTS["chunk"] += 1
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            constrain_serving_params,
        )

        params = constrain_serving_params(params, mesh)
    if adapter_ids is not None:
        # multi-tenant LoRA (serving/adapters.py): bind the batch rows'
        # adapter ids into the attached factor pools so this chunk's
        # projections add the request's segmented delta — the SAME
        # per-row math the tick applies, which is what keeps a LoRA
        # stream's prefill and decode on one adapter identity
        from mamba_distributed_tpu.serving.adapters import (
            bind_adapter_ids,
        )

        params = bind_adapter_ids(params, adapter_ids)
    return lm_prefill_chunk(params, cfg, ids, state, token_mask=mask)


def chunked_prefill(
    params: dict, cfg: ModelConfig, prompt_ids,
    plan: ChunkPlan | None = None, max_len: int = 0, mesh=None,
    prefix_cache=None,
):
    """Drive a whole prompt through the chunk step (the solo-`generate()`
    driver; the serving engine paces the same loop itself, against its
    per-tick budget).

    ``params`` are the fp32 master params — cast here via the shared
    jitted cast.  For HYBRID stacks ``max_len`` (prompt + decode budget)
    sizes the private paged KV cache; its page count is pow2-bucketed so
    the downstream decode trace count stays O(log pages) across prompt/
    budget mixes (page-width differences never perturb the token stream
    — masked attention is bit-stable across page-bucket widths, see
    models/attention.py).  ``mesh`` (a 2-D serving_mesh with model > 1,
    else None) threads the tensor-parallel weight constraint into every
    chunk call — pass the serving engine's mesh to reproduce its chunk
    computation bit-for-bit.  Returns (last_logits (b, V) fp32, state),
    the ``lm_prefill`` contract, ready for the decode loop.

    ``prefix_cache`` (a serving/prefix_cache.PrefixCache; batch-1
    PURE-SSM prompts only — hybrid entries pin a serving engine's page
    pool and are unusable here) reuses and refreshes carry snapshots:
    a full hit returns the cached (logits, state) with zero chunk
    calls, a partial hit seeds the deepest cached boundary carry (a
    COPY — the chunk step donates its state argument, and a donated
    cache entry would be destroyed), and completed chunks store their
    boundaries back.  Cached carries are the literal outputs of this
    exact layout's chunk steps, so warm results are bit-identical to
    cold ones — and to a cache-enabled serving engine's, which shares
    both the layout and the key scheme (tests/test_prefix_cache.py).
    """
    prompt = np.asarray(prompt_ids, np.int32)
    if prompt.ndim == 1:
        prompt = prompt[None, :]
    b, t = prompt.shape
    hybrid = bool(cfg.attn_layer_idx)
    if plan is None:
        plan = plan_chunks(t, cfg.effective_prefill_chunk_tokens,
                           force=hybrid)
    if plan is None:
        raise ValueError(
            f"prompt length {t} does not take the chunked path "
            f"(prefill_chunk_tokens={cfg.effective_prefill_chunk_tokens}); use "
            f"lm_prefill via the pow2 bucket instead"
        )
    dparams = cast_decode_params(params, cfg=cfg)
    if hybrid:
        if max_len < t:
            raise ValueError(
                f"hybrid chunked prefill needs KV capacity for the whole "
                f"request: max_len={max_len} < prompt length {t}"
            )
        from mamba_distributed_tpu.inference.bucketing import (
            next_pow2_bucket,
        )
        from mamba_distributed_tpu.models.attention import (
            attention_page_count,
        )

        pages = next_pow2_bucket(
            attention_page_count(cfg, max_len), min_bucket=1
        )
        state = init_lm_state(cfg, batch=b,
                              max_len=pages * cfg.kv_page_tokens)
    else:
        state = init_lm_state(cfg, batch=b)
    use_cache = prefix_cache is not None and not hybrid and b == 1
    start = 0
    if use_cache:
        hit = prefix_cache.lookup(prompt[0], plan)
        if hit is not None:
            entry, start = hit
            if start == plan.n_chunks:
                # full hit: the snapshot IS this layout's prefill output
                return entry.logits, {"blocks": entry.state["blocks"]}
            # seed a COPY: prefill_chunk donates its state argument, and
            # donating the cached arrays would destroy the entry
            state = {"blocks": jax.tree.map(jnp.copy, entry.state["blocks"])}
    logits = None
    for i in range(start, plan.n_chunks):
        ids, mask = chunk_inputs(prompt, plan, i)
        logits, state = prefill_chunk(dparams, ids, mask, state, cfg=cfg,
                                      mesh=mesh)
        if use_cache:
            # the output carry feeds the NEXT chunk's donation — store a
            # copy (tiny: the O(1) conv+SSM carry) ... except the last,
            # which nothing donates again
            keep = (state["blocks"] if i == plan.n_chunks - 1
                    else jax.tree.map(jnp.copy, state["blocks"]))
            prefix_cache.maybe_store_boundary(
                prompt[0], plan, i, {"blocks": keep})
            if i == plan.n_chunks - 1:
                prefix_cache.maybe_store_full(
                    prompt[0], {"blocks": keep}, logits,
                    chunk=plan.chunk, chunks=plan.n_chunks)
    return logits, state
