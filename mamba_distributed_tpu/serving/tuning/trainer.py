"""Frozen-base LoRA fine-tuning for the online tuning plane.

The trainer reuses the training stack end to end instead of growing a
second one: :func:`training.train_step.make_train_step` provides the
jitted loss/accum/clip/update machinery, and the serving LoRA delta
path (models/common.linear — live whenever a ``"lora"`` subtree with
bound ids sits on a projection) provides the forward.  Factor pools
are attached to a PRIVATE copy of the base params exactly the way the
serving engine attaches its device cache
(serving/adapters.attach_adapter_pools), with ONE slot row per target
— row 0 IS the tenant's factors — and the ids bound to zeros at trace
time via ``make_train_step``'s ``params_map`` hook, so the compiled
step differentiates straight through the segmented delta to the pool
leaves.

Base weights stay BIT-identical: gradients on them are zeroed before
the clip (so the clipped norm is the factors' norm, not the model's),
the masked Adam holds state only for factor leaves, and the step's
``freeze`` splice puts the original frozen arrays back after
``apply_updates`` (adding a literal 0.0 would flip ``-0.0`` sign
bits).

Sharding follows the serving rules (parallel/sharding.
serving_param_specs): A row-parallel on d_in, B column-parallel on
d_out — translated onto the training mesh's axis names ("model" ->
"tensor", "stage" folded away) so one factor layout serves both the
fabric's decode ticks and its train steps.
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mamba_distributed_tpu.config import TrainConfig
from mamba_distributed_tpu.parallel.mesh import single_device_mesh
from mamba_distributed_tpu.parallel.sharding import serving_param_specs
from mamba_distributed_tpu.serving.adapters import (
    UnknownAdapterError,
    attach_adapter_pools,
    bind_adapter_ids,
    split_adapter_version,
)
from mamba_distributed_tpu.serving.tuning.jobs import TuneError, TuneJob
from mamba_distributed_tpu.training.train_step import make_train_step

# fresh-tenant init: A ~ N(0, INIT_SCALE / rank), B = 0 — the first
# version starts AT the base model (zero delta) and only the B grads
# are nonzero on step one (dL/dA = dL/dy @ B^T = 0 at B=0), the
# conventional LoRA warmup; a zero A too would leave BOTH grads zero
# and the job permanently stuck
INIT_SCALE = 0.05


def lora_freeze_tree(params: dict):
    """Pytree of bools matching ``params``: True (frozen) everywhere
    except under a ``"lora"`` key — the trainable factor leaves."""

    def walk(tree, in_lora):
        if isinstance(tree, dict):
            return {k: walk(v, in_lora or k == "lora")
                    for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(walk(v, in_lora) for v in tree)
        return not in_lora

    return walk(params, False)


def lora_optimizer(freeze, lr: float,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    """Masked optimizer over a frozen-base tree.

    Order matters: frozen grads are zeroed FIRST (the base weights DO
    receive real gradients — they are differentiated arguments — and
    must not pollute the clip norm), then the global-norm clip sees
    only the factor gradients, then a masked Adam holds first/second
    moments for the factor leaves alone (``optax.masked`` stores
    ``MaskedNode`` placeholders elsewhere — no shadow copy of the
    model in optimizer state, unlike ``multi_transform``)."""
    train = jax.tree.map(lambda f: not f, freeze)
    return optax.chain(
        optax.masked(optax.set_to_zero(), freeze),
        optax.clip_by_global_norm(grad_clip),
        optax.masked(optax.adam(lr), train),
    )


def pack_examples(examples, batch: int, seq_len: int):
    """Pack token-id example sequences into one ``(1, B, T)`` x/y pair
    (the train step's ``(accum, B_global, T)`` layout, accum=1).

    Standard LM packing: the examples concatenate into one stream,
    cycled until it covers ``B*T + 1`` tokens, then split into
    next-token-shifted x/y — no padding tokens, so every position
    trains on tenant data."""
    stream = [t for ex in examples for t in ex]
    if len(stream) < 2:
        raise TuneError("tune examples pack to fewer than 2 tokens")
    need = batch * seq_len + 1
    reps = -(-need // len(stream))
    arr = np.asarray((stream * reps)[:need], np.int32)
    x = arr[:-1].reshape(1, batch, seq_len)
    y = arr[1:].reshape(1, batch, seq_len)
    return x, y


# ------------------------------------------------------- mesh plumbing


def _training_mesh_from(mesh) -> Mesh:
    """Normalize any fabric mesh to training axis names.

    A serving mesh (``("data", "model")`` or ``("data", "stage",
    "model")``) re-labels onto the training mesh's 6 axes: its data
    (and stage) extent becomes pure data parallel, its model extent
    becomes ``tensor`` — same devices, training-side names, so
    ``batch_spec``/TP rules resolve.  A mesh that already has the
    training axes passes through."""
    names = mesh.axis_names
    if "fsdp" in names:
        return mesh
    shape = dict(mesh.shape)
    data = shape.get("data", 1) * shape.get("stage", 1)
    model = shape.get("model", 1)
    devs = np.asarray(mesh.devices).reshape(data, 1, 1, model, 1, 1)
    return Mesh(devs, ("data", "fsdp", "seq", "tensor", "pipe", "expert"))


def _to_training_spec(spec: P) -> P:
    """Translate one serving PartitionSpec onto training axis names:
    ``"model"`` -> ``"tensor"`` (the TP axis under either name),
    ``"stage"`` -> replicated (the trainer folds stages into data)."""

    def one(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(one(e) for e in entry if e != "stage")
            kept = tuple(e for e in kept if e is not None)
            return kept if kept else None
        if entry == "model":
            return "tensor"
        if entry == "stage":
            return None
        return entry

    return P(*(one(e) for e in spec))


# -------------------------------------------------------------- trainer


class LoraTrainer:
    """Fine-tunes one tenant's {A, B} factors against a frozen base.

    One trainer serves the whole tuning lane: it holds a private copy
    of the base params (the compiled step DONATES its buffers — the
    serving engines' shared read-only tree must never be donated) with
    zero factor pools attached once at construction; each job splices
    its warm-start factors into the pools, re-inits the masked
    optimizer state, and steps the one compiled train step.  Jobs
    serialize — static shapes mean the jit traces once, ever.

    Deploy path: the finished factors register under the job's BARE
    name — :meth:`AdapterRegistry.register` mints ``v(N+1)`` — with
    ``alpha=rank`` so the stored (scaled) B is the trained B
    bit-exactly (the trainer optimizes the EFFECTIVE factors; warm
    starts read the stored ones back symmetrically).
    """

    def __init__(self, params: dict, cfg, registry, *, mesh=None):
        self.cfg = cfg
        self.registry = registry
        self.rank = registry.rank
        self.mesh = (_training_mesh_from(mesh) if mesh is not None
                     else single_device_mesh())
        self.tcfg = TrainConfig(
            model=cfg,
            micro_batch_size=cfg.tune_batch_size,
            seq_len=cfg.tune_seq_len,
            total_batch_size=cfg.tune_batch_size * cfg.tune_seq_len,
        )
        pools = {}
        for path, (n, d_in, d_out) in registry.targets.items():
            pools[path] = {
                "A": jnp.zeros((n, 1, d_in, self.rank), jnp.float32),
                "B": jnp.zeros((n, 1, self.rank, d_out), jnp.float32),
            }
        # private copy: jnp.array copies even for committed jax arrays,
        # so later donation can't invalidate the fabric's shared tree
        tree = attach_adapter_pools(
            jax.tree.map(lambda a: jnp.array(a), params), pools
        )
        # serving-rule placement translated onto the training mesh
        # (identity on one device): factors and kernels shard the same
        # axes whether a decode tick or a train step reads them
        specs = jax.tree.map(
            _to_training_spec,
            serving_param_specs(tree, dict(self.mesh.shape)["tensor"]),
            is_leaf=lambda x: isinstance(x, P),
        )
        self._tree = jax.device_put(
            tree, jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        )
        self.freeze = lora_freeze_tree(self._tree)
        self.optimizer = lora_optimizer(
            self.freeze, cfg.tune_lr, self.tcfg.grad_clip
        )
        self._opt_state = self.optimizer.init(self._tree)
        self._step = None
        self._batch = None
        self.steps_run = 0

    # -------------------------------------------------------- job setup

    def _warm_factors(self, base: str) -> dict:
        """Stored (effective) factors of the tenant's latest version —
        the warm start — or a fresh A-random/B-zero init for a tenant
        the registry has never seen."""
        try:
            return self.registry.factors(base)
        except UnknownAdapterError:
            pass
        rng = np.random.default_rng(zlib.crc32(base.encode("utf-8")))
        fac = {}
        for path, (n, d_in, d_out) in self.registry.targets.items():
            fac[path] = {
                "A": rng.normal(0.0, INIT_SCALE / self.rank,
                                (n, d_in, self.rank)).astype(np.float32),
                "B": np.zeros((n, self.rank, d_out), np.float32),
            }
        return fac

    def start_job(self, job: TuneJob) -> None:
        """Splice the job's warm-start factors into the pools, pack its
        examples, reset optimizer state, and (first job only) compile
        the masked train step."""
        base, ver = split_adapter_version(job.adapter)
        if ver is not None:
            raise TuneError(
                f"tune jobs target a BARE adapter name, got {job.adapter!r}"
            )
        warm = self._warm_factors(base)
        pools = {}
        for path, (n, d_in, d_out) in self.registry.targets.items():
            fac = warm.get(path)
            if fac is not None:
                a = jnp.asarray(fac["A"], jnp.float32)[:, None]
                b = jnp.asarray(fac["B"], jnp.float32)[:, None]
            else:
                a = jnp.zeros((n, 1, d_in, self.rank), jnp.float32)
                b = jnp.zeros((n, 1, self.rank, d_out), jnp.float32)
            pools[path] = {"A": a, "B": b}
        self._tree = attach_adapter_pools(self._tree, pools)
        self._opt_state = self.optimizer.init(self._tree)
        self._batch = pack_examples(
            job.examples, self.cfg.tune_batch_size, self.cfg.tune_seq_len
        )
        if self._step is None:
            bsz = self.cfg.tune_batch_size
            self._step = make_train_step(
                self.tcfg, self.optimizer, self.mesh,
                self._tree, self._opt_state,
                freeze=self.freeze,
                # every batch row reads pool row 0 — the tenant's
                # factors; bound at trace time so the ids are jit
                # constants, not (integer) differentiated arguments
                params_map=lambda p: bind_adapter_ids(
                    p, jnp.zeros((bsz,), jnp.int32)
                ),
            )

    # -------------------------------------------------------- train/fin

    def train_step(self, job: TuneJob) -> float:
        """One masked step on the job's packed batch; returns the mean
        next-token loss (a host float — the one sync per step)."""
        if self._batch is None:
            raise TuneError(f"job {job.job_id} was never started")
        x, y = self._batch
        self._tree, self._opt_state, loss, _ = self._step(
            self._tree, self._opt_state, x, y
        )
        self.steps_run += 1
        return float(loss)

    def finish_job(self, job: TuneJob) -> str:
        """Register the trained factors as the tenant's next version;
        returns the canonical ``name@v(N+1)`` key (``name`` for a
        first-ever version — the PR-15 fast path)."""
        base, _ = split_adapter_version(job.adapter)
        fac = {}
        for path in self.registry.targets:
            node = self._tree
            for name in path.split("/"):
                node = node[name]
            pool = node["lora"]
            fac[path] = {
                "A": np.asarray(pool["A"][:, 0], np.float32),
                "B": np.asarray(pool["B"][:, 0], np.float32),
            }
        # alpha=rank => scale 1.0: the trainer optimizes the EFFECTIVE
        # factors, so the stored B must be the trained B bit-exactly
        return self.registry.register(base, fac, alpha=self.rank)
