"""The tuning plane's fabric face: service, trainer replica, provisioner.

:class:`TuningService` binds the job queue to one :class:`LoraTrainer`
and the PR-18 SLO monitor: every ``tick()`` advances the active job by
ONE train step — unless the shared monitor is in breach, in which case
the lane YIELDS (records ``tune_yields``, runs nothing) and serving
reclaims the iteration.  On a job's last step the trained factors
hot-register as the tenant's next version and the ``deploy`` callback
(the fabric controller's ``ensure_adapter`` push) propagates the new
key fabric-wide — zero offline steps between "tenant POSTs examples"
and "new version takes traffic".

:class:`TrainerReplica` is the router/autoscale-visible face of a
tuning lane: it duck-types ``EngineReplica`` (role ``"trainer"``,
pending = tune-queue depth, ``step()`` = one service tick) so the
autoscaler sizes the trainer tier with the exact machinery that sizes
prefill/decode — but the router's placement paths EXCLUDE the role, so
generation traffic can never land on a lane (``submit`` raises as a
hard backstop).

:class:`TrainerProvisioner` mints lanes for autoscale scale-ups and
delegates serving roles to a wrapped base provisioner.
"""

from __future__ import annotations

import time

from mamba_distributed_tpu.obs import NULL_TRACER
from mamba_distributed_tpu.serving.autoscale.provisioner import (
    ReplicaProvisioner,
)
from mamba_distributed_tpu.serving.replica import ReplicaState
from mamba_distributed_tpu.serving.tuning.jobs import (
    TuneError,
    TuneJob,
    TuneJobQueue,
)
from mamba_distributed_tpu.utils.metrics import ServingMetrics


class TuningService:
    """One fabric's online-tuning plane: queue + trainer + SLO yield.

    Jobs serialize through the single trainer (static shapes — the
    train step compiles once); more trainer replicas mean more
    ``tick()`` calls per fabric iteration, not concurrent jobs.

    Args:
      trainer: the :class:`LoraTrainer` lane.
      queue: shared :class:`TuneJobQueue` (fresh one by default).
      slo: optional shared ``obs.SLOMonitor`` — ``any_breach()`` gates
        every tick (training yields while serving latency is burning).
      metrics: optional ``ServingMetrics`` for the ``tuning`` summary
        block (the first attached :class:`TrainerReplica` installs its
        own when None).
      deploy: optional ``(canonical_key) -> None`` called after a
        version registers — the controller wires
        ``FabricController.ensure_adapter`` here so every worker's
        registry learns the new version before it takes traffic.
    """

    def __init__(self, trainer, *, queue=None, slo=None, metrics=None,
                 deploy=None):
        self.trainer = trainer
        self.queue = queue if queue is not None else TuneJobQueue()
        self.slo = slo
        self.metrics = metrics
        self.deploy = deploy
        self._active: TuneJob | None = None

    # ------------------------------------------------------------ intake

    @property
    def depth(self) -> int:
        """Unfinished jobs (active + queued) — the trainer tier's
        pressure signal."""
        return (1 if self._active is not None else 0) + self.queue.depth

    def submit(self, adapter: str, examples, steps: int | None = None
               ) -> TuneJob:
        """Enqueue one tune job (the ``/v1/tune`` POST body lands
        here); validation failures raise the named :class:`TuneError`
        at the boundary."""
        if steps is None:
            steps = self.trainer.cfg.tune_steps
        job = self.queue.submit(adapter, examples, steps)
        if self.metrics is not None:
            self.metrics.record_tune_job("submitted", job.status())
        return job

    def status(self, job_id: str) -> dict:
        return self.queue.status(job_id)

    # -------------------------------------------------------------- tick

    def tick(self) -> bool:
        """Advance the tuning plane by at most ONE train step; returns
        True when device work ran (False: idle queue or SLO yield).

        The yield check runs BEFORE the step, every tick — a job that
        converges over N steps re-checks serving pressure N times, so
        a breach mid-job pauses training within one iteration and the
        job resumes (state intact — params and optimizer state live on
        the trainer) once the p95s clear."""
        job = self._active
        if job is None:
            job = self.queue.next_queued()
            if job is None:
                return False
            self._active = job
        if self.slo is not None and self.slo.any_breach():
            if self.metrics is not None:
                self.metrics.record_tune_yield()
            return False
        t0 = time.perf_counter()
        try:
            if job.state == "queued":
                self.trainer.start_job(job)
                job.state = "running"
            loss = self.trainer.train_step(job)
        except Exception as e:  # noqa: BLE001 — job-scoped failure
            self._fail(job, e)
            return True
        job.step += 1
        job.losses.append(loss)
        if self.metrics is not None:
            self.metrics.record_tune_step(
                (time.perf_counter() - t0) * 1000.0, loss
            )
        if job.step >= job.steps:
            self._finish(job)
        return True

    def _finish(self, job: TuneJob) -> None:
        try:
            key = self.trainer.finish_job(job)
        except Exception as e:  # noqa: BLE001 — registration failed
            self._fail(job, e)
            return
        job.deployed = key
        job.state = "completed"
        self._active = None
        if self.metrics is not None:
            self.metrics.record_tune_job("completed", job.status())
            self.metrics.record_tune_deploy()
        if self.deploy is not None:
            try:
                self.deploy(key)
            except Exception as e:  # noqa: BLE001 — push is best-effort
                # the version IS registered (a shared-registry fabric
                # already resolves it); surface the push failure on the
                # job instead of un-completing it
                job.error = f"deploy push: {type(e).__name__}: {e}"

    def _fail(self, job: TuneJob, e: Exception) -> None:
        job.state = "failed"
        job.error = f"{type(e).__name__}: {e}"
        self._active = None
        if self.metrics is not None:
            self.metrics.record_tune_job("failed", job.status())

    def summary(self) -> dict:
        out = self.queue.summary()
        out["active"] = (self._active.job_id
                         if self._active is not None else None)
        return out


# --------------------------------------------------- router-facing lane


class _TrainerScheduler:
    """Depth-only scheduler façade (autoscale's ``_queued`` fallback
    reads ``engine.scheduler.depth``)."""

    def __init__(self, service: TuningService):
        self._service = service

    @property
    def depth(self) -> int:
        return self._service.depth


class _TrainerEngine:
    """Duck-typed engine façade for the router's and worker's
    non-placement reads (``summary()`` takes ``engine.metrics``,
    autoscale takes ``engine.scheduler.depth``, the wire worker's
    ``_stats``/``obs_pull`` take capacity/slots/tracer).  Placement
    never sees a trainer — the router excludes the role — so none of
    the engine's serving surface exists here."""

    hybrid = False
    migrate_hook = None
    capacity = 0
    _slots = ()  # no slot pool: a lane holds jobs, not streams

    def __init__(self, service: TuningService, metrics: ServingMetrics,
                 tracer=NULL_TRACER):
        self.scheduler = _TrainerScheduler(service)
        self.metrics = metrics
        self.tracer = tracer


class TrainerReplica:
    """One tuning lane as a fabric replica (role ``"trainer"``).

    ``accepting`` stays True while active — to the AUTOSCALER it means
    "counts toward the tier" (an all-``accepting=False`` tier would
    read as empty, i.e. infinite pressure); generation traffic is kept
    out by the router's role exclusion, with ``submit`` raising as the
    backstop.  ``step()`` runs one service tick, so a router-driven
    fabric trains exactly when it steps — and yields exactly when the
    SLO monitor says serving needs the iteration back.

    Trainer death mid-job (the failure matrix in docs/SERVING.md): the
    lane dies, the SERVICE survives — jobs and trainer state are
    fabric-owned, so a controller-driven fabric keeps ticking and a
    replacement lane (autoscale re-provision) resumes the same queue.
    """

    role = "trainer"

    def __init__(self, replica_id: int, service: TuningService, *,
                 metrics: ServingMetrics | None = None,
                 tracer=NULL_TRACER):
        self.replica_id = replica_id
        self.service = service
        if metrics is None:
            metrics = ServingMetrics(1, replica=replica_id)
        metrics.replica = replica_id
        metrics.configure_tuning()
        self.metrics = metrics
        if service.metrics is None:
            # first lane installs the service's counter sink, so tune
            # steps/deploys/yields land in a replica-stamped summary
            service.metrics = metrics
        self.engine = _TrainerEngine(service, metrics, tracer)
        self.state = ReplicaState.ACTIVE

    # ---------------------------------------------------------- lifecycle

    @property
    def accepting(self) -> bool:
        return self.state is ReplicaState.ACTIVE

    @property
    def alive(self) -> bool:
        return self.state is not ReplicaState.DEAD

    @property
    def pending(self) -> int:
        """Tune jobs this lane would work (0 once draining/dead: the
        queue is fabric-owned, a retiring lane holds nothing — so the
        autoscaler's retire sweep releases it immediately)."""
        return self.service.depth if self.accepting else 0

    def drain(self, requeue: bool = False) -> list[int]:
        if self.state is ReplicaState.ACTIVE:
            self.state = ReplicaState.DRAINING
        return []

    def mark_dead(self) -> None:
        self.state = ReplicaState.DEAD

    # ---------------------------------------------------------- placement

    def place_cost(self, request=None) -> float:
        return float("inf")

    def submit(self, request, force: bool = False) -> int:
        raise TuneError(
            f"replica {self.replica_id} is a trainer lane — it takes "
            f"tune jobs, never generation traffic (router placement "
            f"excludes the role; this is the backstop)"
        )

    def step(self):
        """One tuning tick; no token events (the router appends
        nothing for this replica)."""
        if self.alive and self.accepting:
            self.service.tick()
        return []

    def replay(self, local_id: int, from_index: int = 0):
        return None


class TrainerProvisioner(ReplicaProvisioner):
    """Autoscale provisioner for the trainer tier.

    ``"trainer"`` provisions a fresh :class:`TrainerReplica` over the
    SHARED :class:`TuningService` (lanes multiply tick rate, not
    state); every other role delegates to ``base`` — wrap the fabric's
    existing ``EngineProvisioner``/``ProcessProvisioner`` so one
    controller sizes serving and training tiers together.
    """

    def __init__(self, service: TuningService, base=None):
        self.service = service
        self.base = base
        self.provisioned = 0
        self.retired = 0

    def provision(self, replica_id: int, role: str):
        if role == "trainer":
            self.provisioned += 1
            return TrainerReplica(replica_id, self.service)
        if self.base is None:
            raise ValueError(
                f"TrainerProvisioner has no base provisioner for "
                f"role {role!r}"
            )
        return self.base.provision(replica_id, role)

    def retire(self, replica) -> None:
        if getattr(replica, "role", None) == "trainer":
            # nothing backs a lane beyond the shared service
            self.retired += 1
            return
        if self.base is not None:
            self.base.retire(replica)
