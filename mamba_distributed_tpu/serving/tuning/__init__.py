"""Online per-tenant LoRA tuning on the serving fabric.

No offline pipeline: tenants POST token-id examples to ``/v1/tune``,
a trainer-role replica fine-tunes their {A, B} factors against the
frozen base with the training stack's own jitted step, and the
converged factors hot-register as ``name@v(N+1)`` fabric-wide — new
requests A/B-route across the last two versions
(cfg.lora_ab_fraction) while in-flight streams keep their pinned
version (or hot-swap mid-stream, serving/engine.hot_swap_adapter).

Layout:
  jobs.py     TuneJob / TuneJobQueue / TuneError — intake + lifecycle
  trainer.py  LoraTrainer — masked train step over attached pools
  service.py  TuningService (SLO-yielding tick loop), TrainerReplica
              (the router/autoscale face), TrainerProvisioner
"""

from mamba_distributed_tpu.serving.tuning.jobs import (
    TuneError,
    TuneJob,
    TuneJobQueue,
)
from mamba_distributed_tpu.serving.tuning.service import (
    TrainerProvisioner,
    TrainerReplica,
    TuningService,
)
from mamba_distributed_tpu.serving.tuning.trainer import (
    LoraTrainer,
    lora_freeze_tree,
    lora_optimizer,
    pack_examples,
)

__all__ = [
    "LoraTrainer",
    "TrainerProvisioner",
    "TrainerReplica",
    "TuneError",
    "TuneJob",
    "TuneJobQueue",
    "TuningService",
    "lora_freeze_tree",
    "lora_optimizer",
    "pack_examples",
]
