"""Tune-job bookkeeping for online per-tenant LoRA training.

A :class:`TuneJob` is one tenant's request to fine-tune its adapter on
the serving fabric: a batch of token-id example sequences, a step
budget, and the lifecycle state the ``/v1/tune`` status surface
reports.  Jobs target a BARE adapter name — versions are minted by the
fabric at deploy time (``AdapterRegistry.register`` assigns
``v(N+1)``), never by the tenant, so a job can neither overwrite nor
roll back history.

:class:`TuneJobQueue` is the FIFO the trainer tier drains
(serving/tuning/service.py): submission validates the payload up
front — a malformed job must fail at the HTTP/RPC boundary with a
named error, not steps later inside a jitted train step.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque

from mamba_distributed_tpu.serving.adapters import split_adapter_version


class TuneError(RuntimeError):
    """Named failure for the online-tuning plane: malformed job
    payloads, unknown job ids, generation traffic submitted to a
    trainer replica.  RuntimeError (not ValueError) on purpose — the
    wire layer's ``retriable`` flag keys on ValueError, and none of
    these are retriable as-is."""


# job lifecycle: queued -> running -> completed | failed; the queue
# only ever moves a job forward (status polls see a monotone state)
JOB_STATES = ("queued", "running", "completed", "failed")


@dataclasses.dataclass
class TuneJob:
    """One tenant's fine-tune request and its live state."""

    job_id: str
    adapter: str  # BARE base name; the deploy mints adapter@v(N+1)
    examples: list  # list of token-id sequences (list[list[int]])
    steps: int  # train-step budget (cfg.tune_steps default)
    state: str = "queued"
    step: int = 0  # train steps completed so far
    losses: list = dataclasses.field(default_factory=list)
    deployed: str | None = None  # canonical registered key once live
    error: str | None = None

    def status(self) -> dict:
        """The ``/v1/tune/<id>`` status payload (wire-encodable: plain
        ints/floats/strings only)."""
        out = {
            "job_id": self.job_id,
            "adapter": self.adapter,
            "state": self.state,
            "step": self.step,
            "steps": self.steps,
            "examples": len(self.examples),
        }
        if self.losses:
            out["loss"] = self.losses[-1]
        if self.deployed is not None:
            out["deployed"] = self.deployed
        if self.error is not None:
            out["error"] = self.error
        return out


class TuneJobQueue:
    """FIFO of :class:`TuneJob` with full-history status lookup.

    Completed/failed jobs stay in the table (bounded by ``keep`` — a
    long-lived fabric's status surface must not grow without bound),
    only queued jobs occupy the FIFO.
    """

    def __init__(self, keep: int = 256):
        self._jobs: "OrderedDict[str, TuneJob]" = OrderedDict()
        self._fifo: deque[TuneJob] = deque()
        self._minted = 0
        self.keep = keep

    # ----------------------------------------------------------- submit

    def submit(self, adapter: str, examples, steps: int) -> TuneJob:
        """Validate and enqueue one job; returns it (the caller reads
        ``job_id`` off for the status surface)."""
        if not adapter or not isinstance(adapter, str):
            raise TuneError("tune job needs a non-empty adapter name")
        base, ver = split_adapter_version(adapter)
        if ver is not None:
            raise TuneError(
                f"tune jobs target a BARE adapter name; got "
                f"{adapter!r} — versions are minted by the fabric at "
                f"deploy time ({base}@v{ver + 1} next), never pinned "
                f"by the tenant"
            )
        if not examples:
            raise TuneError("tune job needs at least one example")
        cleaned = []
        for i, ex in enumerate(examples):
            try:
                toks = [int(t) for t in ex]
            except (TypeError, ValueError):
                raise TuneError(
                    f"tune example {i} is not a token-id sequence"
                ) from None
            if len(toks) < 2:
                raise TuneError(
                    f"tune example {i} needs >= 2 tokens (next-token "
                    f"loss has nothing to predict from {len(toks)})"
                )
            cleaned.append(toks)
        if steps < 1:
            raise TuneError(f"tune steps must be >= 1, got {steps}")
        self._minted += 1
        job = TuneJob(job_id=f"tune-{self._minted}", adapter=adapter,
                      examples=cleaned, steps=int(steps))
        self._jobs[job.job_id] = job
        self._fifo.append(job)
        self._prune()
        return job

    def _prune(self) -> None:
        # only terminal jobs are evictable; queued/running ones are the
        # fabric's live obligations
        while len(self._jobs) > self.keep:
            for jid, job in self._jobs.items():
                if job.state in ("completed", "failed"):
                    del self._jobs[jid]
                    break
            else:
                return

    # ----------------------------------------------------------- lookup

    def get(self, job_id: str) -> TuneJob:
        try:
            return self._jobs[job_id]
        except KeyError:
            raise TuneError(
                f"unknown tune job {job_id!r} (completed jobs age out "
                f"after {self.keep} entries)"
            ) from None

    def status(self, job_id: str) -> dict:
        return self.get(job_id).status()

    def next_queued(self) -> TuneJob | None:
        """Pop the oldest queued job (None when the FIFO is dry)."""
        while self._fifo:
            job = self._fifo.popleft()
            if job.state == "queued":
                return job
        return None

    @property
    def depth(self) -> int:
        """Queued-but-unstarted jobs — the trainer tier's autoscale
        pressure signal (mirrors the scheduler-depth shape)."""
        return sum(1 for j in self._fifo if j.state == "queued")

    def summary(self) -> dict:
        states = {s: 0 for s in JOB_STATES}
        for job in self._jobs.values():
            states[job.state] += 1
        return {"depth": self.depth, "jobs": states}
