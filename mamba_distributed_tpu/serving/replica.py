"""One engine replica in the data-parallel serving fabric.

A replica is the router's placement unit (serving/router.py): a full
``ServingEngine`` — slot pool, page pool, scheduler, optionally
mesh-sharded over a ``parallel/mesh.serving_mesh`` — plus the lifecycle
flag and load signals the router places against.  Weights are shared
read-only across replicas (engines never donate params), so N replicas
cost N slot pools, not N param copies.

Lifecycle: ACTIVE -> DRAINING (graceful retire: finish everything
already submitted, accept nothing new) or -> DEAD (failover: the
ROUTER requeues the replica's unfinished requests elsewhere — a dead
replica is never trusted to report anything, and is never stepped
again).

Telemetry: every serving_tick/request record the replica's engine
emits is stamped with its ``replica`` id, and every per-request span
with the router-minted ``trace`` id — give each replica its OWN
``SpanTracer`` (``RequestRouter(replica_tracers=[...])``) and
``scripts/trace_export.py`` merges the streams into one Perfetto
timeline with a process track per replica, a request's spans
flow-linked from the router's ``serving_route`` through to its first
decode tick here.
"""

from __future__ import annotations

import enum

from mamba_distributed_tpu.obs import NULL_TRACER
from mamba_distributed_tpu.serving.engine import ServingEngine
from mamba_distributed_tpu.utils.metrics import ServingMetrics


class ReplicaState(enum.Enum):
    ACTIVE = "active"      # accepting placements and ticking
    DRAINING = "draining"  # finishing what it holds; no new placements
    DEAD = "dead"          # failed over; never stepped again


# placement discount for a replica whose device adapter cache already
# holds the request's LoRA factors (serving/adapters.py): resident
# factors skip an upload AND keep the cache's slot churn down, the
# same shape as the PR-9 prefix-cache affinity — worth about half a
# replica's load range, so affinity steers ties and near-ties without
# overriding a genuinely overloaded-vs-idle gap
ADAPTER_AFFINITY = 0.5

# the disaggregated prefill/decode tiers (docs/SERVING.md
# "Disaggregated tiers"): "mixed" is the exact pre-disagg status quo;
# "prefill" replicas take the long prompts, run the chunked prefill
# and MIGRATE the finished carry out (the router installs
# engine.migrate_hook); "decode" replicas take short prompts and
# migrated-in artifacts, never a long prompt's prefill.  "trainer" is
# the online-tuning lane (serving/tuning.TrainerReplica — NOT an
# engine replica): it takes tune jobs, never generation traffic
# (router placement excludes the role), and the autoscaler sizes its
# tier on tune-queue depth alone
REPLICA_ROLES = ("mixed", "prefill", "decode", "trainer")


class EngineReplica:
    """One ``ServingEngine`` + the host-side routing state around it.

    The router reads ``place_cost()`` for least-loaded placement
    (applied WITHIN the role-filtered tier — see ``role`` and
    serving/router._role_filter), ``drain()`` to retire the replica
    gracefully, and ``mark_dead()`` on failure (requeueing is the
    router's job — it owns the request records; the replica only stops
    accepting and ticking).

    ``role`` ("mixed" default) assigns the replica to a disaggregated
    tier: the router routes long prompts (above
    ``cfg.disagg_prompt_threshold``) to "prefill" replicas — whose
    engines hand the finished carry off via ``migrate_hook`` instead
    of decoding — and short prompts plus migrated-in artifacts to
    "decode"/"mixed" replicas.  "mixed" everywhere (or threshold 0) is
    the exact pre-disagg fabric.
    """

    def __init__(self, replica_id: int, params: dict, cfg, *, mesh=None,
                 metrics: ServingMetrics | None = None, tracer=NULL_TRACER,
                 role: str = "mixed", **engine_kw):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        if role == "trainer":
            raise ValueError(
                "role 'trainer' is serving/tuning.TrainerReplica's — "
                "an engine replica serves; it cannot take tune jobs"
            )
        self.role = role
        self.replica_id = replica_id
        if metrics is None:
            metrics = ServingMetrics(engine_kw.get("capacity", 8),
                                     replica=replica_id)
        # every serving_tick/request record this replica emits carries
        # its id, so a shared jsonl stream splits back per replica
        metrics.replica = replica_id
        self.engine = ServingEngine(params, cfg, metrics=metrics,
                                    tracer=tracer, mesh=mesh, **engine_kw)
        self.state = ReplicaState.ACTIVE

    # ---------------------------------------------------------- lifecycle

    @property
    def accepting(self) -> bool:
        return self.state is ReplicaState.ACTIVE

    @property
    def alive(self) -> bool:
        return self.state is not ReplicaState.DEAD

    @property
    def pending(self) -> int:
        """Unfinished requests resident here (0 once dead: whatever it
        held is the router's to requeue)."""
        return self.engine.pending if self.alive else 0

    def drain(self, requeue: bool = False) -> list[int]:
        """Stop accepting placements; in-flight (and already-queued)
        requests run to completion via normal ``step()`` calls.

        With ``requeue`` the engine's queued-but-UNSTARTED requests
        (status QUEUED, no resume snapshot) are withdrawn and their
        engine-local ids returned for the ROUTER to re-place on the
        survivors — the drain shutdown path
        (``RequestRouter.drain(requeue_queued=True)``): previously a
        drain initiated from outside ``serve()`` only let in-flight
        work survive, stranding the queue unless something kept
        stepping the retiring replica."""
        if self.state is ReplicaState.ACTIVE:
            self.state = ReplicaState.DRAINING
        if not requeue:
            return []
        return self.engine.withdraw_queued()

    def mark_dead(self) -> None:
        self.state = ReplicaState.DEAD

    # ---------------------------------------------------------- placement

    def place_cost(self, request=None) -> float:
        """Placement cost (lower is better) — one of the THREE terms of
        the router's placement contract, which is NOT plain least-
        loaded: (1) the router first filters candidates by ROLE (long
        prompts -> the prefill tier, shorts and migrated artifacts ->
        decode/mixed; serving/router._role_filter — this method never
        sees replicas outside the request's tier), then picks the
        lowest cost = (2) load: queued + resident work per slot, plus
        KV page-pool pressure for hybrid engines — a replica whose
        pages are nearly gone would make a new hybrid request WAIT at
        admission even with slots free, so free pages weigh in next to
        queue depth — minus (3) prefix-cache AFFINITY (PR 9): the
        fraction of this prompt's prefill the replica's cache could
        skip (engine.prefix_hit_fraction, a pure probe) — skipping a
        preamble's prefill is worth more than an idle cold replica, so
        shared-prefix traffic converges on warm caches instead of
        spraying cold prefills across the fabric — minus (4) adapter
        AFFINITY (multi-tenant LoRA): ``ADAPTER_AFFINITY`` when the
        request's adapter factors are resident on this replica's
        device cache (engine.adapter_resident, a pure probe), so one
        tenant's traffic converges on the replicas already serving its
        factors instead of churning every cache in the fabric."""
        eng = self.engine
        load = (eng.scheduler.depth + len(eng._slots)) / eng.capacity
        if eng.hybrid:
            load += eng.page_pool.pages_in_use / eng.page_pool.num_pages
        adapter = (getattr(request, "adapter", None)
                   if request is not None else None)
        if request is not None and eng.prefix_cache is not None:
            load -= eng.prefix_hit_fraction(request.prompt_ids,
                                            adapter=adapter)
        if adapter and eng.adapter_resident(adapter):
            # (4) adapter AFFINITY (multi-tenant LoRA): the request's
            # factors are already on this replica's device cache — no
            # upload, no slot churn; same shape as the prefix term
            load -= ADAPTER_AFFINITY
        return load

    def submit(self, request, force: bool = False) -> int:
        """Place a request here; returns the ENGINE-local request id
        (the router maps it back to its global id).  ``force`` bypasses
        the accepting check — ONLY for the router's drain fallback,
        which returns a withdrawn-but-unplaceable request to the
        draining replica it came from rather than losing it."""
        if not self.accepting and not force:
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state.value}, not "
                f"accepting placements"
            )
        return self.engine.submit(request)

    def step(self):
        """One engine iteration (no-op once dead)."""
        return self.engine.step() if self.alive else []

    def replay(self, local_id: int, from_index: int = 0) -> dict | None:
        """Replay view of one stream for the SSE resume path (the
        router's ``attach_resumed``): the engine's ``stream_state`` —
        tokens already generated from ``from_index`` on, done flag, and
        (for in-flight streams) the original request so a later
        failover can still re-derive the stream.  None when the id is
        unknown here (or the replica is dead — nothing to re-attach
        to)."""
        if not self.alive:
            return None
        return self.engine.stream_state(local_id, from_index)
