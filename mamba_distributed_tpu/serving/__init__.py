"""Continuous-batching serving with a pooled recurrent-state cache.

docs/SERVING.md has the architecture; the short version:

  state_cache  fixed-capacity slot pool of per-layer conv+SSM states
               (+ per-slot sampling params), jit insert/evict, plus
               partial-prefill residency (stash/read/finish)
  prefill      chunked prompt prefill: planner + one compiled chunk
               step threading the mixers' conv/SSM carries
  engine       one compiled decode tick advances all occupied slots;
               admission + budgeted prefill chunks between ticks,
               no retracing; optionally mesh-sharded over a
               serving_mesh's data axis (the shard_slots path)
  scheduler    FCFS queue + request lifecycle (queued -> prefill ->
               decode -> finished)
  replica      one engine + lifecycle (active/draining/dead) and a
               disagg tier role (mixed/prefill/decode) — the router's
               placement unit
  router       data-parallel serving fabric front end: role-filtered
               least-loaded placement over N replicas (prefix-cache
               affinity discounts warm replicas), drain, failover with
               replay dedup, and the prefill->decode tier migration
               (docs/SERVING.md "Multi-host serving" and
               "Disaggregated tiers")
  prefix_cache host-side LRU of chunk-boundary carry snapshots keyed
               by prompt-prefix hash — near-zero TTFT for shared
               prompts; hybrid entries pin KV pages copy-on-write
               (docs/SERVING.md "Prefix caching & preemption")
  adapters     multi-tenant LoRA serving: named adapter registry,
               refcounted/LRU device factor cache, and the segmented
               batched-LoRA pools one tick launch consumes — slots
               running different adapters share one compiled launch
               (docs/SERVING.md "Multi-tenant LoRA")
  spec_decode  speculative decoding on the chunk machinery: K-token
               draft-verify ticks (one lm_verify_chunk launch commits
               up to K+2 greedy tokens per full weight read) with
               n-gram and companion-model drafters — lossless under
               argmax (docs/SERVING.md "Speculative decoding")
  sessions/    durable session fabric: tiered park/resume store
               (device slot -> host RAM -> disk) whose artifact is the
               migration artifact — parked sessions cost zero device
               memory and resume bit-exactly on any replica
               (docs/SERVING.md "Durable sessions")
  autoscale/   elastic fabric control plane: SLO/queue-driven
               AutoscaleController sizing the fleet through a
               ReplicaProvisioner (live-attach via router.add_replica,
               drain-based scale-down), plus AdmissionController load
               shedding — queue deadlines + a fabric queue cap, the
               named AdmissionRejected -> HTTP 429
               (docs/SERVING.md "Elastic fabric")
  tuning/      online per-tenant LoRA training ON the fabric: a
               trainer-role replica runs a frozen-base masked train
               step over the tenant's {A, B} factor pools, yields to
               serving on SLO breach, and hot-registers the trained
               ``name@v(N+1)`` fabric-wide — new submits A/B-route
               across the last two versions, zero offline steps
               (docs/SERVING.md "Online adapter tuning")
  service/     the deployable shape of all of the above: versioned
               wire codec, one replica per worker PROCESS, an asyncio
               HTTP/SSE front end running the UNCHANGED router, and
               heartbeat-driven failover over the wire
               (docs/SERVING.md "Deploying as a service";
               scripts/serve_worker.py + scripts/serve_fabric.py)
"""

from mamba_distributed_tpu.serving.adapters import (
    AdapterCache,
    AdapterCacheError,
    AdapterRegistry,
    AdapterVersionError,
    UnknownAdapterError,
)
from mamba_distributed_tpu.serving.autoscale import (
    AdmissionController,
    AdmissionRejected,
    AutoscaleController,
    AutoscalePolicy,
    EngineProvisioner,
    ProcessProvisioner,
    ReplicaProvisioner,
)
from mamba_distributed_tpu.serving.engine import ServingEngine
from mamba_distributed_tpu.serving.prefix_cache import (
    PrefixCache,
    PrefixEntry,
)
from mamba_distributed_tpu.serving.replica import (
    REPLICA_ROLES,
    EngineReplica,
    ReplicaState,
)
from mamba_distributed_tpu.serving.router import RequestRouter
from mamba_distributed_tpu.serving.sessions import (
    DiskSessionStore,
    SessionStore,
    SessionStoreError,
)
from mamba_distributed_tpu.serving.prefill import (
    ChunkPlan,
    chunked_prefill,
    plan_chunks,
)
from mamba_distributed_tpu.serving.spec_decode import (
    Drafter,
    ModelDrafter,
    NGramDrafter,
)
from mamba_distributed_tpu.serving.scheduler import (
    FCFSScheduler,
    GenerationRequest,
    GenerationResult,
    RequestStatus,
    TenantQuotaExceeded,
    TokenEvent,
)
from mamba_distributed_tpu.serving.tuning import (
    LoraTrainer,
    TrainerProvisioner,
    TrainerReplica,
    TuneError,
    TuneJob,
    TuneJobQueue,
    TuningService,
)
from mamba_distributed_tpu.serving.state_cache import (
    PagePool,
    PagePoolError,
    evict,
    init_pool,
    insert,
)

__all__ = [
    "AdapterCache",
    "AdapterCacheError",
    "AdapterRegistry",
    "AdapterVersionError",
    "UnknownAdapterError",
    "AdmissionController",
    "AdmissionRejected",
    "AutoscaleController",
    "AutoscalePolicy",
    "EngineProvisioner",
    "ProcessProvisioner",
    "ReplicaProvisioner",
    "ChunkPlan",
    "DiskSessionStore",
    "Drafter",
    "EngineReplica",
    "ModelDrafter",
    "NGramDrafter",
    "FCFSScheduler",
    "GenerationRequest",
    "GenerationResult",
    "PagePool",
    "PagePoolError",
    "PrefixCache",
    "PrefixEntry",
    "REPLICA_ROLES",
    "ReplicaState",
    "RequestRouter",
    "RequestStatus",
    "ServingEngine",
    "SessionStore",
    "SessionStoreError",
    "LoraTrainer",
    "TenantQuotaExceeded",
    "TokenEvent",
    "TrainerProvisioner",
    "TrainerReplica",
    "TuneError",
    "TuneJob",
    "TuneJobQueue",
    "TuningService",
    "chunked_prefill",
    "evict",
    "init_pool",
    "insert",
    "plan_chunks",
]
