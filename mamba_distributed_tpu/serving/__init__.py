"""Continuous-batching serving with a pooled recurrent-state cache.

docs/SERVING.md has the architecture; the short version:

  state_cache  fixed-capacity slot pool of per-layer conv+SSM states
               (+ per-slot sampling params), jit insert/evict, plus
               partial-prefill residency (stash/read/finish)
  prefill      chunked prompt prefill: planner + one compiled chunk
               step threading the mixers' conv/SSM carries
  engine       one compiled decode tick advances all occupied slots;
               admission + budgeted prefill chunks between ticks,
               no retracing
  scheduler    FCFS queue + request lifecycle (queued -> prefill ->
               decode -> finished)
"""

from mamba_distributed_tpu.serving.engine import ServingEngine
from mamba_distributed_tpu.serving.prefill import (
    ChunkPlan,
    chunked_prefill,
    plan_chunks,
)
from mamba_distributed_tpu.serving.scheduler import (
    FCFSScheduler,
    GenerationRequest,
    GenerationResult,
    RequestStatus,
    TokenEvent,
)
from mamba_distributed_tpu.serving.state_cache import evict, init_pool, insert

__all__ = [
    "ChunkPlan",
    "FCFSScheduler",
    "GenerationRequest",
    "GenerationResult",
    "RequestStatus",
    "ServingEngine",
    "TokenEvent",
    "chunked_prefill",
    "evict",
    "init_pool",
    "insert",
    "plan_chunks",
]
