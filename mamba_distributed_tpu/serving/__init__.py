"""Continuous-batching serving with a pooled recurrent-state cache.

docs/SERVING.md has the architecture; the short version:

  state_cache  fixed-capacity slot pool of per-layer conv+SSM states
               (+ per-slot sampling params), jit insert/evict
  engine       one compiled decode tick advances all occupied slots;
               admission between ticks, no retracing
  scheduler    FCFS queue + request lifecycle (queued -> prefill ->
               decode -> finished)
"""

from mamba_distributed_tpu.serving.engine import ServingEngine
from mamba_distributed_tpu.serving.scheduler import (
    FCFSScheduler,
    GenerationRequest,
    GenerationResult,
    RequestStatus,
    TokenEvent,
)
from mamba_distributed_tpu.serving.state_cache import evict, init_pool, insert

__all__ = [
    "FCFSScheduler",
    "GenerationRequest",
    "GenerationResult",
    "RequestStatus",
    "ServingEngine",
    "TokenEvent",
    "evict",
    "init_pool",
    "insert",
]
