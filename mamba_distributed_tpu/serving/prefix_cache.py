"""Prefix-state cache: O(1) carry snapshots keyed by prompt-prefix hash.

Millions of requests share system prompts and few-shot preambles, and a
Mamba prefix collapses to a *fixed-size* conv+SSM carry — the chunk
planner (serving/prefill.py) already produces exactly these carries at
chunk boundaries, so recomputing a shared preamble is pure waste
("Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching for Inference", PAPERS.md: portable O(1) snapshots as the
serving primitive).  This module is the host-side LRU store:

  * **Keys** hash the exact chunk LAYOUT prefix, not just the token
    prefix: a chunk-boundary key covers ``(chunk, pad, tokens so far)``
    — the inputs that fully determine the carry after that chunk.  Two
    requests share a snapshot iff their padded layouts agree on every
    chunk up to the boundary, which is what makes a warm stream
    BIT-IDENTICAL to a cold one: the cached carry is the literal output
    of the identical computation the cold run would have executed (the
    SSM carry re-associates fp32 sums across chunk boundaries, so a
    looser key — matching token prefixes across different layouts —
    would only be ~1e-6-equivalent, not exact).  The practical
    consequence: prompts sharing a preamble share snapshots when their
    total lengths are congruent mod the chunk size (equal left-pads).
  * **Full-prompt entries** additionally carry the last logits, so an
    exact prompt repeat (best-of-N sampling, retries, identical
    few-shot questions) skips prefill entirely — zero chunk steps,
    near-zero TTFT (the ``bench_serving --shared-prefix`` headline).
  * **Entries hold device arrays.**  The "host-side" part is the
    bookkeeping: looking up, pinning and LRU-evicting entries costs no
    device sync and no jit trace — a snapshot is just a kept reference
    to buffers a prefill already produced (consumers must never pass a
    cached array into a donating jit; the engine and
    ``chunked_prefill`` copy first where donation looms).
  * **Hybrid entries pin KV pages** by id: the engine increfs the
    prefix's pages in its ``state_cache.PagePool`` when it stores an
    entry, and the ``evict_hook`` decrefs them when the LRU lets go —
    sharing across slots is copy-on-write (serving/engine.py).

Bounded by entries AND bytes (``cfg.prefix_cache_entries`` /
``prefix_cache_bytes``); ``min_hits`` (``cfg.prefix_min_chunk_hits``)
is vLLM-style promotion: a prefix must MISS that many times before its
snapshot is stored, keeping one-off prompts from churning the LRU.

The cache is valid for ONE parameter set (keys hash prompts, not
weights) and — for hybrid entries — ONE engine's page pool; share an
instance between an engine and ``generate(prefix_cache=...)`` only
when both serve the same params (the warm-parity contract,
tests/test_prefix_cache.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict

import numpy as np


def _tokens_digest(h, prompt_ids, n: int) -> None:
    h.update(np.ascontiguousarray(
        np.asarray(prompt_ids, np.int32).reshape(-1)[:n]
    ).tobytes())


def boundary_key(prompt_ids, plan, i: int, salt: bytes = b"") -> str:
    """Key of the carry after chunk ``i`` of ``plan``'s layout: the
    chunk width, the left-pad, and every real token consumed through
    that chunk — exactly the inputs that determine the carry.
    ``salt`` (serving/adapters.prefix_salt) mixes a LoRA adapter
    identity into the key: the carry DEPENDS on the adapter delta, so
    a warm hit under adapter X must never seed adapter Y.  The empty
    default leaves every digest byte-identical to the unsalted one."""
    real = (i + 1) * plan.chunk - plan.pad
    h = hashlib.sha1()
    h.update(salt)
    h.update(b"chunk:%d:%d:" % (plan.chunk, plan.pad))
    _tokens_digest(h, prompt_ids, real)
    return h.hexdigest()


def full_key(prompt_ids, chunk: int, salt: bytes = b"") -> str:
    """Key of a CHUNKED prompt's final (state, last-logits) pair.  The
    pad is a pure function of (len, chunk), so chunk + the full token
    sequence pin the layout (``salt``: see ``boundary_key``)."""
    h = hashlib.sha1()
    h.update(salt)
    h.update(b"full:%d:" % chunk)
    _tokens_digest(h, prompt_ids, len(prompt_ids))
    return h.hexdigest()


def layout_keys(prompt_ids, plan, salt: bytes = b"") -> tuple[list, str]:
    """Every boundary key of ``plan``'s layout plus the full key, in ONE
    O(prompt_len) pass: the boundary digests are prefix-snapshots of a
    single running hash (``hashlib`` copies), byte-identical to calling
    ``boundary_key`` per chunk — which would rehash the whole prefix per
    boundary, O(n_chunks x prompt_len) on the admission/probe hot path
    (the router probes every replica's cache per submit)."""
    ids = np.ascontiguousarray(np.asarray(prompt_ids, np.int32).reshape(-1))
    h = hashlib.sha1()
    h.update(salt)
    h.update(b"chunk:%d:%d:" % (plan.chunk, plan.pad))
    keys = []
    prev = 0
    for i in range(plan.n_chunks):
        real = (i + 1) * plan.chunk - plan.pad
        h.update(ids[prev:real].tobytes())
        prev = real
        keys.append(h.copy().hexdigest())
    hf = hashlib.sha1()
    hf.update(salt)
    hf.update(b"full:%d:" % plan.chunk)
    hf.update(ids.tobytes())
    return keys, hf.hexdigest()


def oneshot_key(prompt_ids, salt: bytes = b"") -> str:
    """Key of a ONE-SHOT (pow2-bucketed) prompt's final (state, logits)
    pair — the short pure-SSM admission path.  The bucket is a pure
    function of the length, so the tokens alone pin the layout
    (``salt``: see ``boundary_key``)."""
    h = hashlib.sha1()
    h.update(salt)
    h.update(b"oneshot:")
    _tokens_digest(h, prompt_ids, len(prompt_ids))
    return h.hexdigest()


@dataclasses.dataclass
class PrefixEntry:
    """One cached snapshot.

    ``state`` is the batch-1 ``{"blocks": (conv, ssm)}`` carry (device
    arrays); ``logits`` (1, V) marks a FULL entry (prefill skippable
    outright).  ``tokens`` is the real prompt tokens the snapshot
    covers (what a hit saves), ``chunks`` the chunk steps it skips.
    Hybrid entries pin ``kv_pages`` (physical ids, prefix order) whose
    first ``kv_len`` token positions hold the prefix's KV — the pages
    live in data-shard ``shard`` and only same-shard slots may attach
    to them (the shard-confined-pages invariant)."""

    state: dict
    tokens: int
    chunks: int
    nbytes: int
    logits: object | None = None
    kv_pages: tuple | None = None
    kv_len: int = 0
    shard: int = 0

    @property
    def full(self) -> bool:
        return self.logits is not None


class PrefixCache:
    """Bounded LRU of :class:`PrefixEntry` keyed by layout-prefix hash.

    Args:
      max_entries: entry-count cap (>= 1).
      max_bytes: byte cap over every entry's ``nbytes`` (0 = no byte
        cap).  Either cap evicts least-recently-used first.
      min_hits: misses a key must accumulate before ``wants`` lets its
        snapshot be stored (1 = store on first sight).
      evict_hook: called with each evicted PrefixEntry — the hybrid
        engine decrefs the entry's pinned KV pages here.
    """

    def __init__(self, max_entries: int = 256, max_bytes: int = 0,
                 min_hits: int = 1, evict_hook=None):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        if min_hits < 1:
            raise ValueError(f"min_hits must be >= 1, got {min_hits}")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.min_hits = min_hits
        self.evict_hook = evict_hook
        self._entries: OrderedDict[str, PrefixEntry] = OrderedDict()
        self._seen: OrderedDict[str, int] = OrderedDict()  # miss counts
        self.nbytes = 0
        # lifetime stats (the engine keeps its own per-tick windows)
        self.hits = 0
        self.misses = 0
        self.saved_tokens = 0
        self.evictions = 0

    # -------------------------------------------------------------- basics

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def get(self, key: str, peek: bool = False) -> PrefixEntry | None:
        """The entry under ``key`` (refreshing its recency), or None.
        ``peek`` skips the recency touch — load probes (the router's
        cache-affinity placement) must not perturb eviction order."""
        e = self._entries.get(key)
        if e is not None and not peek:
            self._entries.move_to_end(key)
        return e

    def wants(self, key: str) -> bool:
        """Should the caller build + store a snapshot for ``key``?  No
        when it is already cached; no until the key has missed
        ``min_hits`` times (''note_miss'' counts — lookup bumps it)."""
        if key in self._entries:
            return False
        if self.min_hits <= 1:
            return True
        return self._seen.get(key, 0) >= self.min_hits

    def commit_lookup(self, prompt_ids, plan, hit,
                      salt: bytes = b"") -> None:
        """Record a lookup outcome once the admission actually went
        through.  The ENGINE probes with ``lookup(peek=True)`` and
        commits here only after securing a slot: a request stalled on
        KV pages retries its admission every step, and counting each
        retry would drift hit/miss stats and self-promote ``min_hits``
        counters (which are meant to count distinct misses, not
        retries of one).  ``hit`` is the peek's ``(entry, chunks_done)``
        — or None for a miss, including a hybrid hit the engine
        abandoned for shard reasons and served cold."""
        if hit is not None:
            entry, chunks_done = hit
            self.hits += 1
            self.saved_tokens += entry.tokens
            if plan is None:
                self.get(oneshot_key(prompt_ids, salt))  # deferred recency
                return
            bkeys, fkey = layout_keys(prompt_ids, plan, salt)
            if chunks_done == plan.n_chunks:
                self.get(fkey)
                return
            self.get(bkeys[chunks_done - 1])
            # keys DEEPER than the hit still missed — they count toward
            # promotion exactly as lookup()'s non-peek path counts them,
            # or a partially-hit prompt could never promote its full
            # entry past min_hits
            self.note_miss(fkey)
            for k in bkeys[chunks_done:plan.n_chunks - 1]:
                self.note_miss(k)
            return
        self.misses += 1
        if plan is None:
            self.note_miss(oneshot_key(prompt_ids, salt))
            return
        bkeys, fkey = layout_keys(prompt_ids, plan, salt)
        for k in [fkey] + bkeys[:-1]:
            self.note_miss(k)

    def evict_one_pinned(self, shards=None) -> bool:
        """Evict the least-recently-used entry that pins KV pages (the
        engine's admission pressure valve, serving/engine.py
        ``_reclaim_cache_pages``), optionally restricted to entries
        whose pages live in ``shards`` — evicting another shard's
        entries can never unblock this admission.  Returns False when
        no eligible entry exists."""
        victim_key = next((k for k, e in self._entries.items()
                           if e.kv_pages
                           and (shards is None or e.shard in shards)),
                          None)
        if victim_key is None:
            return False
        victim = self._entries.pop(victim_key)
        self.nbytes -= victim.nbytes
        self.evictions += 1
        if self.evict_hook is not None:
            self.evict_hook(victim)
        return True

    def note_miss(self, key: str) -> None:
        """Count a lookup miss toward ``min_hits`` promotion (bounded:
        the counter table trims FIFO at 4x the entry cap)."""
        if self.min_hits <= 1:
            return
        self._seen[key] = self._seen.get(key, 0) + 1
        self._seen.move_to_end(key)
        while len(self._seen) > 4 * self.max_entries:
            self._seen.popitem(last=False)

    def put(self, key: str, entry: PrefixEntry) -> None:
        """Store (caller checked ``wants`` first — storing over a live
        key would strand its side effects, e.g. page increfs)."""
        if key in self._entries:
            raise KeyError(f"prefix key {key} already cached — check "
                           f"wants() before building an entry")
        self._entries[key] = entry
        self.nbytes += entry.nbytes
        self._seen.pop(key, None)
        self._evict_over_caps()

    def _evict_over_caps(self) -> None:
        while (len(self._entries) > self.max_entries
               or (self.max_bytes and self.nbytes > self.max_bytes
                   and len(self._entries) > 1)):
            _, victim = self._entries.popitem(last=False)
            self.nbytes -= victim.nbytes
            self.evictions += 1
            if self.evict_hook is not None:
                self.evict_hook(victim)

    def clear(self) -> None:
        """Drop everything (evict hooks run, so pinned pages release)."""
        while self._entries:
            _, victim = self._entries.popitem(last=False)
            self.nbytes -= victim.nbytes
            self.evictions += 1
            if self.evict_hook is not None:
                self.evict_hook(victim)
        self._seen.clear()

    # ------------------------------------------------------------- lookups

    def lookup(self, prompt_ids, plan, peek: bool = False,
               salt: bytes = b""):
        """Deepest cached prefix for this prompt's exact layout.

        Returns ``(entry, chunks_done)`` — ``chunks_done ==
        plan.n_chunks`` (or 0 with ``plan=None``, the one-shot path)
        means a FULL hit whose entry carries the last logits — or None.
        Order: full entry first, then chunk boundaries deepest-first
        (the last boundary is skipped for this plan: without the final
        logits it cannot finish, though it serves LONGER same-pad
        prompts).  Misses bump the promotion counters; ``peek`` probes
        without touching stats or recency (router affinity)."""
        if plan is None:
            key = oneshot_key(prompt_ids, salt)
            e = self.get(key, peek=peek)
            if e is not None:
                if not peek:
                    self.hits += 1
                    self.saved_tokens += e.tokens
                return e, 0
            if not peek:
                self.misses += 1
                self.note_miss(key)
            return None
        bkeys, fkey = layout_keys(prompt_ids, plan, salt)
        keys = [(fkey, plan.n_chunks)]
        keys += [(bkeys[i], i + 1)
                 for i in reversed(range(plan.n_chunks - 1))]
        missed = []
        for key, chunks_done in keys:
            e = self.get(key, peek=peek)
            if e is not None:
                if not peek:
                    self.hits += 1
                    self.saved_tokens += e.tokens
                    for k in missed:
                        self.note_miss(k)
                return e, chunks_done
            missed.append(key)
        if not peek:
            self.misses += 1
            for k in missed:
                self.note_miss(k)
        return None

    # ------------------------------------------- pure-SSM store conveniences

    def maybe_store_boundary(self, prompt_ids, plan, i: int,
                             state: dict, salt: bytes = b"") -> None:
        """Store chunk ``i``'s carry for a PURE-SSM layout (hybrid
        entries need page pinning — the engine builds those itself).
        ``state`` must be safe to retain: never later donated."""
        key = boundary_key(prompt_ids, plan, i, salt)
        if not self.wants(key):
            return
        self.put(key, PrefixEntry(
            state=state, tokens=(i + 1) * plan.chunk - plan.pad,
            chunks=i + 1, nbytes=state_nbytes(state),
        ))

    def maybe_store_full(self, prompt_ids, state: dict, logits, *,
                         chunk: int = 0, chunks: int = 0,
                         salt: bytes = b"") -> None:
        """Store a full (state, logits) snapshot for a pure-SSM prompt
        — ``chunk > 0`` keys the chunked layout, 0 the one-shot pow2
        bucket."""
        key = (full_key(prompt_ids, chunk, salt) if chunk
               else oneshot_key(prompt_ids, salt))
        if not self.wants(key):
            return
        self.put(key, PrefixEntry(
            state=state, tokens=len(prompt_ids), chunks=chunks,
            logits=logits,
            nbytes=state_nbytes(state) + int(logits.nbytes),
        ))


def state_nbytes(state) -> int:
    import jax

    return int(sum(x.nbytes for x in jax.tree.leaves(state)))
