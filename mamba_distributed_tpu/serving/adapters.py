"""Multi-tenant LoRA serving: named adapters, a refcounted device cache,
and the segmented batched-LoRA factor pools one tick launch consumes.

Millions of users means thousands of fine-tuned variants, not one
checkpoint.  Rather than one engine per adapter (N copies of the base
weights, N cold slot pools), ONE engine serves heterogeneous adapters:

  * an :class:`AdapterRegistry` holds up to ``cfg.lora_max_adapters``
    named adapters' low-rank ``{A (d_in, r), B (r, d_out)}`` factors
    over the same ``linear()``-routed projections the serving
    tensor-parallel specs already shard (``_LORA_RULES`` mirrors
    ``parallel/sharding._TP_RULES``: in/out/x projections, attention
    wqkv/out_proj, MLP fc1/fc2 — per LAYER, stacked like the params);
  * an :class:`AdapterCache` generalizes the PagePool refcount/LRU
    discipline to adapter factors: a bounded pool of device slots,
    each holding one adapter's factors stacked into per-target
    ``(L, slots + 1, d_in, r)`` / ``(L, slots + 1, r, d_out)`` arrays
    — ROW 0 is the reserved all-zero "no adapter" entry, the factor
    pools' trash page.  Admission ``acquire``s a slot like it reserves
    KV pages (waits when every slot is pinned — never a mid-flight
    miss), refcounts pin a slot while any resident stream uses it,
    zero-ref residents evict LRU, and a double ``release`` raises the
    named :class:`AdapterCacheError` (the PR-9 page rules, re-applied);
  * the engine attaches the pools under each target's param dict
    (``attach_adapter_pools``) and every compiled launch binds the
    per-row adapter ids from the slot pool's meta
    (``bind_adapter_ids``), so ``models/common.linear`` computes

        y = x @ W + (x @ A[ids]) @ B[ids]

    — slots running DIFFERENT adapters share ONE launch, and id-0 rows
    multiply the zero factors (an exact +0.0 on the fp32 accumulator).

TP composition: a COLUMN-parallel base kernel shards its output axis,
so its ``B`` factor shards ``d_out`` with it (``A`` replicated: the
rank-r inner activation is tiny); a ROW-parallel base kernel shards its
input axis, so ``A`` shards ``d_in`` with it (``B`` replicated; GSPMD
inserts the same all-reduce the base matmul needs).  The rules live in
``parallel/sharding.serving_param_specs`` next to the kernel rules.

Scaling: the conventional LoRA weight ``alpha / rank`` is folded into
the stored ``B`` factors ONCE at registration, so the hot path never
multiplies by it and the merged reference is simply ``W + A @ B_eff``.

Parity regime: a stream under adapter ``a`` must match solo
``generate()`` on the MERGED weights ``merge_adapter_params(params,
registry, a)`` — via ``ops/quant.assert_stream_close``, NOT bit
equality: the segmented delta re-associates float sums (x@(W + AB)
vs x@W + (x@A)@B), so bit-exactness is the wrong pin here; greedy
tokens agree exactly on the fp32 CPU matrix (tests/test_tenant_lora.py
pins zero disagreements across mamba1/mamba2/hybrid, chunked longs,
(2,2) TP, prefix-warm, preempt/resume, migration, spec K>0 and
tick compaction).

Quantized int8 base weights + a LoRA delta is a ROADMAP residual — the
engine rejects the combination with a named error rather than silently
mixing the two dequant paths.
"""

from __future__ import annotations

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np


class AdapterError(RuntimeError):
    """Base of the named multi-tenant LoRA errors."""


class UnknownAdapterError(AdapterError, ValueError):
    """A request (or merge/acquire) named an adapter the registry does
    not hold.  ValueError too, so the service wire marks it retriable
    and the HTTP front end can map it to a 404 — never a hang."""


class AdapterCacheError(AdapterError):
    """An adapter-slot accounting violation: double release, releasing
    a never-acquired adapter, or touching the reserved zero row.
    Always a caller bug (the engine's own paths keep the invariants),
    so it raises loudly instead of silently corrupting refcounts —
    the PagePoolError contract, re-applied to factor slots."""


class AdapterVersionError(AdapterError, ValueError):
    """A version-ordering violation on registration: re-registering an
    existing ``name@vN`` or registering a version at or below the
    current latest (a rollback).  Versions are monotone per base name —
    online tuning deploys ``name@v(N+1)``, never rewrites history.
    ValueError too, so the service wire marks it retriable."""


def split_adapter_version(name: str) -> tuple[str, int | None]:
    """``"tenant@v3"`` -> ``("tenant", 3)``; a bare name -> ``(name,
    None)``.  Only a trailing ``@v<digits>`` is version syntax — any
    other ``@`` is part of the tenant identity."""
    base, sep, tail = name.rpartition("@v")
    if sep and base and tail.isdigit():
        return base, int(tail)
    return name, None


def versioned_name(base: str, version: int) -> str:
    """Canonical registry key: v1 is the BARE name (the PR-15
    single-version fast path — byte-identical salts/records/wire when
    only one version ever exists), v2+ are ``base@vN``."""
    return base if version == 1 else f"{base}@v{version}"


# (path-suffix pattern) of the linear()-routed projection dicts that
# accept LoRA factors — the same projections _TP_RULES shards, which is
# what makes the A/B sharding rules compose with tensor parallelism.
# (mamba1's dt_proj bypasses linear(); conv/router/norms/SSM scalars
# are not matmul targets — exactly the ops/quant.py denylist.)
_LORA_RULES: tuple[tuple[str, ...], ...] = (
    ("mixer", "in_proj"),
    ("mixer", "out_proj"),
    ("mixer", "x_proj"),
    ("mixer", "wqkv"),
    ("mlp", "fc1"),
    ("mlp", "fc2"),
)


def is_lora_target(names: list[str]) -> bool:
    """Does the param-dict path accept LoRA factors?"""
    return any(tuple(names[-len(p):]) == p for p in _LORA_RULES)


def lora_targets(params: dict) -> "OrderedDict[str, tuple[int, int, int]]":
    """Derive the adapter target table from a param tree: ordered map
    of ``"a/b/c"`` path -> ``(n_stack, d_in, d_out)`` for every
    layer-stacked projection kernel ``_LORA_RULES`` names.  Factors are
    per LAYER (the leading stack axis mirrors the param layout so the
    scan-over-layers slices them alongside the kernels)."""
    out: OrderedDict[str, tuple[int, int, int]] = OrderedDict()

    def walk(tree, names):
        if not isinstance(tree, dict):
            return
        if "kernel" in tree and not isinstance(tree["kernel"], dict) \
                and is_lora_target(names):
            shape = np.shape(tree["kernel"])
            if len(shape) == 3:  # (L, d_in, d_out) — stacked, as served
                out["/".join(names)] = (shape[0], shape[1], shape[2])
            return
        for k in sorted(tree.keys()):
            walk(tree[k], names + [k])

    walk(params, [])
    if not out:
        raise ValueError(
            "no LoRA-targetable projections found in the param tree "
            "(expected layer-stacked mixer/MLP kernels)"
        )
    return out


def prefix_salt(adapter: str | None) -> bytes:
    """Prefix-cache key salt for one adapter identity.  Carry snapshots
    DEPEND on the adapter whose delta shaped them, so a warm hit under
    adapter X must never seed adapter Y — the engine mixes this into
    every prefix-cache key.  ``None``/empty (no adapter) is ``b""``:
    cache keys byte-identical to a LoRA-less engine's."""
    if not adapter:
        return b""
    return b"adapter:" + adapter.encode("utf-8") + b":"


# ------------------------------------------------------------- registry


class AdapterRegistry:
    """Host-side table of named adapters' fp32 factors.

    Factors are keyed by target path (``lora_targets``); each entry is
    ``{"A": (L, d_in, r) f32, "B": (L, r, d_out) f32}`` with the
    ``alpha / rank`` scale already folded into ``B``.  A registered
    adapter may cover a SUBSET of the targets (LoRA-on-attention-only
    is common); uncovered targets contribute the zero delta.

    One registry may back many engines (the in-process router passes
    one instance through ``engine_kw`` so every replica — including a
    migration target — re-pins factors from the same table); each
    engine keeps its own :class:`AdapterCache` of device slots.
    """

    def __init__(self, cfg, params: dict):
        if cfg.lora_max_adapters <= 0:
            raise ValueError(
                "AdapterRegistry needs cfg.lora_max_adapters > 0 "
                "(0 = multi-tenant LoRA off)"
            )
        self.cfg = cfg
        self.rank = cfg.lora_rank
        self.alpha = cfg.lora_alpha
        self.max_adapters = cfg.lora_max_adapters
        self.targets = lora_targets(params)
        self._adapters: "OrderedDict[str, dict]" = OrderedDict()
        # base name -> highest registered version (monotone; rollbacks
        # raise AdapterVersionError).  v1 is stored under the BARE name.
        self._versions: dict[str, int] = {}

    # ------------------------------------------------------------ lookup

    def __contains__(self, name: str) -> bool:
        return self.resolve(name) in self._adapters

    def __len__(self) -> int:
        return len(self._adapters)

    def names(self) -> list[str]:
        return list(self._adapters.keys())

    def resolve(self, name: str) -> str:
        """Canonical registry key for ``name``: a bare name resolves to
        its LATEST version's key, an explicit ``@v1`` to the bare fast
        path, any other ``@vN`` to itself.  Pure — never raises; an
        unresolvable name passes through and misses in :meth:`factors`
        with the named :class:`UnknownAdapterError`."""
        base, ver = split_adapter_version(name)
        if ver is None:
            cur = self._versions.get(name)
            return name if cur is None else versioned_name(name, cur)
        if ver == 1 and base in self._adapters:
            return base
        return name

    def latest(self, name: str) -> str:
        """The newest registered version of ``name``'s base (version
        syntax on the input is ignored): the deploy target A/B routing
        steers new traffic toward.  Raises the named
        :class:`UnknownAdapterError` on an unknown base."""
        base, _ = split_adapter_version(name)
        cur = self._versions.get(base)
        if cur is None:
            raise UnknownAdapterError(
                f"unknown adapter base {base!r}: this registry holds "
                f"{self.names()}"
            )
        return versioned_name(base, cur)

    def version_of(self, name: str) -> int:
        """The version an adapter name denotes: explicit ``@vN`` -> N,
        bare -> the current latest (1 if only one ever registered)."""
        base, ver = split_adapter_version(name)
        if ver is not None:
            return ver
        return self._versions.get(base, 1)

    def factors(self, name: str) -> dict:
        """The adapter's stored (scaled) factors, keyed by target path.
        Bare names resolve to their latest version.  Raises the named
        :class:`UnknownAdapterError` on a miss."""
        try:
            return self._adapters[self.resolve(name)]
        except KeyError:
            raise UnknownAdapterError(
                f"unknown adapter {name!r}: this registry holds "
                f"{self.names()} (register it, or preload via "
                f"scripts/serve_worker.py --adapter name=path)"
            ) from None

    # ------------------------------------------------------ registration

    def register(self, name: str, factors: dict,
                 alpha: float | None = None) -> str:
        """Register ``factors`` (target path -> {"A", "B"} of UNscaled
        arrays) under ``name``.  Shapes are validated against the
        target table; ``alpha`` (default ``cfg.lora_alpha``) over
        ``rank`` is folded into the stored B once.

        Versioning: a BARE name registers the next version of its base
        (v1 on first sight — stored under the bare key, the PR-15
        single-version fast path; v(N+1) on re-register).  An explicit
        ``name@vN`` pins the version: N at or below the current latest
        raises the named :class:`AdapterVersionError` (history is
        immutable — no overwrites, no rollbacks); forward jumps are
        allowed so a late-joining replica can receive ``@v3`` without
        ever holding v1/v2.  Returns the canonical registered name."""
        if not name:
            raise ValueError("adapter name must be non-empty")
        base, ver = split_adapter_version(name)
        cur = self._versions.get(base, 0)
        if ver is None:
            ver = cur + 1
        elif ver <= cur:
            raise AdapterVersionError(
                f"adapter {base!r} is at v{cur}; registering "
                f"{base}@v{ver} would "
                + ("overwrite it" if ver == cur else "roll it back")
                + " — versions are monotone (register the bare name "
                "for the next version)"
            )
        key = versioned_name(base, ver)
        if len(self._adapters) >= self.max_adapters:
            raise ValueError(
                f"registry full: cfg.lora_max_adapters="
                f"{self.max_adapters} adapters already registered"
            )
        scale = (self.alpha if alpha is None else float(alpha)) / self.rank
        stored: dict[str, dict] = {}
        for path, fac in factors.items():
            if path not in self.targets:
                raise ValueError(
                    f"adapter {name!r} names unknown target {path!r}; "
                    f"valid targets: {list(self.targets)}"
                )
            n, d_in, d_out = self.targets[path]
            A = np.asarray(fac["A"], np.float32)
            B = np.asarray(fac["B"], np.float32)
            if A.shape != (n, d_in, self.rank):
                raise ValueError(
                    f"adapter {name!r} target {path!r}: A shape "
                    f"{A.shape} != {(n, d_in, self.rank)} "
                    f"(cfg.lora_rank={self.rank})"
                )
            if B.shape != (n, self.rank, d_out):
                raise ValueError(
                    f"adapter {name!r} target {path!r}: B shape "
                    f"{B.shape} != {(n, self.rank, d_out)}"
                )
            stored[path] = {"A": A, "B": B * scale}
        if not stored:
            raise ValueError(
                f"adapter {name!r} covers no targets (empty factors)"
            )
        self._adapters[key] = stored
        self._versions[base] = ver
        return key

    def register_random(self, name: str, seed: int = 0,
                        scale: float = 0.05,
                        targets: list[str] | None = None) -> str:
        """Register a random adapter (tests/bench): A ~ N(0, scale/r)
        per target, B ~ N(0, scale) — BOTH nonzero so the delta is
        live from the first token (the conventional B=0 init would
        make every adapter a no-op and parity vacuous)."""
        import zlib

        # crc32, not hash(): str hashing is per-process randomized, and
        # random adapters must be reproducible across worker processes
        rng = np.random.default_rng(
            (zlib.crc32(name.encode("utf-8")) + int(seed)) & 0xFFFFFFFF
        )
        fac = {}
        for path in (targets if targets is not None else self.targets):
            n, d_in, d_out = self.targets[path]
            fac[path] = {
                "A": rng.normal(0.0, scale / self.rank,
                                (n, d_in, self.rank)),
                "B": rng.normal(0.0, scale, (n, self.rank, d_out)),
            }
        return self.register(name, fac)

    # ----------------------------------------------------- merged weights

    def merge(self, params: dict, name: str) -> dict:
        """The PARITY reference: a fresh fp32 master tree with each
        target kernel replaced by ``W + A @ B_eff`` (the scale is
        already inside the stored B).  Feed it to a solo
        ``generate()`` call — its stream is what the engine's
        segmented launch must reproduce per-slot."""
        fac = self.factors(name)

        def walk(tree, names):
            if not isinstance(tree, dict):
                return tree
            path = "/".join(names)
            if path in fac and "kernel" in tree:
                delta = np.einsum(
                    "ndr,nro->ndo", fac[path]["A"], fac[path]["B"]
                )
                kernel = np.asarray(tree["kernel"],
                                    np.float32) + delta
                return {**tree, "kernel": jnp.asarray(kernel)}
            return {k: walk(v, names + [k]) for k, v in tree.items()}

        return walk(params, [])


def merge_adapter_params(params: dict, registry: AdapterRegistry,
                         name: str | None) -> dict:
    """``registry.merge`` that treats ``None`` (no adapter) as the base
    params — so callers can build every request's reference uniformly."""
    if not name:
        return params
    return registry.merge(params, name)


# ----------------------------------------------------------- file format


def save_adapter_file(path: str, factors: dict) -> None:
    """One adapter's (unscaled) factors as an ``.npz``: keys are
    ``"<target path>::A"`` / ``"::B"`` — what ``scripts/serve_worker.py
    --adapter name=path`` preloads."""
    flat = {}
    for tpath, fac in factors.items():
        flat[tpath + "::A"] = np.asarray(fac["A"], np.float32)
        flat[tpath + "::B"] = np.asarray(fac["B"], np.float32)
    np.savez(path, **flat)


def load_adapter_file(path: str) -> dict:
    """Inverse of :func:`save_adapter_file`."""
    out: dict[str, dict] = {}
    with np.load(path) as z:
        for key in z.files:
            tpath, _, part = key.rpartition("::")
            if part not in ("A", "B") or not tpath:
                raise ValueError(
                    f"{path}: key {key!r} is not '<target>::A|B'"
                )
            out.setdefault(tpath, {})[part] = z[key]
    for tpath, fac in out.items():
        if "A" not in fac or "B" not in fac:
            raise ValueError(f"{path}: target {tpath!r} missing A or B")
    return out


# --------------------------------------------------------- device cache


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_factor_row(pool: jax.Array, slot: jax.Array,
                      value: jax.Array) -> jax.Array:
    """Write one adapter's stacked factor (L, d_in, r) into row
    ``slot`` of the (L, slots+1, d_in, r) pool — a traced slot index,
    so one trace serves every (slot, adapter) upload of a given
    shape (the state_cache ``_set_row`` idiom on axis 1)."""
    v = value.astype(pool.dtype)[:, None]
    return jax.lax.dynamic_update_slice_in_dim(pool, v, slot, axis=1)


class AdapterCache:
    """Bounded device cache of adapter factor slots (see module
    docstring): the PagePool refcount/LRU discipline over stacked
    factor pools.  Row 0 of every pool is the reserved all-zero
    "no adapter" entry — never handed out, never written.

    ``acquire(name)`` returns the adapter's device slot (uploading the
    factors on a miss, evicting a zero-ref resident LRU-first) or
    ``None`` when every slot is pinned by refcounts — admission treats
    that exactly like a short KV page pool: wait, never OOM mid-
    flight.  ``release(name)`` drops one holder; a zero-ref adapter
    STAYS resident (warm for the next acquire) until evicted.
    ``version`` bumps on every pool write so the engine knows when to
    re-attach the pools to its param tree."""

    def __init__(self, registry: AdapterRegistry, slots: int,
                 compute_dtype=jnp.bfloat16):
        if slots < 1:
            raise ValueError(f"need >= 1 adapter cache slot, got {slots}")
        self.registry = registry
        self.slots = slots
        self.dtype = jnp.dtype(compute_dtype)
        r = registry.rank
        self.pools: dict[str, dict] = {
            path: {
                "A": jnp.zeros((n, slots + 1, d_in, r), self.dtype),
                "B": jnp.zeros((n, slots + 1, r, d_out), self.dtype),
            }
            for path, (n, d_in, d_out) in registry.targets.items()
        }
        self.version = 0  # bumps on every pool write (upload/evict)
        self._slot_of: dict[str, int] = {}  # resident adapter -> row
        self._refs: dict[str, int] = {}  # resident adapter -> holders
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # zero-ref
        self._free: list[int] = list(range(1, slots + 1))
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -------------------------------------------------------------- state

    @property
    def resident_count(self) -> int:
        return len(self._slot_of)

    def resident(self, name: str) -> bool:
        """Is the adapter's factor set on-device right now?  A pure
        probe (no stats, no LRU touch) — the router's adapter-affinity
        placement term reads it."""
        return name in self._slot_of

    def resident_names(self) -> list[str]:
        return sorted(self._slot_of)

    def slot_of(self, name: str) -> int | None:
        return self._slot_of.get(name)

    def refcount(self, name: str) -> int:
        return self._refs.get(name, 0)

    # ---------------------------------------------------------- lifecycle

    def acquire(self, name: str) -> int | None:
        """Pin ``name``'s factors to a device slot and return its row
        id (>= 1), or ``None`` when every slot is pinned by other
        streams (the caller waits — admission's page-wait contract).
        Unknown names raise :class:`UnknownAdapterError` (via the
        registry) before any slot state changes."""
        factors = self.registry.factors(name)  # raises on unknown
        slot = self._slot_of.get(name)
        if slot is not None:
            self.hits += 1
            self._refs[name] = self._refs.get(name, 0) + 1
            self._lru.pop(name, None)
            return slot
        if self._free:
            slot = self._free.pop(0)
        else:
            victim = next(iter(self._lru), None)
            if victim is None:
                # every slot pinned: wait, never evict live.  NOT a
                # miss: admission retries this every engine step, and
                # counting each retry would drift the gauge (a miss is
                # one factor UPLOAD — the commit_lookup discipline)
                return None
            self._lru.pop(victim)
            slot = self._slot_of.pop(victim)
            self._refs.pop(victim, None)
            self.evictions += 1
            # no scrub pass: _upload overwrites EVERY target's rows
            # (explicit zeros for uncovered targets), so the evicted
            # tenant's factors cannot survive the reuse and a separate
            # erase would just double the device writes
        self.misses += 1  # one miss == one factor upload
        self._upload(slot, factors)
        self._slot_of[name] = slot
        self._refs[name] = 1
        return slot

    def release(self, name: str) -> None:
        """Drop one holder.  At zero the adapter stays RESIDENT but
        becomes LRU-evictable (warm reuse beats eager eviction; the
        pools are bounded either way).  Releasing below zero — or an
        adapter that was never acquired — raises the named
        :class:`AdapterCacheError`: always a caller bug."""
        rc = self._refs.get(name, 0)
        if name not in self._slot_of or rc <= 0:
            raise AdapterCacheError(
                f"release of adapter {name!r} with no holders "
                f"(double release, or never acquired)"
            )
        if rc == 1:
            self._refs[name] = 0
            self._lru[name] = None
            self._lru.move_to_end(name)
        else:
            self._refs[name] = rc - 1

    # ------------------------------------------------------------ uploads

    def _upload(self, slot: int, factors: dict) -> None:
        for path, pool in self.pools.items():
            fac = factors.get(path)
            for part in ("A", "B"):
                if fac is not None:
                    value = jnp.asarray(fac[part])
                else:
                    # target not covered by this adapter: its delta is
                    # zero — write the zero factors explicitly so a
                    # recycled slot can't leak the previous tenant's
                    value = jnp.zeros(
                        pool[part].shape[:1] + pool[part].shape[2:],
                        pool[part].dtype,
                    )
                pool[part] = _write_factor_row(
                    pool[part], jnp.int32(slot), value
                )
        self.version += 1


# ----------------------------------------------- param-tree integration


def attach_adapter_pools(params: dict, pools: dict) -> dict:
    """Splice the cache's factor pools into a (decode-cast) param tree:
    each target's projection dict gains ``"lora": {"A": pool, "B":
    pool}``.  Pure host-side dict surgery — no device work; the engine
    re-attaches after every cache upload (``AdapterCache.version``)."""

    def walk(tree, names):
        if not isinstance(tree, dict):
            return tree
        path = "/".join(names)
        if path in pools:
            return {**tree, "lora": dict(pools[path])}
        return {k: walk(v, names + [k]) for k, v in tree.items()}

    return walk(params, [])


def bind_adapter_ids(params, ids: jax.Array):
    """Bind the per-row adapter ids into every attached ``"lora"``
    subtree (called INSIDE the compiled tick/prefill/verify steps —
    pure tree surgery at trace time).  ``ids`` is the launch's (b,)
    int32 row->cache-slot map (the slot pool's ``meta["adapter_id"]``,
    compacted to lane order when the tick is compacted).  Stacked
    targets broadcast the ids over their leading layer axis so the
    scan-over-layers slices a per-layer copy alongside the factors.
    Trees without ``"lora"`` subtrees pass through untouched — the
    LoRA-off path is structurally identical to pre-LoRA."""

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        lora = tree.get("lora")
        if isinstance(lora, dict) and "A" in lora:
            n = lora["A"].shape[0]
            bound = jnp.broadcast_to(ids[None, :], (n,) + ids.shape)
            return {
                **{k: walk(v) for k, v in tree.items() if k != "lora"},
                "lora": {**lora, "ids": bound},
            }
        return {k: walk(v) for k, v in tree.items()}

    return walk(params)
