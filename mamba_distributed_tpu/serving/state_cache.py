"""Pooled recurrent-state cache: the slot pool under the serving engine.

Mamba's decode state is O(1) per sequence — a (d_conv-1)-wide conv cache
plus one (nheads, headdim, d_state) SSM state per layer — so a serving
"KV cache" collapses to a fixed-capacity pool of S slots whose arrays
never change shape: admitting, advancing, and finishing requests are all
writes into a preallocated batch axis ("Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching for Inference",
PAPERS.md; the slot-pool idiom follows the ragged-paged-attention
serving pattern, minus the paging that attention's growing KV needs).

The pool is a plain pytree:

  pool = {
    "state": {
      "blocks": conv+SSM states, (L, S, ...) leaves  # per-slot rows
      "attn_blocks": (A, P, nkv, page, hd) x2        # hybrid only: the
    },                                # shared HEAD-MAJOR KV page pool
    "logits": (S, V_padded) fp32                    # last logits per slot
    "meta": {
      "active":      (S,) bool   # slot holds a live request
      "done":        (S,) bool   # request finished, awaiting eviction
      "prefilling":  (S,) bool   # slot holds a PARTIAL prefill carry
      "key":         (S, 2) u32  # request base PRNG key
      "step":        (S,) i32    # tokens generated so far
      "max_new":     (S,) i32    # per-request budget
      "top_k":       (S,) i32    # per-slot top-k (<= the engine's static k_max)
      "temperature": (S,) f32
      "eos_id":      (S,) i32    # -1 => no EOS stopping
      "adapter_id":  (S,) i32    # LoRA factor-pool row (0 = none)
    },
  }

``adapter_id`` is the multi-tenant LoRA identity (serving/adapters.py):
the device AdapterCache slot whose stacked factors this slot's rows
multiply inside the tick — 0 (the default, and the only value on
LoRA-less engines) selects the reserved all-zero factor row, an exact
no-op.  It lives in the pool meta — not a separate tick argument — so
the compacted-tick gathers/scatters move it with the other axis-0
meta rows for free.

``insert``/``evict`` are jit-compiled with the pool donated: the slot
index is a traced scalar, so admitting a request into ANY slot reuses
one trace, and the update lowers to ``dynamic_update_slice`` on the
donated buffers — no reallocation, no retrace, which is what keeps the
decode loop hot while requests come and go (serving/engine.py).

Chunked prefill (serving/prefill.py) adds partial-prefill residency: a
half-prefilled request occupies its slot with its scan carry —
``stash_prefill`` parks the carry + request meta with
``prefilling=True`` (the decode tick treats the slot as not-live and
must NOT overwrite its state rows), ``read_state`` slices the carry
back out to resume at the next budget grant, and ``finish_prefill``
writes the final state + logits and flips ``prefilling`` off, making
the slot decodable.

HYBRID stacks (``attn_layer_idx`` non-empty) pool too: the attention KV
lives in a fixed PAGE pool — per-layer HEAD-MAJOR ``(P, nkv, page, hd)``
page arrays under ``state["attn_blocks"]`` (page 0 is a reserved trash
page; head-major is the Pallas kernels' native block layout, so the
decode/prefill page walks read pages without any per-call transpose)
— while the page table and per-slot lengths stay HOST-side on the
engine (they change only between ticks, and the tick takes them as
plain array arguments).  With ``cfg.kv_page_dtype="int8"`` each layer's
tuple grows per-(page, kv-head) f32 scale arrays ``(A, P, nkv)``
alongside the int8 pages (models/attention.py "Int8 KV page
quantization"); every page-granular helper below — ``copy_page``,
``read_pages``, ``write_pages``, the slot-pool shardings — treats the
scales as just more page-axis-1 leaves, so CoW sharing, migration
artifacts and the data-axis tiling carry the scales with their pages
automatically.  ``PagePool`` is the host allocator: admission
reserves ceil((prompt + max_new) / page) pages up front (so a request
can never run out mid-flight), eviction recycles them.  KV HBM is
therefore O(pages in use), not O(capacity * max_len), and slots at
arbitrary positions coexist because everything per-row — RoPE angles,
causal masks, KV write offsets — is computed from the per-slot lengths
(models/attention.py, the ragged/paged-attention pattern).  The state
pytree the jitted slot writes cover is the ``"blocks"`` (conv+SSM)
subtree; attention pages flow through the chunk step's and the tick's
own donations instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.lm import init_lm_blocks_state


def page_shard_ranges(
    num_pages: int, num_shards: int
) -> list[tuple[int, int]]:
    """Per-shard usable page-id ranges ``[lo, hi)`` mirroring the DEVICE
    layout of a page pool sharded over the data axis: the (P+1)-row
    page arrays (trash page 0 included) partition contiguously, so
    shard d owns rows ``[d*(P+1)/n, (d+1)*(P+1)/n)``, minus row 0 —
    the trash page, which lives in shard 0 and is never handed out.
    Requires ``(num_pages + 1) % num_shards == 0`` (``hybrid_pool_pages``
    rounds the pool up to guarantee it), so host bookkeeping and the
    NamedSharding tile boundaries can never disagree about which shard
    a physical page lives on."""
    rows = num_pages + 1
    if rows % num_shards:
        raise ValueError(
            f"page array of {rows} rows (pages + trash) does not divide "
            f"over {num_shards} shards"
        )
    per = rows // num_shards
    if per < 2:
        # shard 0's tile is the trash page (+ per-2 more): with per == 1
        # it has ZERO usable pages, silently killing every slot resident
        # there — refuse the configuration instead
        raise ValueError(
            f"{num_pages} usable pages over {num_shards} shards leaves "
            f"shard 0 with none (its tile is the trash page); raise "
            f"cfg.kv_pool_pages or lower serving_data_shards"
        )
    return [(max(1, d * per), (d + 1) * per) for d in range(num_shards)]


class PagePoolError(RuntimeError):
    """A page-accounting violation: double free, freeing the trash
    page, or touching a page id outside the pool.  These are always
    caller bugs (the allocator's invariants make them impossible on the
    engine's own paths), so they raise loudly instead of silently
    corrupting the free lists."""


class PagePool:
    """Host-side KV page allocator (hybrid pools): free lists over
    physical pages [1, P) — page 0 is the trash page and never handed
    out.  Purely bookkeeping; the page *arrays* live in the pool pytree
    and are written by the compiled chunk/tick steps.

    Pages are REFCOUNTED: ``alloc`` hands out pages at refcount 1,
    ``incref`` lets another holder (a prefix-cache entry, a slot
    sharing a cached prefix copy-on-write — serving/prefix_cache.py)
    pin the same physical page, and ``free`` decrements — a page
    returns to the free list only when its last holder lets go.  This
    is what lets N slots serve one cached system-prompt's KV from one
    set of physical pages.  ``free`` rejects double-frees and the
    trash page with a named ``PagePoolError``.

    With ``num_shards > 1`` (the mesh-sharded slot pool), the usable
    pages partition into per-shard free lists along the SAME contiguous
    boundaries as the page arrays' NamedSharding over the data axis
    (``page_shard_ranges``): a slot resident in data-shard d allocates
    only from shard d's pages, so every slot's KV reads and writes stay
    on the devices that hold its rows of the pool.  The 2-D serving
    mesh's MODEL axis is invisible here — weights shard over it, pages
    never do (parallel/sharding.serving_param_specs vs
    slot_pool_specs), so this accounting is identical at any
    ``serving_model_shards``."""

    def __init__(self, num_pages: int, num_shards: int = 1):
        if num_pages < 1:
            raise ValueError(f"need >= 1 usable page, got {num_pages}")
        if num_shards < 1:
            raise ValueError(f"need >= 1 shard, got {num_shards}")
        self.num_pages = num_pages
        self.num_shards = num_shards
        self._ranges = page_shard_ranges(num_pages, num_shards)
        self._free_lists = [list(range(lo, hi)) for lo, hi in self._ranges]
        self._refs: dict[int, int] = {}  # allocated page -> holder count

    @property
    def _free(self) -> list[int]:
        """Flat sorted view of every free page (shard-agnostic callers
        and tests; per-shard state lives in ``_free_lists``)."""
        return sorted(p for lst in self._free_lists for p in lst)

    @property
    def free_pages(self) -> int:
        return sum(len(lst) for lst in self._free_lists)

    def free_pages_in(self, shard: int) -> int:
        return len(self._free_lists[shard])

    def shard_capacity(self, shard: int) -> int:
        """Usable pages shard ``shard`` could EVER have free (its range
        size) — the bound the admission deadlock check tests against."""
        lo, hi = self._ranges[shard]
        return hi - lo

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.free_pages

    def _owner(self, page: int) -> int:
        if not 1 <= page <= self.num_pages:
            raise ValueError(f"page {page} outside every shard range")
        # ranges are uniform contiguous tiles of the (P+1)-row page axis
        return page // ((self.num_pages + 1) // self.num_shards)

    def alloc(self, n: int, shard: int = 0) -> list[int]:
        """Reserve ``n`` pages from ``shard``'s range, or raise if it
        can't cover them (callers check ``free_pages_in`` first —
        admission just waits).  Pages come back at refcount 1."""
        lst = self._free_lists[shard]
        if n > len(lst):
            raise RuntimeError(
                f"KV page pool exhausted: want {n}, shard {shard} has "
                f"{len(lst)}"
            )
        ids, self._free_lists[shard] = lst[:n], lst[n:]
        for p in ids:
            self._refs[p] = 1
        return ids

    def incref(self, ids: list[int]) -> None:
        """Add one holder to each page (prefix-cache entries pinning a
        cached prefix's KV; a slot admitted onto shared pages).  Only
        allocated pages can gain holders."""
        for p in ids:
            if self._refs.get(p, 0) <= 0:
                raise PagePoolError(
                    f"incref of page {p}, which is not allocated — only a "
                    f"live page can gain a holder"
                )
        for p in ids:
            self._refs[p] += 1

    def refcount(self, page: int) -> int:
        """Current holder count (0 = free / never allocated)."""
        return self._refs.get(page, 0)

    def free(self, ids: list[int]) -> None:
        """Drop one holder per page; a page returns to its shard's free
        list only at refcount 0 (eviction decrefs, never yanks a page a
        prefix-cache entry or a sharing slot still reads).  Raises
        ``PagePoolError`` on the trash page, on ids outside the pool,
        and on double-frees (including a duplicate id inside one batch)
        — all caller bugs."""
        touched = set()
        for p in ids:
            if p == 0:
                raise PagePoolError(
                    "page 0 is the trash page — it is never allocated and "
                    "must never be freed (masked writes depend on it)"
                )
            if not 1 <= p <= self.num_pages:
                raise PagePoolError(
                    f"page {p} is outside the pool's [1, {self.num_pages}] "
                    f"physical range"
                )
            rc = self._refs.get(p, 0)
            if rc <= 0:
                raise PagePoolError(
                    f"double free of page {p}: it has no holders (already "
                    f"on the free list or never allocated)"
                )
            if rc == 1:
                del self._refs[p]
                d = self._owner(p)
                self._free_lists[d].append(p)
                touched.add(d)
            else:
                self._refs[p] = rc - 1
        for d in touched:
            self._free_lists[d].sort()  # deterministic reuse order


def hybrid_pool_pages(
    cfg: ModelConfig, capacity: int, num_shards: int = 1
) -> int:
    """Usable page count of a serving pool (excluding the trash page):
    ``cfg.kv_pool_pages``, or auto = every slot can run to its full
    ``kv_slot_tokens`` budget simultaneously.  With a sharded pool the
    count rounds UP so the page arrays' (P+1)-row page axis divides
    evenly over the data axis — NamedSharding can't place uneven tiles,
    and the extra pages are usable capacity, never waste."""
    pages = cfg.kv_pool_pages or capacity * cfg.kv_pages_per_slot
    if num_shards > 1 and (pages + 1) % num_shards:
        pages += num_shards - (pages + 1) % num_shards
    return pages


def init_pool(cfg: ModelConfig, capacity: int, num_shards: int = 1) -> dict:
    """Allocate an empty slot pool for ``capacity`` concurrent requests.

    ``num_shards`` sizes a hybrid pool's page count for a mesh-sharded
    batch axis (``hybrid_pool_pages`` rounding) — the pytree itself is
    layout-agnostic; the engine device_puts it with
    ``parallel/sharding.slot_pool_shardings``."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    S = capacity
    state = {"blocks": init_lm_blocks_state(cfg, batch=S)}
    if cfg.attn_layer_idx:
        if cfg.effective_prefill_chunk_tokens <= 0:
            raise ValueError(
                "hybrid serving needs chunked prefill: every hybrid "
                "prompt runs through the chunk step (the one prefill "
                "that writes straight into the paged KV pool); set "
                "prefill_chunk_tokens > 0"
            )
        from mamba_distributed_tpu.models.attention import (
            init_attention_state,
        )

        n_pages = hybrid_pool_pages(cfg, capacity, num_shards)
        # init_attention_state builds (1 + batch*W) pages; ask for the
        # pool's page count directly via batch=n_pages, W=1-page slots
        pages = [
            init_attention_state(cfg, n_pages, cfg.kv_page_tokens)
            for _ in cfg.attn_layer_idx
        ]
        state["attn_blocks"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *pages
        )
    return {
        "state": state,
        "logits": jnp.zeros((S, cfg.vocab_size_padded), jnp.float32),
        "meta": {
            "active": jnp.zeros((S,), bool),
            "done": jnp.zeros((S,), bool),
            "prefilling": jnp.zeros((S,), bool),
            "key": jnp.zeros((S, 2), jnp.uint32),
            "step": jnp.zeros((S,), jnp.int32),
            "max_new": jnp.ones((S,), jnp.int32),
            "top_k": jnp.ones((S,), jnp.int32),
            "temperature": jnp.ones((S,), jnp.float32),
            "eos_id": jnp.full((S,), -1, jnp.int32),
            "adapter_id": jnp.zeros((S,), jnp.int32),
        },
    }


def _set_row(arr: jax.Array, slot: jax.Array, value) -> jax.Array:
    """Write one row of a (S, ...) array at a traced slot index."""
    v = jnp.asarray(value, arr.dtype).reshape((1,) + arr.shape[1:])
    return jax.lax.dynamic_update_slice_in_dim(arr, v, slot, axis=0)


@functools.partial(jax.jit, donate_argnums=(0,))
def insert(
    pool: dict,
    slot: jax.Array,
    state: dict,
    logits: jax.Array,
    key: jax.Array,
    max_new: jax.Array,
    top_k: jax.Array,
    temperature: jax.Array,
    eos_id: jax.Array,
    adapter_id: jax.Array = 0,
) -> dict:
    """Admit a prefilled request (batch-1 ``state`` + last ``logits``)
    into ``slot``.  One trace serves every (slot, request) combination —
    all arguments are traced, the pool buffers are donated.
    ``adapter_id`` is the request's LoRA factor-pool row (0 = none)."""
    # state leaves are layer-stacked (L, 1, ...) -> write batch axis 1
    new_state = _write_blocks(pool["state"], slot, state)
    meta = pool["meta"]
    new_meta = {
        "active": _set_row(meta["active"], slot, True),
        "done": _set_row(meta["done"], slot, False),
        "prefilling": _set_row(meta["prefilling"], slot, False),
        "key": _set_row(meta["key"], slot, key),
        "step": _set_row(meta["step"], slot, 0),
        "max_new": _set_row(meta["max_new"], slot, max_new),
        "top_k": _set_row(meta["top_k"], slot, top_k),
        "temperature": _set_row(meta["temperature"], slot, temperature),
        "eos_id": _set_row(meta["eos_id"], slot, eos_id),
        "adapter_id": _set_row(meta["adapter_id"], slot, adapter_id),
    }
    return {
        "state": new_state,
        "logits": _set_row(pool["logits"], slot, logits),
        "meta": new_meta,
    }


@functools.partial(jax.jit, donate_argnums=(0,))
def restore(
    pool: dict,
    slot: jax.Array,
    state: dict,
    logits: jax.Array,
    key: jax.Array,
    step: jax.Array,
    max_new: jax.Array,
    top_k: jax.Array,
    temperature: jax.Array,
    eos_id: jax.Array,
    adapter_id: jax.Array = 0,
) -> dict:
    """Re-admit a PREEMPTED request mid-decode: identical to ``insert``
    except the generated-token counter is restored instead of zeroed,
    so the next tick samples ``fold_in(key, step)`` — the stream
    continues bit-exactly where the swap-out cut it (the engine's
    priority-preemption path, serving/engine.py).  ``adapter_id`` is
    re-stamped from the tracker (the factor-pool row may differ on a
    migration target — cache slots are engine-local)."""
    new_state = _write_blocks(pool["state"], slot, state)
    meta = pool["meta"]
    new_meta = {
        "active": _set_row(meta["active"], slot, True),
        "done": _set_row(meta["done"], slot, False),
        "prefilling": _set_row(meta["prefilling"], slot, False),
        "key": _set_row(meta["key"], slot, key),
        "step": _set_row(meta["step"], slot, step),
        "max_new": _set_row(meta["max_new"], slot, max_new),
        "top_k": _set_row(meta["top_k"], slot, top_k),
        "temperature": _set_row(meta["temperature"], slot, temperature),
        "eos_id": _set_row(meta["eos_id"], slot, eos_id),
        "adapter_id": _set_row(meta["adapter_id"], slot, adapter_id),
    }
    return {
        "state": new_state,
        "logits": _set_row(pool["logits"], slot, logits),
        "meta": new_meta,
    }


@functools.partial(jax.jit, donate_argnums=(0,))
def copy_page(attn_blocks, src: jax.Array, dst: jax.Array):
    """Copy-on-write page duplication: copy physical page ``src`` into
    ``dst`` across every attention layer's K and V pool (the page axis
    is axis 1 of the (A, P+1, nkv, page, hd) leaves), in place on the
    donated buffers.  The prefix cache uses it so a slot that APPENDS
    to a shared cached prefix writes into its own copy of the boundary
    page — sharers keep reading the frozen original.  One trace serves
    every (src, dst) pair (both indices are traced scalars)."""

    def cp(p):
        page = jax.lax.dynamic_slice_in_dim(p, src, 1, axis=1)
        return jax.lax.dynamic_update_slice_in_dim(p, page, dst, axis=1)

    return jax.tree.map(cp, attn_blocks)


@jax.jit
def read_pages(attn_blocks, page_ids: jax.Array):
    """Gather physical pages ``page_ids`` (n,) out of every attention
    layer's K and V pool (page axis 1 of the (A, P+1, nkv, page, hd)
    leaves) -> (A, n, nkv, page, hd) leaves, logical order.  The
    serialization half of the disaggregated prefill->decode MIGRATION
    artifact (serving/engine._package_migration): the prefill replica
    reads the request's live pages here and ``jax.device_get``s them
    alongside the O(1) conv/SSM carry.  NOT donated — the source pool
    lives on; ``page_ids`` is traced, so one trace serves every page
    set of a given (pow2-bucketed) count."""
    return jax.tree.map(
        lambda p: jnp.take(p, page_ids, axis=1), attn_blocks
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def write_pages(attn_blocks, data, page_ids: jax.Array):
    """Scatter serialized page ``data`` (read_pages layout) into
    physical pages ``page_ids`` of the donated pool — the restore half
    of the migration artifact, run on the DECODE replica against its
    own freshly allocated page ids.  ``page_ids`` is traced (one trace
    per bucketed count); pad entries may point at the trash page 0,
    whose contents are garbage by contract (masked writes land there),
    so bucket padding never corrupts a live page."""
    return jax.tree.map(
        lambda p, d: p.at[:, page_ids].set(d.astype(p.dtype)),
        attn_blocks, data,
    )


def _write_blocks(pool_state, slot: jax.Array, state):
    """Write a batch-1 ``{"blocks": ...}`` pytree into ``slot`` of the
    (L, S, ...) conv+SSM pool leaves (shared by insert / stash_prefill /
    finish_prefill).  Only the "blocks" subtree has a per-slot batch
    axis — hybrid attention KV lives in the shared page pool and is
    written by the chunk/tick steps themselves, so any attn entries on
    ``pool_state`` pass through untouched (and ``state`` must not carry
    them: the engine strips to the blocks subtree before these calls,
    which also keeps the donated page buffers from aliasing another
    argument)."""
    new_blocks = jax.tree.map(
        lambda p, n: jax.lax.dynamic_update_slice_in_dim(
            p, n.astype(p.dtype), slot, axis=1
        ),
        pool_state["blocks"],
        state["blocks"],
    )
    return {**pool_state, "blocks": new_blocks}


# ------------------------------------------------- compacted-tick lanes
#
# Occupancy-adaptive compacted ticks (serving/engine.py; docs/SERVING.md
# "Occupancy-adaptive ticks"): the engine gathers the LIVE slots' rows
# into a pow2 lane bucket, runs the existing jitted tick/verify step at
# bucket width, and scatters the results back — compute per tick tracks
# live slots, not static capacity.  These two jits are the whole device
# side of that layer.  One trace per bucket width (the index arrays are
# traced; only the width is a shape) — the engine's per-bucket trace
# pins ride on these counters, mirroring the prompt-bucket discipline.
TRACE_COUNTS = {"gather": 0, "scatter": 0}


@functools.partial(jax.jit, static_argnames=("mesh",))
def gather_slots(rows: dict, idx: jax.Array, mesh=None):
    """Gather slot rows ``idx`` (W,) of a ``{"blocks", "logits",
    "meta"}`` tree (the per-slot subtrees of a pool — ``blocks`` leaves
    (L, S, ...) take axis 1, ``logits``/``meta`` leaves axis 0) into a
    compact (.., W, ..) tree.  NOT donated: the full pool lives on (the
    compacted tick's scatter writes it back).  Pad lanes may repeat any
    in-range slot index — their computed results are garbage the
    scatter never reads.  ``mesh`` (static; a serving_mesh, else None)
    pins the compact lanes to the data-axis layout via the SAME
    ``slot_pool_specs`` rules the full pool uses (the engine keeps the
    bucket a multiple of the shard count and gathers shard-locally, so
    the tiling carries over)."""
    TRACE_COUNTS["gather"] += 1
    out = {
        "blocks": jax.tree.map(
            lambda a: jnp.take(a, idx, axis=1), rows["blocks"]
        ),
        "logits": jnp.take(rows["logits"], idx, axis=0),
        "meta": jax.tree.map(
            lambda a: jnp.take(a, idx, axis=0), rows["meta"]
        ),
    }
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            slot_pool_shardings,
        )

        out = jax.lax.with_sharding_constraint(
            out, slot_pool_shardings(out, mesh)
        )
    return out


@functools.partial(jax.jit, static_argnames=("mesh",), donate_argnums=(0,))
def scatter_slots(rows: dict, compact: dict, inv: jax.Array,
                  touched: jax.Array, mesh=None):
    """Write a compacted tick's output lanes back into the full-width
    rows: slot s takes compact lane ``inv[s]`` where ``touched[s]``,
    else keeps its old row (mid-prefill carries, empty slots — and pad
    lanes, which no slot maps to — are never written).  Implemented as
    a per-slot gather + select rather than a scatter, so duplicate pad
    indices can never race a live row.  ``rows`` (the full pool's
    per-slot subtrees) is donated — the output aliases it; the compact
    buffers are the tick's spent output and simply expire."""
    TRACE_COUNTS["scatter"] += 1
    t_slot = lambda ndim, ax: touched.reshape(
        (1,) * ax + (-1,) + (1,) * (ndim - ax - 1)
    )
    out = {
        "blocks": jax.tree.map(
            lambda f, c: jnp.where(
                t_slot(f.ndim, 1), jnp.take(c, inv, axis=1), f
            ),
            rows["blocks"], compact["blocks"],
        ),
        "logits": jnp.where(
            t_slot(rows["logits"].ndim, 0),
            jnp.take(compact["logits"], inv, axis=0), rows["logits"],
        ),
        "meta": jax.tree.map(
            lambda f, c: jnp.where(
                t_slot(f.ndim, 0), jnp.take(c, inv, axis=0), f
            ),
            rows["meta"], compact["meta"],
        ),
    }
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            slot_pool_shardings,
        )

        out = jax.lax.with_sharding_constraint(
            out, slot_pool_shardings(out, mesh)
        )
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def evict(pool: dict, slot: jax.Array) -> dict:
    """Free ``slot``: mark it empty.  The stale state/logits stay in
    place — the next ``insert`` overwrites them, and the decode tick
    masks inactive slots, so no scrubbing is needed."""
    meta = dict(pool["meta"])
    meta["active"] = _set_row(meta["active"], slot, False)
    meta["done"] = _set_row(meta["done"], slot, False)
    meta["prefilling"] = _set_row(meta["prefilling"], slot, False)
    return {"state": pool["state"], "logits": pool["logits"], "meta": meta}


# ------------------------------------------------- partial-prefill residency


@functools.partial(jax.jit, donate_argnums=(0,))
def stash_prefill(
    pool: dict,
    slot: jax.Array,
    state: dict,
    key: jax.Array,
    max_new: jax.Array,
    top_k: jax.Array,
    temperature: jax.Array,
    eos_id: jax.Array,
    adapter_id: jax.Array = 0,
) -> dict:
    """Park a PARTIAL prefill carry in ``slot``: the request occupies the
    slot (``active=True``) with its chunk-scan carry and its sampling
    meta, but ``prefilling=True`` keeps it out of the decode tick — the
    tick masks it from sampling AND from state writes (a tick's
    ``lm_step`` over the whole pool must not clobber the carry).  The
    slot's stale logits are left in place (masked; ``finish_prefill``
    writes the real ones).  Idempotent — re-stashing after more chunks
    just overwrites the carry."""
    meta = pool["meta"]
    new_meta = {
        "active": _set_row(meta["active"], slot, True),
        "done": _set_row(meta["done"], slot, False),
        "prefilling": _set_row(meta["prefilling"], slot, True),
        "key": _set_row(meta["key"], slot, key),
        "step": _set_row(meta["step"], slot, 0),
        "max_new": _set_row(meta["max_new"], slot, max_new),
        "top_k": _set_row(meta["top_k"], slot, top_k),
        "temperature": _set_row(meta["temperature"], slot, temperature),
        "eos_id": _set_row(meta["eos_id"], slot, eos_id),
        "adapter_id": _set_row(meta["adapter_id"], slot, adapter_id),
    }
    return {
        "state": _write_blocks(pool["state"], slot, state),
        "logits": pool["logits"],
        "meta": new_meta,
    }


@jax.jit
def read_state(pool: dict, slot: jax.Array):
    """Slice ``slot``'s batch-1 state pytree back out (resume a stashed
    prefill at the next budget grant).  NOT donated — the pool lives on."""
    return {
        "blocks": jax.tree.map(
            lambda p: jax.lax.dynamic_slice_in_dim(p, slot, 1, axis=1),
            pool["state"]["blocks"],
        )
    }


@functools.partial(jax.jit, donate_argnums=(0,))
def finish_prefill(pool: dict, slot: jax.Array, state: dict,
                   logits: jax.Array) -> dict:
    """Complete a chunked prefill: write the final carry + last logits and
    flip ``prefilling`` off — the next tick samples this slot's first
    token from ``fold_in(key, step=0)``, exactly like a fresh insert."""
    meta = dict(pool["meta"])
    meta["prefilling"] = _set_row(meta["prefilling"], slot, False)
    return {
        "state": _write_blocks(pool["state"], slot, state),
        "logits": _set_row(pool["logits"], slot, logits),
        "meta": meta,
    }
