"""Continuous-batching serving engine over the pooled recurrent-state cache.

One compiled decode tick advances EVERY occupied slot by ``tokens_per_tick``
tokens; finished and empty slots are masked, and new requests are admitted
into freed slots between ticks — bucketed prefill (inference/bucketing.py)
plus ``state_cache.insert`` write a request's state into its slot without
retracing anything.  Decode is weight-bandwidth-bound, so filling more
slots costs (nearly) nothing per tick: aggregate tokens/sec scales with
occupancy (docs/SERVING.md; scripts/bench_serving.py measures it against
sequential ``generate()`` calls).

Long prompts (``t > cfg.prefill_chunk_tokens``) prefill in CHUNKS
(serving/prefill.py) interleaved with decode ticks: each ``step()``
spends at most ``cfg.prefill_tokens_per_tick`` tokens of chunk work
(oldest request first) before running the tick, and a half-prefilled
request keeps its slot with its scan carry parked in the pool
(``state_cache.stash_prefill``; the tick masks such slots from sampling
and from state writes) until the next budget grant resumes it.  Short
prompts keep the PR-1 behavior: a one-shot pow2-bucketed prefill at
admission, not counted against the chunk budget (they are at most
~chunk-sized by construction).  This bounds both the TTFT of short
requests and the ITL of running slots while a long prompt streams in —
the head-of-line blocking ``bench_serving --long-prompt`` measures.

Speculative decoding (``cfg.spec_tokens = K > 0``; serving/
spec_decode.py, docs/SERVING.md "Speculative decoding") swaps the
decode tick for a K-token draft-verify tick: one ``lm_verify_chunk``
launch scores a drafter's K guesses for every live slot and commits
the longest correct prefix — up to K+2 tokens per full weight read,
greedy-only and token-identical to the non-speculative stream.

Parity contract: a request's token stream is bit-identical to a solo
``generate(params, cfg, prompt[None], key, ...)`` call with the same key
whenever ``request.top_k == engine.max_top_k`` (the static top-k width),
regardless of what else shares the batch.  The pieces that make this
hold, pinned by tests/test_serving.py and tests/test_prefill.py:

* both pad the same prompt to the same bucket — pow2 one-shot for short
  prompts, the chunk-aligned layout driven through the SAME jitted
  chunk step for long ones (neither is an engine knob: both live on
  ModelConfig / the bucketing module, so the two callers can never
  disagree);
* the step-i sampling key is ``fold_in(request_key, i)``, reproducible
  from the per-slot counter alone — and a vmapped per-row
  ``categorical`` draws the same bits as generate's batch-1 call;
* ``lm_step`` is row-independent, so co-batched strangers can't
  perturb a slot's logits.

Requests with ``top_k < max_top_k`` are served via masking (positions
beyond the slot's k get -inf) — a valid top-k draw, but from a different
noise stream than a solo ``generate(top_k=k)`` call would use.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.inference.bucketing import next_pow2_bucket, pad_to_bucket
from mamba_distributed_tpu.obs import NULL_TRACER, StreamingHistogram
from mamba_distributed_tpu.inference.generate import vocab_pad_mask
from mamba_distributed_tpu.models.attention import attention_page_count
from mamba_distributed_tpu.models.lm import (
    init_lm_blocks_state,
    lm_prefill,
    lm_step,
)
from mamba_distributed_tpu.serving import adapters as adapters_mod
from mamba_distributed_tpu.serving import prefix_cache as prefix_cache_mod
from mamba_distributed_tpu.serving import spec_decode
from mamba_distributed_tpu.serving import state_cache
from mamba_distributed_tpu.serving.sessions import SessionStoreError
from mamba_distributed_tpu.serving.prefix_cache import PrefixCache
from mamba_distributed_tpu.serving.prefill import (
    cast_decode_params,
    chunk_inputs,
    plan_chunks,
    prefill_chunk,
)
from mamba_distributed_tpu.serving.scheduler import (
    FCFSScheduler,
    GenerationRequest,
    GenerationResult,
    RequestStatus,
    TenantQuotaExceeded,
    TokenEvent,
    _Tracked,
    check_tenant_quota,
)
from mamba_distributed_tpu.utils.metrics import ServingMetrics

# Python-side-effect trace counters (one bump per jit trace) — the
# bucketing exists to bound these; tests/test_serving.py pins them (the
# chunk step's counter lives in serving/prefill.py, pinned by
# tests/test_prefill.py).
TRACE_COUNTS = {"prefill": 0, "tick": 0}


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"))
def _prefill(params: dict, ids: jax.Array, mask: jax.Array, cfg: ModelConfig,
             mesh=None, adapter_ids=None):
    """Bucketed batch-1 prompt prefill -> (last_logits (1, V), state).

    ``mesh`` (static; only passed when the serving mesh has a model
    axis > 1) re-asserts the tensor-parallel weight layout so this
    prefill partitions exactly like ``generate(mesh=)``'s — an input to
    the engine==generate() parity argument at ``model > 1``.
    ``adapter_ids`` (LoRA engines only; (1,) int32) binds the request's
    factor-pool row so the prefill computes the same segmented delta
    the ticks will (serving/adapters.py)."""
    TRACE_COUNTS["prefill"] += 1
    if mesh is not None:
        from mamba_distributed_tpu.parallel.sharding import (
            constrain_serving_params,
        )

        params = constrain_serving_params(params, mesh)
    if adapter_ids is not None:
        params = adapters_mod.bind_adapter_ids(params, adapter_ids)
    return lm_prefill(params, cfg, ids, token_mask=mask)


@functools.partial(
    jax.jit, static_argnames=("cfg", "k_max", "steps", "mesh", "n_micro"),
    donate_argnums=(1,),
)
def _tick(params: dict, pool: dict, tbl=None, lengths=None, *,
          cfg: ModelConfig, k_max: int, steps: int, mesh=None,
          n_micro=None):
    """Advance every slot ``steps`` tokens.  Returns (pool', tokens
    (steps, S), emitted (steps, S), done (steps, S)) — ``emitted[j, s]``
    marks a real token (slot live at sub-step j), ``done[j, s]`` the
    slot's finish state after it; the rest is masked garbage.  The host
    consumes ``done`` rather than re-deriving the finish rule, so there
    is exactly one copy of it (here).

    HYBRID stacks additionally take the host-owned paged-KV metadata:
    ``tbl`` (S, B) int32 — page-table rows sliced to the tick's page
    BUCKET B (pow2 of the largest active slot's allocation, so attention
    reads scale with what is actually resident, and one trace per bucket
    covers every occupancy/length mix) — and ``lengths`` (S,) int32.
    The per-sub-step KV writes of non-live slots are routed to the trash
    page via ``lm_step``'s write_mask, so a dead slot can never touch a
    page that was recycled to someone else; the host re-derives the
    lengths advance from ``emitted`` (bit-equal: both count live
    sub-steps), so nothing metadata-shaped needs fetching.

    Mirrors generate()'s decode loop exactly: sample from the carried
    logits with key fold_in(key, step), then lm_step.  Slots that hit
    their eos keep feeding it forward (same as generate's eos_id path);
    slots that are empty or budget-done still compute — that waste is
    the price of a single static-shape trace, and it is reclaimed by
    admitting new requests into those slots between ticks.

    ``n_micro`` (static; only ever set when ``mesh`` has a ``stage``
    axis > 1 and the stack is pure-SSM) engages the explicit GPipe
    schedule inside ``lm_step``: the slot lanes split into ``n_micro``
    microbatches that flow through the stage-resident layer groups
    (parallel/pipeline.pipelined_decode_layers) — bitwise identical to
    the sequential layer scan, only the placement of work changes.
    ``n_micro=None`` at ``stage > 1`` still runs correctly: GSPMD
    executes the stage-sharded layer scan without the explicit
    microbatch clock.
    """
    TRACE_COUNTS["tick"] += 1
    pad_mask = vocab_pad_mask(cfg)
    col = jnp.arange(k_max)[None, :]
    hybrid = tbl is not None
    if mesh is not None:
        # the shard_slots path (static ``mesh``, a serving_mesh): pin
        # the slot/page state — and the host-owned per-slot tick inputs
        # — to their data-axis layout so the batched lm_step partitions
        # its batch axis instead of decaying to one device, whatever
        # the between-ticks insert/evict propagation concluded.  With a
        # model axis > 1 the WEIGHTS get the same treatment on their
        # tensor-parallel axis (serving_param_shardings): GSPMD then
        # runs every slot's lm_step as d_inner/head-sharded matmuls
        # with compiler-inserted all-reduces — 2-D parallelism, slots
        # over data x weights over model.
        from mamba_distributed_tpu.parallel.sharding import (
            constrain_serving_params,
            slot_axis_sharding,
            slot_pool_shardings,
        )

        if (dict(mesh.shape).get("model", 1) > 1
                or dict(mesh.shape).get("stage", 1) > 1):
            params = constrain_serving_params(params, mesh)
        pool = jax.lax.with_sharding_constraint(
            pool, slot_pool_shardings(pool, mesh)
        )
        if hybrid:
            tbl = jax.lax.with_sharding_constraint(
                tbl, slot_axis_sharding(mesh)
            )
            lengths = jax.lax.with_sharding_constraint(
                lengths, slot_axis_sharding(mesh)
            )
    # multi-tenant LoRA (serving/adapters.py): bind each slot's factor-
    # pool row from the pool meta into the attached pools — a no-op
    # tree walk on LoRA-less params (no "lora" subtrees), and the ids
    # are constant across the tick's sub-steps (admission happens
    # between ticks), so one bind serves the whole scan.
    params = adapters_mod.bind_adapter_ids(
        params, pool["meta"]["adapter_id"]
    )

    def one(carry, _):
        pool, lengths = carry
        meta = pool["meta"]
        # a slot mid-chunked-prefill is resident but NOT live: it emits
        # nothing, and its parked scan carry must survive the tick
        live = meta["active"] & ~meta["done"] & ~meta["prefilling"]
        has_eos = meta["eos_id"] >= 0
        keys = jax.vmap(jax.random.fold_in)(meta["key"], meta["step"])
        vals, idx = jax.lax.top_k(pool["logits"] + pad_mask, k_max)
        vals = jnp.where(col < meta["top_k"][:, None], vals, -jnp.inf)
        # per-row categorical: same bits as generate's batch-1 draw
        choice = jax.vmap(
            lambda k, v, t: jax.random.categorical(k, v / t)
        )(keys, vals, meta["temperature"])
        tok = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
        tok = jnp.where(meta["done"] & has_eos, meta["eos_id"], tok)
        if hybrid:
            state_in = {**pool["state"], "attn_meta": (tbl, lengths)}
            logits, state = lm_step(params, cfg, state_in, tok,
                                    write_mask=live)
            lengths = state["attn_meta"][1]
            state = {k: v for k, v in state.items() if k != "attn_meta"}
        else:
            logits, state = lm_step(
                params, cfg, pool["state"], tok,
                pipeline=((mesh, n_micro) if n_micro else None),
            )
        # empty/done slots may compute garbage freely (masked, overwritten
        # by the next insert), but a prefilling slot's rows hold a REAL
        # carry — keep them (select per (L, S, ...) leaf on the S axis).
        # Only the conv+SSM "blocks" subtree has a per-slot axis; the
        # attention page pool is protected by write_mask instead.
        hold = meta["prefilling"]
        blocks = jax.tree.map(
            lambda new, old: jnp.where(
                hold.reshape((1, -1) + (1,) * (new.ndim - 2)), old, new
            ),
            state["blocks"],
            pool["state"]["blocks"],
        )
        state = {**state, "blocks": blocks}
        logits = jnp.where(hold[:, None], pool["logits"], logits)
        step = meta["step"] + live.astype(jnp.int32)
        done = meta["done"] | (
            live & ((has_eos & (tok == meta["eos_id"])) | (step >= meta["max_new"]))
        )
        new_pool = {
            "state": state,
            "logits": logits,
            "meta": {**meta, "step": step, "done": done},
        }
        return (new_pool, lengths), (tok, live, done)

    (pool, _), (tokens, emitted, done) = jax.lax.scan(
        one, (pool, lengths), None, length=steps
    )
    return pool, tokens, emitted, done


class ServingEngine:
    """Continuous-batching host loop: FCFS admission -> compiled ticks.

    Args:
      params: trained fp32 params (cast once to the decode layout here).
      cfg: ModelConfig.  Hybrid stacks (``attn_layer_idx`` non-empty)
        serve through the paged attention KV pool: admission reserves
        ceil((prompt + max_new) / kv_page_tokens) pages up front (a
        request waits in the queue while the pool is short), every
        hybrid prompt prefills through the chunk step (which writes
        straight into its slot's pages), and eviction recycles the
        pages.  Requests must fit ``cfg.kv_slot_tokens``.
      capacity: slot count S — the max concurrent requests.
      max_top_k: static top-k width of the compiled sampler; per-request
        ``top_k`` may be anything in [1, max_top_k] (see parity note in
        the module docstring).
      tokens_per_tick: decode sub-steps fused into one compiled tick.
        Larger amortizes dispatch; smaller admits waiting requests
        sooner (admission only happens between ticks).
      prefill_tokens_per_tick: chunk-prefill token budget spent between
        consecutive ticks (oldest in-flight prefill first; at least one
        chunk per step so progress is guaranteed).  None (default) takes
        ``cfg.prefill_tokens_per_tick``; 0 => unbounded.  Short-prompt
        one-shot prefills are NOT budgeted — each is at most ~one chunk
        of work, the PR-1 admission behavior.
      retain_results: keep every finished request's GenerationResult in
        ``self.results`` (what ``run()`` reads).  A long-lived streaming
        server consuming TokenEvents should pass False — retention
        grows host memory without bound — and the final event's
        ``done``/``finish_reason`` carries the completion signal.
      metrics: a ServingMetrics, or None to create one.  Give it a
        ``jsonl_path`` to stream per-tick and per-request records.
      tracer: an obs.SpanTracer for host-side phase spans
        (``serving_admit`` / ``serving_prefill`` /
        ``serving_prefill_chunk`` / ``serving_tick``); default
        NULL_TRACER (off).  Per-request spans carry the request's
        ``trace`` id and tick spans/records the live trace-id set, so
        ``scripts/trace_export.py`` can flow-link one request's journey
        across streams.  Strictly host-side: enabling it adds zero
        device syncs and zero jit traces (pinned by tests/test_obs.py).
      slo: an obs.SLOMonitor fed every finished request's latency
        record (rolling-window p95 targets -> breach events); None
        (default) off.  The router shares ONE monitor across replicas
        so the window is fabric-wide.
      compile_watchdog: an obs.CompileWatchdog (already installed on
        jax.monitoring) drained once per tick — window deltas stamp
        ``compiles``/``compile_ms`` on the tick record, lifetime
        totals feed summary()["compile"] and GET /metrics.  None
        (default) off: records stay byte-stable.
      tick_regression: an obs.TickRegressionDetector fed every tick's
        wall ms (EWMA baseline; transition-only ``tick_regression``
        events when ticks run a factor slower than steady state).
        None (default) off.
      mesh: a ``parallel/mesh.serving_mesh`` — the sharded path (2-D
        ``(data, model)``, or 3-D ``(data, stage, model)`` when the
        pipeline axis is on).  Slot/page state and the tick's batch
        axis partition over the mesh's DATA axis; the weights
        partition over its MODEL axis (tensor parallel: Mamba d_inner
        channels, attention heads, embedding/head vocab —
        parallel/sharding.serving_param_specs; ``model=1`` replicates
        them, the exact pre-TP layout); the scan-over-layers parameter
        stacks AND the per-layer slot-state stacks partition their
        leading LAYER axis over the STAGE axis (GPipe residency: each
        stage holds only its own layers' weights, conv/SSM carries
        and KV page pools).  Pure-SSM decode ticks at ``stage > 1``
        additionally run the explicit microbatched clock
        (parallel/pipeline.pipelined_decode_layers) when the live
        width tiles over the stages — bitwise identical either way.
        One engine's pool and weights span every device in the mesh;
        ``capacity`` must divide over the data shards, d_inner/heads/
        vocab over the model shards, and every stacked layer family
        over the stage shards (checked here, loudly).  None (default)
        builds a mesh from ``cfg.serving_data_shards`` x
        ``cfg.serving_stage_shards`` x ``cfg.serving_model_shards``
        when any knob is > 1, else everything stays single-device.
        Host bookkeeping follows the device layout: a slot resident
        in data-shard d draws KV pages only from shard d's contiguous
        page range (state_cache.PagePool); the model and stage axes
        never touch page accounting — pages tile over data only.
      prefix_cache: a serving/prefix_cache.PrefixCache, or None to
        build one from ``cfg.prefix_cache_entries`` (> 0 enables; the
        default 0 keeps the cache off).  Admission matches the longest
        cached chunk-aligned prefix of each prompt and seeds the slot
        from the snapshot — a FULL hit inserts the cached state+logits
        outright (zero prefill compute, near-zero TTFT), a partial hit
        resumes chunking at the first uncached chunk.  Warm streams
        stay bit-identical to cold ones because a snapshot is the
        literal output of the identical chunk computation.  Hybrid
        entries pin KV pages in THIS engine's pool (copy-on-write
        sharing across slots, refcounted) — hybrid caches are engine-
        private; pure-SSM caches may be shared with
        ``generate(prefix_cache=)`` under the same params.

      migrate_hook: the disaggregated prefill/decode handoff
        (serving/router.py installs it on PREFILL-role replicas'
        engines).  Called as ``hook(tracked, package)`` for every slot
        that just turned decodable with zero tokens emitted — i.e. at
        prefill-complete, whether the prefill was chunked, one-shot,
        or a full prefix-cache hit.  ``package()`` serializes the
        migration artifact (the O(1) conv/SSM carry + last logits,
        plus hybrid KV page contents); a True return means the router
        re-placed the request on a decode replica (this engine frees
        the slot and its pages), False means no decode capacity — the
        slot decodes HERE (mixed-mode fallback, offered exactly once
        via ``no_migrate`` so a declined request never stalls).  The
        receiving engine admits the artifact via ``submit_migrated``
        and ``state_cache.restore`` — the resumed stream is bit-exact
        (the preempt/resume contract, tests/test_disagg.py).

      adapters: a ``serving/adapters.AdapterRegistry`` of named LoRA
        adapters (read only when ``cfg.lora_max_adapters > 0``; None
        builds an empty registry from the engine's own params —
        register before submitting).  The engine keeps its own
        bounded device ``AdapterCache`` of factor slots over the
        registry: admission ``acquire``s the request's adapter slot
        like it reserves KV pages (waits when every slot is pinned —
        no mid-flight miss), refcounts pin it while the stream is
        resident, and the per-slot ids ride the pool meta so slots
        running DIFFERENT adapters share one compiled launch
        (docs/SERVING.md "Multi-tenant LoRA").  Share one registry
        across a router's replicas so a migration target re-pins the
        factors from its own cache.  Streams under adapter ``a``
        match solo ``generate()`` on ``adapters.merge(params, a)``
        via ``ops/quant.assert_stream_close`` (the segmented delta
        re-associates float sums; tests/test_tenant_lora.py).
        Int8 weights + LoRA is a ROADMAP residual — rejected here.

      drafter: a ``serving/spec_decode.Drafter`` for speculative
        decoding (only read when ``cfg.spec_tokens > 0``).  None builds
        the config's drafter (``spec_drafter="ngram"``; ``"model"``
        REQUIRES an explicit ``ModelDrafter(draft_params, draft_cfg)``
        — the companion's params aren't derivable from cfg).  Draft
        quality moves the acceptance rate, never the tokens (greedy
        speculation is lossless), so any drafter is parity-safe.
        Drafter streams are keyed by ENGINE-LOCAL request ids — give
        each engine/replica its own instance rather than sharing one
        across a router fabric.

    Priority + preemption: requests carry a ``priority`` (higher wins;
    default ``cfg.serving_default_priority``).  When the queue's best
    request outranks a resident DECODING slot and no slot is free, the
    engine preempts the lowest-priority victim — its carry + logits
    swap to host RAM (``state_cache.restore`` puts them back with the
    token counter intact, so the resumed stream continues bit-exactly),
    its KV page refs ride along (no page churn, no re-prefill).  With
    every request at one priority the scheduler is the FCFS queue it
    always was.

    Prefill buckets are the module defaults of inference/bucketing.py —
    deliberately not a knob, so the engine and a solo ``generate()``
    call can never pad the same prompt differently (the parity
    contract depends on identical padding).
    """

    def __init__(
        self,
        params: dict,
        cfg: ModelConfig,
        capacity: int = 8,
        max_top_k: int = 50,
        tokens_per_tick: int = 8,
        prefill_tokens_per_tick: int | None = None,
        retain_results: bool = True,
        metrics: ServingMetrics | None = None,
        tracer=NULL_TRACER,
        slo=None,
        mesh=None,
        prefix_cache: PrefixCache | None = None,
        migrate_hook=None,
        drafter: spec_decode.Drafter | None = None,
        adapters: adapters_mod.AdapterRegistry | None = None,
        session_store=None,
        compile_watchdog=None,
        tick_regression=None,
    ):
        if not 1 <= max_top_k <= cfg.vocab_size_padded:
            raise ValueError(
                f"max_top_k={max_top_k} must be in [1, {cfg.vocab_size_padded}]"
            )
        if tokens_per_tick < 1:
            raise ValueError("tokens_per_tick must be >= 1")
        if prefill_tokens_per_tick is None:
            prefill_tokens_per_tick = cfg.prefill_tokens_per_tick
        if prefill_tokens_per_tick < 0:
            raise ValueError("prefill_tokens_per_tick must be >= 0 "
                             "(0 => unbounded)")
        if mesh is None and (cfg.serving_data_shards > 1
                             or cfg.serving_model_shards > 1
                             or cfg.serving_stage_shards > 1):
            from mamba_distributed_tpu.parallel.mesh import serving_mesh

            mesh = serving_mesh(cfg.serving_data_shards,
                                model_shards=cfg.serving_model_shards,
                                stage_shards=cfg.serving_stage_shards)
        self.mesh = mesh
        self.num_shards = 1 if mesh is None else int(mesh.shape["data"])
        self.model_shards = (
            1 if mesh is None else int(dict(mesh.shape).get("model", 1))
        )
        self.stage_shards = (
            1 if mesh is None else int(dict(mesh.shape).get("stage", 1))
        )
        if capacity % self.num_shards:
            raise ValueError(
                f"capacity={capacity} must divide over "
                f"serving_data_shards={self.num_shards} (each data shard "
                f"holds capacity/shards slot rows)"
            )
        if self.model_shards > 1:
            # clear rejection at CONSTRUCTION (d_inner/heads/vocab must
            # tile over the model axis), not a GSPMD error mid-flight
            from mamba_distributed_tpu.parallel.sharding import (
                validate_serving_model_shards,
            )

            validate_serving_model_shards(cfg, self.model_shards)
        if self.stage_shards > 1:
            # same construction-time loudness for the pipeline axis:
            # every stacked layer family must tile over the stages
            from mamba_distributed_tpu.parallel.sharding import (
                validate_serving_stage_shards,
            )

            validate_serving_stage_shards(cfg, self.stage_shards)
        self.cfg = cfg
        self.capacity = capacity
        self.max_top_k = max_top_k
        self.tokens_per_tick = tokens_per_tick
        self.prefill_tokens_per_tick = prefill_tokens_per_tick
        self.retain_results = retain_results
        self.pool = state_cache.init_pool(  # validates cfg
            cfg, capacity, self.num_shards
        )
        self._params = cast_decode_params(params, cfg=cfg)
        if mesh is not None:
            from mamba_distributed_tpu.parallel.sharding import (
                serving_param_shardings,
                slot_pool_shardings,
            )

            # weights tensor-parallel over the model axis (replicated
            # when model=1 — serving_param_specs degenerates to P()),
            # slot/page state partitioned over the data axis — the
            # layout every subsequent insert/evict/tick inherits (and
            # the tick re-asserts via its constraints)
            self._params = jax.device_put(
                self._params, serving_param_shardings(self._params, mesh)
            )
            self.pool = jax.device_put(
                self.pool, slot_pool_shardings(self.pool, mesh)
            )
        # the mesh the chunk step / one-shot prefill need for weight
        # constraints — None when neither the model nor the stage axis
        # partitions the weights, so the sharding-off jit signatures
        # (and trace counts) are byte-identical to the pre-TP engine
        self._tp_mesh = (
            mesh if (self.model_shards > 1 or self.stage_shards > 1)
            else None
        )
        self.scheduler = FCFSScheduler(
            default_priority=cfg.serving_default_priority
        )
        self.metrics = metrics or ServingMetrics(capacity)
        self.tracer = tracer
        self.slo = slo
        # --- live telemetry plane (obs/watchdog.py + obs/slo.py;
        # docs/OBSERVABILITY.md "Live telemetry plane"): an attached
        # CompileWatchdog is drained once per tick — its window deltas
        # become the record's `compiles`/`compile_ms` stamps and its
        # lifetime totals summary()["compile"] / the /metrics counters.
        # An attached TickRegressionDetector is fed every tick's wall
        # ms (EWMA baseline -> transition-only `tick_regression`
        # events).  Both None (default) keep records byte-stable.
        self.compile_watchdog = compile_watchdog
        if compile_watchdog is not None:
            self.metrics.configure_compile()
        self.tick_regression = tick_regression
        # goodput: analytic FLOPs rates (utils/flops.py, the "model"
        # convention — parameter matmuls + recurrent state math, no
        # device counters, no syncs) so every serving_tick record can
        # carry a host-computed serving_mfu.  Decode rates are per
        # sampled token; chunk-prefill rates per real prompt token at
        # the chunk's sequence length.
        from mamba_distributed_tpu.utils.flops import (
            flops_per_token,
            peak_flops_per_chip,
        )

        # with chunking disabled (one-shot only) price prefill at the
        # DEFAULT chunk width rather than seq_len=1: the length only
        # moves the O(t) attention terms, and charging a hybrid's
        # one-shot prefill at decode-length rates would systematically
        # understate serving_mfu in exactly that config
        prefill_seq = cfg.effective_prefill_chunk_tokens or 256
        self.metrics.configure_goodput(
            flops_per_decode_token=flops_per_token(
                cfg, 1, training=False, convention="model"),
            flops_per_prefill_token=flops_per_token(
                cfg, prefill_seq, training=False, convention="model"),
            peak_flops=peak_flops_per_chip() * self.num_shards
            * self.model_shards * self.stage_shards,
        )
        if self.stage_shards > 1:
            self.metrics.configure_pipeline(self.stage_shards)
        self._free: list[int] = list(range(capacity))
        self._slots: dict[int, _Tracked] = {}
        # slots holding a partial chunked prefill, in admission order;
        # the per-tick budget round-robins ONE chunk at a time across
        # them so one long prompt can't starve another's TTFT
        self._prefill_queue: list[int] = []
        # --- hybrid paged-KV bookkeeping (host-owned; the tick takes the
        # sliced table + lengths as plain arguments, so admission/evict
        # page moves are pure host work) ---
        # --- speculative decoding (serving/spec_decode.py; docs/
        # SERVING.md "Speculative decoding").  K = cfg.spec_tokens > 0
        # swaps the decode tick for a draft-verify tick: one
        # lm_verify_chunk launch of width W = K+1 per step, committing
        # the longest correct prefix (up to W+1 tokens) per full weight
        # read.  Greedy-only — submit() rejects top_k != 1.  K = 0 is
        # the byte-stable status quo: no spec state, no record stamps,
        # identical traces.
        self.spec = cfg.spec_tokens > 0
        if self.spec:
            # tokens_per_tick paces the NON-speculative tick; in spec
            # mode each step runs exactly one verify launch instead
            self.spec_width = cfg.spec_tokens + 1
            self.drafter = (drafter if drafter is not None
                            else spec_decode.make_drafter(cfg))
            self._spec_drafted = 0  # per-window gauges -> serving_tick
            self._spec_accepted = 0
            self._spec_streams = 0  # live slot-launches in the window
            # verify lanes the LAST tick computed: debited from the next
            # step's chunk-prefill budget so speculation's extra per-step
            # work is accounted against the same interleaving bound
            # (the serving_mfu / ITL honesty contract)
            self._spec_budget_debt = 0
            self.metrics.configure_speculation(
                cfg.spec_tokens, cfg.spec_drafter
            )
        else:
            self.drafter = None
        self.hybrid = bool(cfg.attn_layer_idx)
        if self.hybrid:
            self.page_pool = state_cache.PagePool(
                state_cache.hybrid_pool_pages(cfg, capacity,
                                              self.num_shards),
                num_shards=self.num_shards,
            )
            # spec mode appends one permanent trash column: the verify
            # chunk may write up to W tokens past a slot's reservation
            # (drafts beyond its budget), and those writes must clamp
            # onto a trash entry — never wrap onto the slot's own last
            # live page (attention_mixer_chunk clips page indices to
            # the table width)
            self._page_tbl = np.zeros(
                (capacity, cfg.kv_pages_per_slot + (1 if self.spec else 0)),
                np.int32,
            )
            self._kv_len = np.zeros((capacity,), np.int32)
            self._page_allocs = 0  # per-step gauges -> serving_tick
            self._page_frees = 0
        # --- prefix-state cache (serving/prefix_cache.py): host-side
        # LRU of chunk-boundary carries + full-prompt snapshots keyed
        # by prompt-prefix hash.  Off unless cfg.prefix_cache_entries
        # > 0 or an explicit instance is passed.  Hybrid entries pin
        # KV pages in THIS engine's pool (refcounts; the LRU's evict
        # hook decrefs), so hybrid caches are engine-private.
        if prefix_cache is None and cfg.prefix_cache_entries > 0:
            prefix_cache = PrefixCache(
                max_entries=cfg.prefix_cache_entries,
                max_bytes=cfg.prefix_cache_bytes,
                min_hits=cfg.prefix_min_chunk_hits,
            )
        self.prefix_cache = prefix_cache
        if prefix_cache is not None:
            if self.hybrid:
                prefix_cache.evict_hook = self._drop_entry_pages
                # bytes one physical page pins across every layer's K+V
                # pool — the KV share of an entry's byte accounting
                self._page_nbytes = int(sum(
                    x.nbytes // x.shape[1]
                    for x in jax.tree.leaves(
                        self.pool["state"]["attn_blocks"])
                ))
            self.metrics.configure_prefix_cache()
        # --- quantized serving (ops/quant.py; docs/SERVING.md
        # "Quantized serving"): resident-bytes gauges, installed only
        # when quant is on so bf16 engines' records/summaries stay
        # byte-stable.  weight bytes are the device-resident decoded
        # tree (int8 kernels + f32 scales when quantized); page-pool
        # bytes the hybrid KV pools incl. their scale arrays.
        self.quantized_weights = cfg.serving_weight_dtype == "int8"
        self.quantized_kv = self.hybrid and cfg.kv_quantized
        if self.quantized_weights or self.quantized_kv:
            from mamba_distributed_tpu.ops.quant import param_bytes

            self._weight_bytes = param_bytes(self._params)
            self._pool_bytes = (
                sum(int(x.nbytes) for x in
                    jax.tree.leaves(self.pool["state"]["attn_blocks"]))
                if self.hybrid else None
            )
            self._quant_stamp = {"weights": cfg.serving_weight_dtype,
                                 "kv": cfg.kv_page_dtype}
            self.metrics.configure_memory(
                weight_bytes=self._weight_bytes,
                page_pool_bytes=self._pool_bytes or 0,
                weight_dtype=cfg.serving_weight_dtype,
                kv_dtype=cfg.kv_page_dtype,
            )
        # --- occupancy-adaptive compacted ticks (docs/SERVING.md
        # "Occupancy-adaptive ticks"): cfg.tick_compaction gathers the
        # LIVE slots into a pow2 lane bucket per data shard, runs the
        # existing tick/verify jit at bucket width, scatters back.
        # Off (default) is the byte-stable status quo — no gather/
        # scatter traces, no record stamps.
        self.compaction = cfg.tick_compaction
        if self.compaction:
            # current per-shard lane bucket (pow2): grows immediately
            # when live slots need it, shrinks only after
            # cfg.compaction_hysteresis_ticks consecutive smaller-
            # sufficient ticks so occupancy jitter around a pow2
            # boundary can't thrash gather/tick/scatter recompiles
            self._compact_bucket = 1
            self._shrink_streak = 0
            self.metrics.configure_compaction()
        # --- multi-tenant LoRA serving (serving/adapters.py; docs/
        # SERVING.md "Multi-tenant LoRA"): cfg.lora_max_adapters > 0
        # attaches bounded device factor pools to the decode params and
        # threads per-slot adapter ids through every launch.  Off
        # (default) is the byte-stable status quo: no pools, no record
        # stamps, identical traces.
        self.lora = cfg.lora_max_adapters > 0
        if self.lora:
            if self.quantized_weights:
                raise ValueError(
                    "int8 base weights + a LoRA delta is a ROADMAP "
                    "residual (the two dequant paths don't compose "
                    "yet): serve LoRA adapters with "
                    "serving_weight_dtype='bf16', or quantize without "
                    "lora_max_adapters"
                )
            self.adapters = (adapters if adapters is not None
                             else adapters_mod.AdapterRegistry(cfg, params))
            if self.adapters.rank != cfg.lora_rank:
                raise ValueError(
                    f"adapter registry rank {self.adapters.rank} != "
                    f"cfg.lora_rank {cfg.lora_rank} — the factor pools "
                    f"are static-shape; one rank per engine"
                )
            self.adapter_cache = adapters_mod.AdapterCache(
                self.adapters, cfg.effective_lora_cache_slots,
                compute_dtype=cfg.compute_dtype,
            )
            self._base_decode_params = self._params
            self._lora_version = -1
            self._refresh_lora_params()
            # window deltas for the tick-record gauges (the cache keeps
            # cumulative counters)
            self._ad_hits0 = 0
            self._ad_misses0 = 0
            self._ad_evictions0 = 0
            self.metrics.configure_adapters(
                cfg.lora_max_adapters, cfg.lora_rank,
                cfg.effective_lora_cache_slots,
            )
        else:
            self.adapters = None
            self.adapter_cache = None
        # --- per-tenant fairness quota + online-tuning hot swaps
        # (docs/SERVING.md "Online adapter tuning"): cfg.tenant_max_slots
        # caps the concurrent resident slots one tenant (adapter BASE
        # name — versions share the cap) may hold; an over-quota
        # admission requeues with the named TenantQuotaExceeded counted,
        # never shed.  0 (default) is the byte-stable status quo.
        self.tenant_max_slots = getattr(cfg, "tenant_max_slots", 0)
        self._quota_stalls = 0  # window counter -> tick records
        self._hot_swaps = 0  # mid-stream adapter version swaps, ditto
        if self.tenant_max_slots:
            self.metrics.configure_tuning()
        # --- durable session fabric (serving/sessions/; docs/SERVING.md
        # "Durable sessions"): an attached SessionStore lets streams
        # PARK — slot, KV pages and adapter ref all released, the
        # stream serialized into the migration artifact (+ its emitted
        # tokens) — and resume bit-exactly later, here or on any
        # replica.  The admission valve parks pressure victims through
        # it (full artifact to the tiered store, a tiny session-pointer
        # snapshot on the requeued tracker) instead of pinning their
        # carries in host RAM forever.  Off (default) is the
        # byte-stable status quo: no stamps, no spans, no sweeps.
        self.session_store = session_store
        self._session_parks = 0  # window counters -> tick records
        self._session_resumes = 0
        self._session_expires = 0
        if session_store is not None:
            self.metrics.configure_sessions()
        # recently finished streams' tokens (bounded), so a restarted
        # front end can re-attach an SSE stream whose final events died
        # with the old connection (stream_state; docs/SERVING.md
        # "Deploying as a service" — SSE resume tokens).  In-flight
        # streams replay from their trackers; this ring only covers the
        # just-finished tail.
        self._recent_finished: dict[int, tuple[list[int], str]] = {}
        self._pc_hits = 0  # per-window gauges -> serving_tick records
        self._pc_misses = 0
        self._pc_saved_tokens = 0
        self._preemptions = 0
        # disaggregated prefill/decode handoff (serving/router.py):
        # the hook a prefill-role replica's router installs, plus the
        # per-window migration counters -> serving_tick records
        self.migrate_hook = migrate_hook
        self._migrations_out = 0
        self._migrations_in = 0
        # prefill accounting awaiting a tick record: tick-less steps
        # (everything resident still mid-prefill) roll their stall /
        # chunk counters into the NEXT tick's jsonl record so the
        # serving_tick stream never drops work (obs_report.py totals)
        self._pending_stall_ms = 0.0
        self._pending_chunk_tokens = 0
        self._pending_chunk_real_tokens = 0  # non-pad (goodput useful)
        self._pending_chunk_ms = 0.0
        # one-shot (unchunked) admissions in the window: real prompt
        # tokens vs padded bucket lanes — without these the goodput
        # fields would credit a 33-token (chunked) prompt but not a
        # 32-token (one-shot) one over the same wall window
        self._pending_oneshot_real_tokens = 0
        self._pending_oneshot_lanes = 0
        self.results: dict[int, GenerationResult] = {}

    # ------------------------------------------------------------- admission

    def submit(self, request: GenerationRequest) -> int:
        """Queue a request; returns its request_id."""
        return self._submit_tracked(request).request_id

    def _submit_tracked(self, request: GenerationRequest) -> _Tracked:
        """``submit`` returning the scheduler's tracker itself (what
        ``submit_migrated`` decorates with the migration artifact)."""
        if not 1 <= request.top_k <= self.max_top_k:
            raise ValueError(
                f"request top_k={request.top_k} must be in "
                f"[1, max_top_k={self.max_top_k}]"
            )
        if self.spec and request.top_k != 1:
            raise ValueError(
                f"speculative decoding (cfg.spec_tokens="
                f"{self.cfg.spec_tokens}) is greedy-only: request "
                f"top_k={request.top_k} must be 1 (argmax).  Sampling-"
                f"mode rejection sampling is a ROADMAP residual; serve "
                f"sampled requests on a spec_tokens=0 engine"
            )
        adapter = getattr(request, "adapter", None)
        if adapter:
            if not self.lora:
                raise ValueError(
                    f"request names adapter {adapter!r} but this engine "
                    f"serves the base model only "
                    f"(cfg.lora_max_adapters=0); enable multi-tenant "
                    f"LoRA serving (docs/SERVING.md) or drop the "
                    f"adapter field"
                )
            if adapter not in self.adapters:
                # the NAMED error, at submit — never a hang, and the
                # HTTP front end maps it to a 404 (serving/adapters.py)
                raise adapters_mod.UnknownAdapterError(
                    f"unknown adapter {adapter!r}: this engine's "
                    f"registry holds {self.adapters.names()}"
                )
            # pin the VERSION at submit: a bare name canonicalizes to
            # its latest registered version (the identity for a single-
            # version adapter — bytes unchanged vs PR-15), so a v(N+1)
            # registered mid-flight never silently retargets an
            # already-queued stream (prefix salt, cache slot, records
            # and failover replay all carry the pinned name).  With
            # cfg.lora_ab_fraction < 1 the pin A/B-routes across the
            # last two versions (_ab_resolve)
            request.adapter = self._ab_resolve(request, adapter)
        if self.hybrid:
            need = len(request.prompt_ids) + request.max_new_tokens
            if need > self.cfg.kv_slot_tokens:
                raise ValueError(
                    f"hybrid request needs {need} KV tokens (prompt + "
                    f"max_new_tokens) > cfg.kv_slot_tokens="
                    f"{self.cfg.kv_slot_tokens}; raise the knob or split "
                    f"the request"
                )
            need_pages = attention_page_count(self.cfg, need)
            if need_pages > self._max_shard_pages():
                # an oversubscribed pool (kv_pool_pages < slots * pages)
                # may be smaller than one slot's budget — and a SHARDED
                # pool confines each slot to its own shard's page range:
                # admission waits for frees, so a request bigger than
                # any shard could EVER free would stall the queue
                # forever — reject it up front (the same check guards
                # _admit for requests that bypass submit)
                raise ValueError(
                    f"hybrid request needs {need_pages} KV pages but the "
                    f"page pool's widest shard only holds "
                    f"{self._max_shard_pages()} "
                    f"({self.page_pool.num_pages} total over "
                    f"{self.num_shards} shard(s); cfg.kv_pool_pages); "
                    f"it could never be admitted"
                )
        return self.scheduler.submit(request)

    def submit_migrated(self, request: GenerationRequest, snapshot: dict,
                        *, source_replica: int | None = None) -> int:
        """Admit a request mid-journey: it finished prefill on ANOTHER
        replica (the prefill tier, docs/SERVING.md "Disaggregated
        tiers") and arrives as the O(1) migration artifact — conv/SSM
        carry + last logits, plus serialized hybrid KV page contents —
        instead of a prompt to prefill.  Queued like any request
        (same validation, same FCFS/priority order); admission routes
        it through the ``state_cache.restore`` path (zero prefill
        compute here, fresh pages allocated and the serialized KV
        scattered in), and the resumed stream is bit-exactly the one
        a local prefill would have produced.  Latency stamps span the
        WHOLE journey: ``snapshot["t_submit"]`` carries the original
        submit time, so the finished record's TTFT/e2e include the
        prefill-tier residency.  Returns the engine-local request id."""
        tracked = self._submit_tracked(request)
        tracked.snapshot = snapshot
        tracked.no_migrate = True  # never bounce back to a prefill tier
        tracked.migration_source = source_replica
        # a PARKED session's artifact additionally carries the tokens
        # already streamed to the client (a migration artifact never
        # does — migration happens before the first token): restore
        # them so the resumed stream CONTINUES — token indices, the
        # max_new_tokens budget and the artifact's ``step`` all line up
        # with the park point instead of replaying from zero
        prior = snapshot.get("new_tokens")
        if prior:
            tracked.new_tokens.extend(int(t) for t in prior)
        # a hot-swapped stream's artifact carries its step re-base (the
        # request arriving here is already the continuation, so future
        # preempt/park stamps keep subtracting it); absent = 0
        tracked.swap_base = int(snapshot.get("swap_base", 0))
        now = time.perf_counter()
        if snapshot.get("t_submit_age_s") is not None:
            # cross-host-safe: reconstruct the original stamps on THIS
            # host's monotonic clock from their ages at packaging (raw
            # perf_counter values don't transport between hosts);
            # t_admit is localized in place so the restore path's
            # existing read consumes it unchanged
            tracked.t_submit = now - snapshot["t_submit_age_s"]
            if snapshot.get("t_admit_age_s") is not None:
                snapshot["t_admit"] = now - snapshot["t_admit_age_s"]
        elif snapshot.get("t_submit") is not None:
            tracked.t_submit = snapshot["t_submit"]
        return tracked.request_id

    # finished streams whose token lists stay replayable for SSE resume
    # (stream_state) after eviction — a small host-side ring
    RECENT_FINISHED_KEEP = 128

    def stream_state(self, request_id: int,
                     from_index: int = 0) -> dict | None:
        """Replay view of one stream for a re-attaching consumer (the
        SSE resume path, docs/SERVING.md "Deploying as a service"):
        ``{"tokens": <emitted[from_index:]>, "done", "finish_reason",
        "request"}`` for an in-flight (resident, queued or preempted)
        request — whose tokens live on its tracker — or a recently
        finished one (the bounded ``RECENT_FINISHED_KEEP`` ring;
        ``request`` is None there).  None for an unknown id.  Pure
        host-side bookkeeping: no device sync, no stream perturbation,
        and the engine keeps generating whether or not anyone
        re-attaches."""
        for t in list(self._slots.values()) + list(self.scheduler):
            if t.request_id == request_id:
                return {
                    "tokens": list(t.new_tokens[from_index:]),
                    "done": False,
                    "finish_reason": None,
                    "request": t.request,
                }
        fin = self._recent_finished.get(request_id)
        if fin is not None:
            toks, reason = fin
            return {
                "tokens": list(toks[from_index:]),
                "done": True,
                "finish_reason": reason,
                "request": None,
            }
        return None

    def withdraw_queued(self) -> list[int]:
        """Pull every queued-but-UNSTARTED request (status QUEUED, no
        resume/migration snapshot) out of the admission queue and
        return their request ids — the drain shutdown path
        (``EngineReplica.drain(requeue=True)``): the router re-places
        withdrawn work on surviving replicas instead of stranding it
        behind a retiring engine's queue.  Requests already holding a
        slot, a preemption snapshot, or a migrated-in artifact are NOT
        withdrawn — their state lives here and finishes here."""
        return [t.request_id for t in self.scheduler.withdraw_unstarted()]

    def _seed_spec(self, tracked: _Tracked, logits) -> None:
        """Seed a freshly-decodable slot's pending queue with the greedy
        argmax of its prefill logits — the exact token the first
        non-speculative tick would emit, and the anchor the drafter
        needs to propose continuations.  The ``np.asarray`` fetch is
        the one extra host sync speculation costs per REQUEST (every
        subsequent next-token comes back inside the tick's own greedy
        fetch).  No-op when speculation is off."""
        if not self.spec:
            return
        tracked.spec_pending = [spec_decode.greedy_token(
            np.asarray(logits).reshape(-1), self.cfg.vocab_size
        )]
        tracked.spec_pending_emitted = 0

    # ------------------------------------------------ multi-tenant LoRA

    def _refresh_lora_params(self) -> None:
        """Re-attach the adapter cache's factor pools to the decode
        params after a pool write (upload/evict — ``AdapterCache.
        version``).  Pure host-side tree surgery plus, on a mesh, a
        device_put that is a no-op for every already-placed base leaf;
        the compiled launches see the pools as ordinary param leaves,
        so one trace serves every resident-adapter mix."""
        if self.adapter_cache.version == self._lora_version:
            return
        p = adapters_mod.attach_adapter_pools(
            self._base_decode_params, self.adapter_cache.pools
        )
        if self.mesh is not None:
            from mamba_distributed_tpu.parallel.sharding import (
                serving_param_shardings,
            )

            p = jax.device_put(p, serving_param_shardings(p, self.mesh))
        self._params = p
        self._lora_version = self.adapter_cache.version

    def adapter_resident(self, name: str) -> bool:
        """Is ``name``'s factor set on this engine's device cache right
        now?  A pure probe — the router's adapter-affinity placement
        term reads it (serving/replica.place_cost)."""
        return (self.lora and self.adapter_cache.resident(name))

    def _ab_resolve(self, request, adapter: str) -> str:
        """Submit-time version pin with A/B routing.

        Identity with ``cfg.lora_ab_fraction >= 1`` (default — the
        plain ``resolve`` pin, bytes unchanged vs PR-15).  Below 1, a
        BARE name on a tenant with >= 2 registered versions routes
        only that fraction of new submits to the latest version; the
        rest pin the PREVIOUS one — the control arm of an online-tune
        deploy.  The arm choice hashes the request's identity (adapter
        base, sampling seed, prompt bytes — crc32, not ``hash()``,
        which is per-process randomized), so a resubmitted request
        lands on the same arm on every replica.  Explicit ``@vN``
        names bypass: a pinned version is an explicit routing decision.
        """
        frac = getattr(self.cfg, "lora_ab_fraction", 1.0)
        base, ver = adapters_mod.split_adapter_version(adapter)
        if frac >= 1.0 or ver is not None:
            return self.adapters.resolve(adapter)
        latest = self.adapters.version_of(base)
        if latest < 2:
            return self.adapters.resolve(adapter)
        prev_key = adapters_mod.versioned_name(base, latest - 1)
        if prev_key not in self.adapters:
            # forward version jump (e.g. a late-joining replica got
            # @v3 but never held v2): no control arm to route to
            return self.adapters.resolve(adapter)
        import zlib

        h = zlib.crc32(
            np.asarray(request.prompt_ids, np.int32).tobytes(),
            zlib.crc32(f"{base}:{request.seed}".encode("utf-8")),
        )
        if (h % 10_000) < int(frac * 10_000):
            return adapters_mod.versioned_name(base, latest)
        return prev_key

    def _adapter_salt(self, request) -> bytes:
        """Prefix-cache key salt for one request's adapter identity —
        carry snapshots depend on the adapter delta that shaped them,
        so a warm hit under adapter X must never seed adapter Y.
        ``b""`` on LoRA-less engines and adapter-less requests: keys
        byte-identical to pre-LoRA."""
        if not self.lora:
            return b""
        return adapters_mod.prefix_salt(getattr(request, "adapter", None))

    def _acquire_adapter_ref(self, tracked: _Tracked) -> bool:
        """Reserve the request's adapter factor slot (the admission
        analogue of the KV page reservation).  True = ready —
        ``tracked.adapter_slot`` holds the pool row (0 = no adapter);
        False = every cache slot is pinned by other resident streams:
        the caller requeues and admission waits, exactly like a short
        page pool — never a mid-flight miss."""
        if not self.lora or not getattr(tracked.request, "adapter", None):
            tracked.adapter_slot = 0
            return True
        if tracked.adapter_slot:  # preempted resume: the ref rode along
            return True
        slot = self.adapter_cache.acquire(tracked.request.adapter)
        if slot is None:
            return False
        tracked.adapter_slot = slot
        self._refresh_lora_params()  # a miss uploaded fresh pool rows
        return True

    def _lora_call_kw(self, tracked: _Tracked) -> dict:
        """The ``adapter_ids=`` kwarg for a batch-1 prefill/chunk
        launch — EMPTY on LoRA-less engines: even an explicit
        ``adapter_ids=None`` would change the jit cache key vs a
        caller that omits it (solo ``generate()``'s chunk driver),
        splitting the one shared chunk trace the parity contract
        leans on.  LoRA engines always pass the (1,) array, row 0
        (the zero factors) for adapter-less requests, so one trace
        serves every adapter mix."""
        if not self.lora:
            return {}
        return {"adapter_ids": jnp.full((1,), tracked.adapter_slot or 0,
                                        jnp.int32)}

    def _release_adapter_ref(self, tracked: _Tracked) -> None:
        """Drop the request's adapter-slot ref (finish, failure,
        migrate-out, failed admission requeue).  Idempotent via the
        ``adapter_slot`` sentinel — the cache itself raises the named
        ``AdapterCacheError`` on a genuine double release."""
        if self.lora and tracked.adapter_slot:
            self.adapter_cache.release(tracked.request.adapter)
        tracked.adapter_slot = None

    def _slot_shard(self, slot: int) -> int:
        """Which data shard holds ``slot``'s pool rows (NamedSharding
        partitions the slot axis contiguously)."""
        return slot * self.num_shards // self.capacity

    def _max_shard_pages(self) -> int:
        """The most KV pages any one shard could EVER have free — the
        upper bound on a single request's reservation (each slot draws
        only from its own shard's range)."""
        return max(self.page_pool.shard_capacity(d)
                   for d in range(self.num_shards))

    def _release_pages(self, slot: int, tracked: _Tracked) -> None:
        """Recycle a slot's KV pages (evict/failure): return them to the
        allocator and point the slot's table row at the trash page so
        nothing it computes can ever touch a recycled page."""
        if not (self.hybrid and tracked.pages):
            return
        self.page_pool.free(tracked.pages)
        self._page_frees += len(tracked.pages)
        tracked.pages = None
        self._page_tbl[slot] = 0
        self._kv_len[slot] = 0

    def _admit(self, tracked: _Tracked) -> bool:
        """Grant the next queued request a slot.  Short pure-SSM prompts
        prefill one-shot right here (PR-1 path); long prompts — and ALL
        hybrid prompts, whose chunk step writes straight into the paged
        KV pool — register a chunk plan and park a zero carry, their
        chunks running in the budget phase (``_advance_prefill``).

        With the prefix cache on, admission first matches the longest
        cached chunk-aligned prefix of the prompt: a FULL hit inserts
        the snapshot's state+logits outright (zero chunk steps — the
        near-zero-TTFT path), a partial hit seeds the carry so prefill
        resumes at the first uncached chunk.  Hybrid hits attach to the
        cached prefix's KV pages copy-on-write (read-only refs on whole
        pages, a fresh device copy of the boundary page the slot will
        append into), confined to the prefix's data shard.

        A preempted request (``tracked.snapshot``) re-admits through
        ``_resume`` instead — host carry restored, no prefill at all.

        Returns False (request back at the queue head, admission stalls)
        when a hybrid request's page reservation doesn't fit the free
        pool yet — evictions recycle pages, never a mid-flight OOM."""
        if tracked.snapshot is not None:
            return self._resume(tracked)
        r = tracked.request
        # per-tenant fairness quota (cfg.tenant_max_slots): a tenant at
        # its concurrent-slot cap WAITS in the queue — the page-stall
        # idiom (requeue + retry next step), named and counted, never
        # shedding.  Resumes bypass this check (they held a slot
        # before; blocking a snapshot-holder could strand its state).
        if self.tenant_max_slots:
            try:
                check_tenant_quota(
                    getattr(r, "adapter", None),
                    (getattr(t.request, "adapter", None)
                     for t in self._slots.values()),
                    self.tenant_max_slots,
                )
            except TenantQuotaExceeded:
                self._quota_stalls += 1
                self.metrics.record_quota_stall()
                self.scheduler.requeue(tracked)
                return False
        # multi-tenant LoRA: reserve the adapter's factor slot FIRST
        # (the page-reservation discipline) — when every cache slot is
        # pinned by other resident streams the request waits in the
        # queue, and finishing streams release slots, so admission can
        # never miss factors mid-flight
        if not self._acquire_adapter_ref(tracked):
            self.scheduler.requeue(tracked)
            return False
        salt = self._adapter_salt(r)
        plan = plan_chunks(len(r.prompt_ids),
                           self.cfg.effective_prefill_chunk_tokens,
                           force=self.hybrid)
        # PEEK: stats/recency/promotion commit only after a slot is
        # secured (commit_lookup below) — a page-stalled request retries
        # this every step and must not drift the cache's counters
        hit = (None if self.prefix_cache is None
               else self.prefix_cache.lookup(r.prompt_ids, plan,
                                             peek=True, salt=salt))
        n_pages = shared_n = fresh_n = 0
        cow = False
        if self.hybrid:
            n_pages = attention_page_count(
                self.cfg, len(r.prompt_ids) + r.max_new_tokens
            )
            if n_pages > self._max_shard_pages():
                # DEADLOCK check: free + in-flight reservations is all a
                # shard can ever hold, so this reservation could never
                # be satisfied by future evictions — waiting would stall
                # the queue forever.  submit() rejects such requests up
                # front; this guards ones fed past it (e.g. straight
                # into the scheduler).  The request is DROPPED, not
                # requeued: requeueing would park the poison request at
                # the queue head and re-raise on every subsequent
                # step(), starving everything behind it.
                raise RuntimeError(
                    f"request {tracked.request_id} needs {n_pages} KV "
                    f"pages but no shard's pool exceeds "
                    f"{self._max_shard_pages()} pages even with every "
                    f"in-flight reservation evicted "
                    f"({self.page_pool.num_pages} usable pages over "
                    f"{self.num_shards} shard(s)) — it can never be "
                    f"admitted and has been dropped from the queue; "
                    f"raise cfg.kv_pool_pages or split the request"
                )
            if hit is not None:
                # a cached prefix's pages live in ONE data shard (pages
                # never cross shards); attaching needs a same-shard slot
                # plus fresh pages for everything this slot will write —
                # whole shared pages stay read-only, and a prefix ending
                # mid-page costs one extra fresh page for the CoW copy
                entry = hit[0]
                page = self.cfg.kv_page_tokens
                shared_n = entry.kv_len // page
                cow = bool(entry.kv_len % page)
                fresh_n = n_pages - shared_n
                slot = next(
                    (s for s in self._free
                     if self._slot_shard(s) == entry.shard
                     and fresh_n <= self.page_pool.free_pages_in(
                         entry.shard)),
                    None,
                )
                if slot is None:
                    hit = None  # serve cold rather than wait on one
                    # shard (commit_lookup below records the miss: the
                    # work gets fully recomputed)
            if hit is None:
                # first free slot whose shard can cover the reservation
                # (a sharded pool confines each slot to its shard's
                # pages; unsharded pools have one shard, preserving FCFS
                # slot order)
                def _fits():
                    return next(
                        (s for s in self._free
                         if n_pages <= self.page_pool.free_pages_in(
                             self._slot_shard(s))),
                        None,
                    )

                slot = _fits()
                if slot is None and self._reclaim_cache_pages(n_pages):
                    slot = _fits()
                if slot is None:
                    # page-stalled: drop the adapter ref too, so a
                    # withdrawn (drained-away) queued request can't
                    # strand a factor slot; the retry re-acquires
                    self._release_adapter_ref(tracked)
                    self.scheduler.requeue(tracked)
                    return False
            self._free.remove(slot)
        else:
            slot = self._free.pop(0)
        tracked.status = RequestStatus.PREFILL
        entry = hit[0] if hit is not None else None
        seeded_chunks = hit[1] if hit is not None else 0
        full_hit = entry is not None and entry.full
        t0 = time.perf_counter()
        try:
            if self.hybrid and entry is not None:
                fresh = self.page_pool.alloc(fresh_n, entry.shard)
                shared = list(entry.kv_pages[:shared_n])
                self.page_pool.incref(shared)
                # the gauges count page REFS acquired/released (incref
                # included) so allocs == frees still closes the loop on
                # cache-sharing engines — _release_pages decrefs every
                # ref this slot holds, shared or fresh
                self._page_allocs += fresh_n + len(shared)
                tracked.pages = shared + fresh
                if cow:
                    # the slot's first KV write targets position kv_len,
                    # inside the prefix's last (partial) page: append
                    # into an owned copy, never the shared original
                    self.pool["state"]["attn_blocks"] = \
                        state_cache.copy_page(
                            self.pool["state"]["attn_blocks"],
                            int(entry.kv_pages[shared_n]), int(fresh[0]),
                        )
                self._page_tbl[slot] = 0
                self._page_tbl[slot, :n_pages] = tracked.pages
                self._kv_len[slot] = entry.kv_len
            if full_hit:
                # the snapshot IS the prefill's output: insert it and
                # decode — zero chunk steps, zero prefill compute (the
                # next tick's fetch is the one sync point, as ever)
                with self.tracer.span("serving_prefill", slot=slot,
                                      request=tracked.request_id,
                                      trace=tracked.trace_id,
                                      cache="full"):
                    self.pool = state_cache.insert(
                        self.pool, slot,
                        {"blocks": entry.state["blocks"]}, entry.logits,
                        r.resolve_key(), r.max_new_tokens, r.top_k,
                        r.temperature,
                        -1 if r.eos_id is None else r.eos_id,
                        adapter_id=tracked.adapter_slot or 0,
                    )
                    self._seed_spec(tracked, entry.logits)
            elif entry is not None:
                # partial hit: seed the cached carry; chunking resumes
                # at the first uncached chunk (the remaining chunks run
                # the identical computation a cold admission would, so
                # the warm stream is bit-identical to cold)
                tracked.plan = plan
                tracked.chunks_done = seeded_chunks
                tracked.prefill_dt = 0.0
                tracked.prefill_seeded_tokens = entry.tokens
                self.pool = state_cache.stash_prefill(
                    self.pool, slot, {"blocks": entry.state["blocks"]},
                    r.resolve_key(), r.max_new_tokens, r.top_k,
                    r.temperature, -1 if r.eos_id is None else r.eos_id,
                    adapter_id=tracked.adapter_slot or 0,
                )
            elif plan is None:
                # one per-request span (trace-stamped) so even a short
                # prompt's journey has an anchor in this replica's
                # stream for the exporter's flow arrows
                with self.tracer.span("serving_prefill", slot=slot,
                                      request=tracked.request_id,
                                      trace=tracked.trace_id):
                    prompt = jnp.asarray(r.prompt_ids, jnp.int32)[None, :]
                    padded, mask = pad_to_bucket(
                        prompt, next_pow2_bucket(prompt.shape[1])
                    )
                    # async dispatch: admitting k queued requests between
                    # ticks queues k prefills+inserts without a host sync
                    # each — the next tick's token fetch is the one
                    # synchronization point
                    logits, state = _prefill(
                        self._params, padded, mask, cfg=self.cfg,
                        mesh=self._tp_mesh,
                        **self._lora_call_kw(tracked),
                    )
                    self.pool = state_cache.insert(
                        self.pool, slot, state, logits, r.resolve_key(),
                        r.max_new_tokens, r.top_k, r.temperature,
                        -1 if r.eos_id is None else r.eos_id,
                        adapter_id=tracked.adapter_slot or 0,
                    )
                    self._seed_spec(tracked, logits)
                    if self.prefix_cache is not None:
                        # snapshot the one-shot prefill's output (state
                        # was NOT donated by insert — safe to retain):
                        # an exact prompt repeat skips _prefill outright
                        self.prefix_cache.maybe_store_full(
                            r.prompt_ids, state, logits, salt=salt
                        )
            else:
                tracked.plan = plan
                tracked.chunks_done = 0
                tracked.prefill_dt = 0.0
                if self.hybrid:
                    tracked.pages = self.page_pool.alloc(
                        n_pages, self._slot_shard(slot)
                    )
                    self._page_allocs += n_pages
                    self._page_tbl[slot] = 0
                    self._page_tbl[slot, :n_pages] = tracked.pages
                    self._kv_len[slot] = 0
                self.pool = state_cache.stash_prefill(
                    self.pool, slot,
                    {"blocks": init_lm_blocks_state(self.cfg, batch=1)},
                    r.resolve_key(), r.max_new_tokens, r.top_k,
                    r.temperature, -1 if r.eos_id is None else r.eos_id,
                    adapter_id=tracked.adapter_slot or 0,
                )
        except Exception:
            # a failed prefill must neither leak the slot (capacity would
            # shrink for the process lifetime) nor drop the request — it
            # goes back to the queue head so a caller catching the raise
            # still sees it in `pending` and can retry or cancel
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            self._free.insert(0, slot)
            self.scheduler.requeue(tracked)
            raise
        if self.prefix_cache is not None:
            # admission went through: commit the lookup outcome — cache
            # lifetime stats/recency/promotion + the engine's window
            # gauges.  AFTER the try block, so a failed (requeued +
            # retried) admission can't double-count, and a shard-
            # dropped hybrid hit commits as the miss it became.
            self.prefix_cache.commit_lookup(r.prompt_ids, plan, hit,
                                            salt=salt)
            kind = None if entry is None else (
                "full" if full_hit else "partial")
            tracked.cache_hit = kind
            if kind is None:
                self._pc_misses += 1
            else:
                self._pc_hits += 1
                self._pc_saved_tokens += entry.tokens
            self.metrics.record_prefix_lookup(
                kind, 0 if entry is None else entry.tokens)
        # dt is host dispatch time (prefill runs async; the next tick's
        # fetch absorbs device completion)
        t_admit = time.perf_counter()
        if plan is None and entry is None:
            self.metrics.record_prefill(int(len(r.prompt_ids)), t_admit - t0)
            # goodput: the one-shot prefill's real tokens vs the padded
            # bucket lanes it computed, attributed to the next tick's
            # window (its dispatch time is already in the stall).  A
            # full-hit admission ran NO prefill lanes, so it counts in
            # neither side — its win shows up as prefix_saved_tokens.
            self._pending_oneshot_real_tokens += int(len(r.prompt_ids))
            self._pending_oneshot_lanes += next_pow2_bucket(
                len(r.prompt_ids)
            )
        # lifecycle stamps: queue-wait is submit -> slot granted; the
        # per-request ITL histogram rides in the finish record so
        # obs_report.py can merge per-token percentiles across requests
        tracked.t_admit = t_admit
        tracked.itl_hist = StreamingHistogram()
        self.metrics.record_queue_wait(t_admit - tracked.t_submit)
        tracked.slot = slot
        self._slots[slot] = tracked
        if full_hit or plan is None:
            tracked.status = RequestStatus.DECODE
        else:
            self._prefill_queue.append(slot)
        return True

    def _advance_prefill(self, slot: int, budget_left: float) -> float:
        """Run ONE chunk of ``slot``'s partial prefill (the budget loop
        round-robins single chunks across concurrent prefills, so the
        caller controls fairness).  Completion flips the slot decodable;
        otherwise the carry is re-stashed.  Returns the remaining
        budget."""
        tracked = self._slots[slot]
        plan, r = tracked.plan, tracked.request
        try:
            state = state_cache.read_state(self.pool, slot)
            if self.hybrid:
                # the chunk step writes THIS slot's pages in the shared
                # pool directly (donated through the call): compose the
                # full carry from the pool pages + the host-owned
                # table row / length
                state["attn_blocks"] = self.pool["state"]["attn_blocks"]
                state["attn_meta"] = (
                    jnp.asarray(self._page_tbl[slot : slot + 1]),
                    jnp.asarray(self._kv_len[slot : slot + 1]),
                )
            i = tracked.chunks_done
            ids, mask = chunk_inputs(r.prompt_ids, plan, i)
            t0 = time.perf_counter()
            with self.tracer.span("serving_prefill_chunk", slot=slot,
                                  chunk=i, of=plan.n_chunks,
                                  trace=tracked.trace_id):
                logits, state = prefill_chunk(
                    self._params, ids, mask, state, cfg=self.cfg,
                    mesh=self._tp_mesh,
                    **self._lora_call_kw(tracked),
                )
                if self.hybrid:
                    # pages were written in place (donated): swap the
                    # fresh buffers into the pool IMMEDIATELY — before
                    # any tracer/metrics host work can raise — so the
                    # except path below never touches donated-away
                    # buffers; advance the host-side length mirror by
                    # this chunk's REAL tokens (the left pad of chunk 0
                    # is never written)
                    self.pool["state"]["attn_blocks"] = state["attn_blocks"]
                    self._kv_len[slot] += plan.real_tokens(i)
            dt = time.perf_counter() - t0  # host dispatch time
            tracked.chunks_done += 1
            tracked.prefill_dt += dt
            budget_left -= plan.chunk
            self.metrics.record_prefill_chunk(plan.chunk, dt)
            # goodput: real (non-pad) chunk tokens are the useful share
            # of this window's prefill lanes
            self._pending_chunk_real_tokens += plan.real_tokens(i)
            state = {"blocks": state["blocks"]}
            # prefix cache: snapshot this boundary's carry (the arrays
            # are chunk-step OUTPUTS — the next grant resumes from the
            # pool via read_state, so nothing ever donates them away).
            # The LAST boundary is stored too: it seeds longer prompts
            # with the same left-pad that extend this one.
            salt = self._adapter_salt(r)
            self._store_prefix(r.prompt_ids, plan, i, state, slot,
                               salt=salt)
            if tracked.chunks_done == plan.n_chunks:
                # ...and the full-prompt entry (state + last logits):
                # an exact repeat skips prefill entirely
                self._store_prefix(r.prompt_ids, plan, i, state, slot,
                                   logits=logits, salt=salt)
                self.pool = state_cache.finish_prefill(
                    self.pool, slot, state, logits
                )
                self._seed_spec(tracked, logits)
                self._prefill_queue.remove(slot)
                tracked.status = RequestStatus.DECODE
                # a partial hit seeded prefill_seeded_tokens of this
                # prompt from the cache — report only the COMPUTED
                # share (the seeded share is already accounted as
                # prefix_saved_tokens; counting it here too would
                # inflate prefill throughput on warm workloads)
                self.metrics.record_prefill(
                    plan.prompt_len - tracked.prefill_seeded_tokens,
                    tracked.prefill_dt,
                )
            else:
                self.pool = state_cache.stash_prefill(
                    self.pool, slot, state, r.resolve_key(),
                    r.max_new_tokens, r.top_k, r.temperature,
                    -1 if r.eos_id is None else r.eos_id,
                    adapter_id=tracked.adapter_slot or 0,
                )
                # rotate to the back: the NEXT chunk grant (this step or
                # the next) goes to the other in-flight prefills first —
                # round-robin across ticks, not just within one pass
                self._prefill_queue.remove(slot)
                self._prefill_queue.append(slot)
        except Exception:
            # mirror the one-shot contract: free the slot (and its KV
            # pages), requeue the request (restarting its prefill from
            # chunk 0), re-raise.  This recovery covers host- and
            # trace-time failures (bad inputs, retrace errors) — the
            # donated buffers are still intact then.  A RUNTIME device
            # failure inside a dispatched step poisons the donated pool
            # buffers (here via the chunk step's state donation, exactly
            # as it would via the tick's own pool donation) — that class
            # has never been recoverable engine-side and surfaces as
            # deleted-array errors on the next use.
            self.pool = state_cache.evict(self.pool, slot)
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            self._prefill_queue.remove(slot)
            del self._slots[slot]
            self._free.insert(0, slot)
            self._free.sort()
            tracked.plan = None
            tracked.chunks_done = 0
            tracked.slot = None
            self.scheduler.requeue(tracked)
            raise
        return budget_left

    # ------------------------------------------------- prefix-state cache

    def _drop_entry_pages(self, entry) -> None:
        """Prefix-cache LRU evict hook: release the entry's pinned KV
        page refs.  A page frees only when no slot still shares it
        (PagePool refcounts) — eviction decrefs, never yanks."""
        if entry.kv_pages:
            self.page_pool.free(list(entry.kv_pages))
            self._page_frees += len(entry.kv_pages)

    def _store_prefix(self, prompt_ids, plan, i: int, state: dict, slot,
                      logits=None, salt: bytes = b"") -> None:
        """Snapshot chunk ``i``'s carry into the prefix cache (with
        ``logits``: the full-prompt entry instead).  Hybrid snapshots
        pin the KV pages covering the prefix (incref — the cache is a
        holder like any slot; its evict hook decrefs).  ``state`` must
        be retainable: batch-1 device arrays no later call donates."""
        pc = self.prefix_cache
        if pc is None:
            return
        if logits is not None:
            key = prefix_cache_mod.full_key(prompt_ids, plan.chunk, salt)
            tokens = plan.prompt_len
        else:
            key = prefix_cache_mod.boundary_key(prompt_ids, plan, i, salt)
            tokens = (i + 1) * plan.chunk - plan.pad
        if not pc.wants(key):
            return
        kv_pages = None
        kv_len = shard = page_bytes = 0
        if self.hybrid:
            kv_len = tokens
            n = -(-kv_len // self.cfg.kv_page_tokens)
            kv_pages = tuple(self._slots[slot].pages[:n])
            self.page_pool.incref(list(kv_pages))
            self._page_allocs += n  # ref acquired (balances the evict
            # hook's decref in the kv_page_allocs/frees gauges)
            shard = self._slot_shard(slot)
            page_bytes = n * self._page_nbytes
        nbytes = (prefix_cache_mod.state_nbytes(state) + page_bytes
                  + (int(logits.nbytes) if logits is not None else 0))
        pc.put(key, prefix_cache_mod.PrefixEntry(
            state=state, tokens=tokens, chunks=i + 1, nbytes=nbytes,
            logits=logits, kv_pages=kv_pages, kv_len=kv_len, shard=shard,
        ))

    def _reclaim_cache_pages(self, n_pages: int) -> bool:
        """Admission pressure valve: the queue head needs KV pages that
        prefix-cache entries are pinning.  Evict page-pinned entries
        LRU-first (their hooks decref; a page actually frees only when
        no slot still shares it) until some free slot's shard covers
        the reservation.  Without this, non-resident holders could
        starve hybrid admission forever — resident slots always finish
        and release pages, cache entries never would.  Returns True
        when the reservation now fits somewhere."""
        pc = self.prefix_cache
        if pc is None or not self._free:
            return False

        shards = {self._slot_shard(s) for s in self._free}

        def satisfied():
            return any(n_pages <= self.page_pool.free_pages_in(d)
                       for d in shards)

        while not satisfied():
            if not pc.evict_one_pinned(shards):
                return False
        return True

    def _resume_parked(self) -> None:
        """Resume queued PREEMPTED requests into remaining free slots
        even though the queue's best request is stalled on KV pages:
        their swap-ins need no new pages (the refs ride on their
        trackers), and running them to completion is the only way the
        pages they pin ever release — without this, a stalled head
        and a page-holding preempted request behind it deadlock each
        other."""
        while self._free:
            parked = self.scheduler.pop_preempted()
            if parked is None or not self._admit(parked):
                return

    def prefix_hit_fraction(self, prompt_ids, adapter=None) -> float:
        """Fraction of ``prompt_ids`` whose prefill this engine's prefix
        cache could skip right now (0.0 with the cache off) — a pure
        probe: no stats bumped, no LRU recency touched.  The router's
        placement cost subtracts it (cache affinity: a warm replica is
        cheaper than an idle cold one for a shared-prefix prompt).
        ``adapter`` keys the probe to the request's LoRA identity —
        snapshots under another adapter are not hits for this one."""
        pc = self.prefix_cache
        if pc is None or len(prompt_ids) == 0:
            return 0.0
        plan = plan_chunks(len(prompt_ids),
                           self.cfg.effective_prefill_chunk_tokens,
                           force=self.hybrid)
        hit = pc.lookup(np.asarray(prompt_ids, np.int32), plan, peek=True,
                        salt=(b"" if not self.lora
                              else adapters_mod.prefix_salt(adapter)))
        if hit is None:
            return 0.0
        return min(1.0, hit[0].tokens / len(prompt_ids))

    # ------------------------------------------------ priority preemption

    def _victim_slot_admits(self, head: _Tracked, victim: _Tracked) -> bool:
        """Would preempting ``victim`` actually let ``head`` admit?  A
        swap-out that can't be followed by the head's admission is pure
        loss — the victim's stream stalls behind a still-stuck head
        (preemption frees a SLOT, never pages: the victim's KV refs
        ride along).  Checks the COLD page reservation (conservative: a
        same-shard cache hit could get by with fewer fresh pages, but
        cold is the guaranteed fallback route), and a preempted head's
        snapshot pins it to its own data shard."""
        if not self.hybrid:
            return True
        shard = self._slot_shard(victim.slot)
        if head.snapshot is not None:
            if head.snapshot.get("migrated"):
                # a migrated-in head brings page CONTENTS, not refs: it
                # re-allocates its full reservation in the freed slot's
                # shard, so that shard's free pages must cover it
                r = head.request
                return attention_page_count(
                    self.cfg, len(r.prompt_ids) + r.max_new_tokens
                ) <= self.page_pool.free_pages_in(shard)
            return shard == head.snapshot.get("shard", shard)
        r = head.request
        n_pages = attention_page_count(
            self.cfg, len(r.prompt_ids) + r.max_new_tokens
        )
        return n_pages <= self.page_pool.free_pages_in(shard)

    def _pick_victim(self):
        """The decoding slot to preempt for the queue's best request:
        lowest priority strictly below the incoming one, restricted to
        victims whose freed slot the head could actually occupy
        (``_victim_slot_admits``); ties prefer the fewest generated
        tokens (least latency already sunk), then the newest request.
        None when nothing is outranked — with uniform priorities
        preemption never triggers."""
        head = self.scheduler.peek()
        if head is None:
            return None
        victims = [t for t in self._slots.values()
                   if t.status is RequestStatus.DECODE
                   and t.priority < head.priority
                   and self._victim_slot_admits(head, t)]
        if not victims:
            return None
        return min(victims, key=lambda t: (t.priority, len(t.new_tokens),
                                           -t.request_id))

    def _preempt(self, tracked: _Tracked) -> None:
        """Swap a decoding slot out to host RAM: copy its carry + last
        logits off-device (the one deliberate sync on this path — a
        swap-out IS a device->host move), keep its KV page refs riding
        on the tracker (hybrid: zero page churn, the pages stay shard-
        pinned for the resume), free the slot, requeue.  ``_resume``
        restores via ``state_cache.restore`` with the token counter
        intact, so the continued stream is bit-exactly the one the
        swap-out interrupted — no re-prefill, no replayed token."""
        slot = tracked.slot
        with self.tracer.span("serving_preempt", slot=slot,
                              request=tracked.request_id,
                              trace=tracked.trace_id):
            state = state_cache.read_state(self.pool, slot)
            snap = {
                "blocks": jax.device_get(state["blocks"]),
                "logits": jax.device_get(self.pool["logits"][slot][None]),
                # device step counter, relative to the CURRENT request
                # (a hot-swapped continuation restarted it at 0 —
                # swap_base re-bases the emitted-token count)
                "step": len(tracked.new_tokens) - tracked.swap_base,
            }
            if self.hybrid:
                snap["kv_len"] = int(self._kv_len[slot])
                snap["shard"] = self._slot_shard(slot)
                self._page_tbl[slot] = 0
                self._kv_len[slot] = 0
            tracked.snapshot = snap
            tracked.preempted += 1
            self._preemptions += 1
            self.metrics.record_preemption()
            self.pool = state_cache.evict(self.pool, slot)
            del self._slots[slot]
            self._free.append(slot)
            self._free.sort()
            self.scheduler.requeue(tracked)

    def _pressure_evict(self, victim: _Tracked) -> None:
        """Free the victim's slot for the queue's best request: PREEMPT
        (carry to host RAM, KV page refs kept — the status quo), or —
        with a session store attached — PARK: the full replica-unbound
        artifact (KV page CONTENTS included) goes to the tiered store,
        the victim's pages recycle immediately, and its requeued
        tracker holds only a tiny session pointer.  Parking is the
        generalized valve: a pressure victim costs zero device pages
        and near-zero host RAM while it waits, instead of pinning a
        snapshot in RAM forever."""
        if self.session_store is None:
            self._preempt(victim)
        else:
            self._park_victim(victim)

    def _park_victim(self, tracked: _Tracked) -> None:
        """Pressure-driven park of a decoding slot: package the full
        migration-format artifact, store it, release slot + pages +
        adapter ref, and requeue the tracker with a session-pointer
        snapshot (``{"migrated", "parked", "session"}``) that
        ``_resume`` hydrates from the store only once a slot is
        actually available.  ``pop_preempted`` skips the pointer (it
        is ``migrated``-flagged — the resume needs a full page
        re-allocation, so it competes through normal admission)."""
        slot = tracked.slot
        with self.tracer.span("serving_park", slot=slot,
                              request=tracked.request_id,
                              trace=tracked.trace_id, pressure=True):
            snap = self._package_migration(slot, tracked)
            snap["parked"] = True
            # no TTL: the queued tracker owns this session's lifetime
            sid = self.session_store.park(
                {"request": None, "snapshot": snap}, ttl_s=0)
            self.pool = state_cache.evict(self.pool, slot)
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            del self._slots[slot]
            self._free.append(slot)
            self._free.sort()
            tracked.snapshot = {"migrated": True, "parked": True,
                                "session": sid}
            tracked.preempted += 1
            self._preemptions += 1
            self.metrics.record_preemption()
            self._session_parks += 1
            self.metrics.record_session_park()
            self.scheduler.requeue(tracked)

    def park(self, request_id: int) -> tuple[GenerationRequest, dict]:
        """Explicitly park a DECODING stream (client idled, or
        ``POST /v1/park``): serialize it into the replica-unbound park
        artifact — the migration artifact plus the tokens already
        emitted — release its slot, KV pages and adapter ref, and DROP
        it from this engine.  Returns ``(request, artifact)``; the
        caller persists the pair (a ``SessionStore``, or the
        controller's over the park RPC) and later resumes it through
        ``submit_migrated`` on ANY replica — the artifact carries page
        contents, never physical ids, so the resumed stream is
        bit-identical to one that never parked.  Raises ``ValueError``
        (retriable) for a stream not in a parkable state: queued or
        mid-prefill streams have no decode carry yet, and a stream
        with in-flight speculative drafts parks on the next tick, once
        the verify launch drains them."""
        tracked = next((t for t in self._slots.values()
                        if t.request_id == request_id), None)
        if tracked is None or tracked.status is not RequestStatus.DECODE:
            raise ValueError(
                f"request {request_id} is not parkable: only a resident "
                f"DECODING stream has the carry the park artifact "
                f"serializes (queued/prefilling streams finish prefill "
                f"first; retry shortly)"
            )
        if self.spec and tracked.spec_pending:
            raise ValueError(
                f"request {request_id} has {len(tracked.spec_pending)} "
                f"speculative draft token(s) in flight; retry after the "
                f"next verify tick drains them"
            )
        slot = tracked.slot
        with self.tracer.span("serving_park", slot=slot,
                              request=tracked.request_id,
                              trace=tracked.trace_id):
            snap = self._package_migration(slot, tracked)
            snap["parked"] = True
            snap["new_tokens"] = [int(t) for t in tracked.new_tokens]
            self.pool = state_cache.evict(self.pool, slot)
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            del self._slots[slot]
            self._free.append(slot)
            self._free.sort()
            if self.spec:
                self.drafter.forget(tracked.request_id)
            if self.session_store is not None:
                self._session_parks += 1
                self.metrics.record_session_park()
        return tracked.request, snap

    def hot_swap_adapter(self, request_id: int,
                         adapter: str | None = None) -> str:
        """Switch a live DECODING stream to another adapter version
        mid-flight — the PR-15 residual online tuning needs: when a
        tenant's ``name@v(N+1)`` deploys, an opted-in stream moves to
        it WITHOUT losing a token.  ``adapter`` pins the target
        (default: the latest version of the stream's current base).

        The recurrent carry was shaped by the OLD factors, so it is
        invalidated — exactly once — by evicting the slot and releasing
        its KV pages + adapter ref; the stream is then requeued as a
        CONTINUATION request whose prompt is the original prompt plus
        every token already emitted, decoding under the new version.
        ``tracked.new_tokens`` (and thus TokenEvent indices, SSE
        replay, and the finish record's token count) continue across
        the swap; ``tracked.orig_request`` preserves what the USER
        submitted for the finish record, and ``tracked.swap_base``
        re-bases the device step counter the continuation restarts
        (preempt/park/migration stamps subtract it).

        Returns the adapter name now in effect (a no-op when already
        there).  Raises retriable ``ValueError`` for streams not in a
        swappable state — queued/prefilling streams have no carry to
        invalidate yet, and in-flight speculative drafts drain on the
        next verify tick first (the ``park`` preconditions)."""
        if not self.lora:
            raise ValueError(
                "hot_swap_adapter needs multi-tenant LoRA serving "
                "(cfg.lora_max_adapters > 0)"
            )
        tracked = next((t for t in self._slots.values()
                        if t.request_id == request_id), None)
        if tracked is None or tracked.status is not RequestStatus.DECODE:
            raise ValueError(
                f"request {request_id} is not swappable: only a "
                f"resident DECODING stream holds the carry a swap "
                f"invalidates (queued/prefilling streams finish "
                f"prefill first; retry shortly)"
            )
        if self.spec and tracked.spec_pending:
            raise ValueError(
                f"request {request_id} has {len(tracked.spec_pending)} "
                f"speculative draft token(s) in flight; retry after "
                f"the next verify tick drains them"
            )
        r = tracked.request
        old = getattr(r, "adapter", None)
        if not old:
            raise ValueError(
                f"request {request_id} decodes the base model — there "
                f"is no adapter to swap"
            )
        new = self.adapters.resolve(
            adapter if adapter is not None else self.adapters.latest(old)
        )
        self.adapters.factors(new)  # UnknownAdapterError before any state change
        if new == old:
            return old
        slot = tracked.slot
        emitted = len(tracked.new_tokens)
        with self.tracer.span("serving_hot_swap", slot=slot,
                              request=tracked.request_id,
                              trace=tracked.trace_id,
                              adapter=new):
            # THE carry invalidation, exactly once: the old-factor
            # state, its KV pages and the old version's factor ref all
            # go — the release keys off tracked.request.adapter, so it
            # runs BEFORE the request mutates to the new version
            self.pool = state_cache.evict(self.pool, slot)
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            del self._slots[slot]
            self._free.append(slot)
            self._free.sort()
            if self.spec:
                # the drafter's observed history pairs with the old
                # stream; the continuation reseeds from its re-prefill
                self.drafter.forget(tracked.request_id)
            if tracked.orig_request is None:
                tracked.orig_request = r
            tracked.request = dataclasses.replace(
                r,
                prompt_ids=np.concatenate([
                    np.asarray(r.prompt_ids, np.int32),
                    np.asarray(tracked.new_tokens[tracked.swap_base:],
                               np.int32),
                ]),
                max_new_tokens=(r.max_new_tokens
                                - (emitted - tracked.swap_base)),
                adapter=new,
            )
            tracked.swap_base = emitted
            tracked.hot_swaps += 1
            self._hot_swaps += 1
            self.metrics.record_hot_swap()
            # requeue re-admits through the normal path: the
            # continuation re-prefills (prefix-warm under the NEW
            # version's salt where possible) and decodes on
            self.scheduler.requeue(tracked)
        return new

    def _resume(self, tracked: _Tracked) -> bool:
        """Re-admit a request from a host snapshot with ``step``
        preserved: a PREEMPTED request back into a free slot — the
        same data shard for hybrids, where its page refs live — or a
        MIGRATED one (``snapshot["migrated"]``, the prefill-tier
        handoff artifact) into any slot whose shard can cover its full
        page reservation: the pages are allocated HERE and the
        serialized KV contents scattered in (``state_cache
        .write_pages``), so the artifact is shard- and replica-
        agnostic.  Returns False (requeued) when no compatible slot is
        free yet."""
        snap = tracked.snapshot
        migrated = bool(snap.get("migrated"))
        # the adapter factor slot first (a preempted request's ref rode
        # its snapshot — instant; a MIGRATED one re-pins from THIS
        # engine's cache, waiting like any admission when all slots
        # are pinned)
        if not self._acquire_adapter_ref(tracked):
            self.scheduler.requeue(tracked)
            return False
        n_pages = 0
        if self.hybrid:
            if migrated:
                r = tracked.request
                n_pages = attention_page_count(
                    self.cfg, len(r.prompt_ids) + r.max_new_tokens
                )
                slot = next(
                    (s for s in self._free
                     if n_pages <= self.page_pool.free_pages_in(
                         self._slot_shard(s))), None)
            else:
                slot = next((s for s in self._free
                             if self._slot_shard(s) == snap["shard"]), None)
        else:
            slot = self._free[0] if self._free else None
        if slot is None:
            self.scheduler.requeue(tracked)
            return False
        self._free.remove(slot)
        t0 = time.perf_counter()
        if "session" in snap:
            # pressure-parked: hydrate the full artifact from the
            # tiered store only now that a slot is actually free (an
            # eager hydrate on a tracker that then failed admission
            # would haul the artifact back into host RAM for nothing)
            try:
                snap = self.session_store.resume(snap["session"])["snapshot"]
                tracked.snapshot = snap
            except (KeyError, SessionStoreError):
                # the parked artifact is gone (store restarted without
                # its state dir, or the frame failed its CRC): this
                # stream cannot continue — drop it finished-with-error
                # instead of crashing the admission loop (the
                # named-error/skip contract), its emitted tokens still
                # replayable from the recent-finished ring
                self._release_adapter_ref(tracked)
                self._free.insert(0, slot)
                self._free.sort()
                tracked.snapshot = None
                self._recent_finished[tracked.request_id] = (
                    list(tracked.new_tokens), "session_lost")
                while (len(self._recent_finished)
                       > self.RECENT_FINISHED_KEEP):
                    self._recent_finished.pop(
                        next(iter(self._recent_finished)))
                return True
        parked = bool(snap.get("parked"))
        r = tracked.request
        try:
            with self.tracer.span("serving_resume", slot=slot,
                                  request=tracked.request_id,
                                  trace=tracked.trace_id,
                                  **({"migrated": True} if migrated
                                     else {})):
                if self.hybrid and migrated:
                    tracked.pages = self.page_pool.alloc(
                        n_pages, self._slot_shard(slot)
                    )
                    self._page_allocs += n_pages
                    n_live = snap["n_live"]
                    if n_live:
                        # dst ids padded to the artifact's pow2 page
                        # bucket with the trash page (whose contents
                        # are garbage by contract), so one scatter
                        # trace covers every page count
                        bucket = jax.tree.leaves(
                            snap["kv_data"])[0].shape[1]
                        dst = np.zeros((bucket,), np.int32)
                        dst[:n_live] = tracked.pages[:n_live]
                        self.pool["state"]["attn_blocks"] = \
                            state_cache.write_pages(
                                self.pool["state"]["attn_blocks"],
                                jax.tree.map(jnp.asarray,
                                             snap["kv_data"]),
                                jnp.asarray(dst),
                            )
                self.pool = state_cache.restore(
                    self.pool, slot,
                    {"blocks": jax.tree.map(jnp.asarray, snap["blocks"])},
                    jnp.asarray(snap["logits"]), r.resolve_key(),
                    snap["step"], r.max_new_tokens, r.top_k,
                    r.temperature, -1 if r.eos_id is None else r.eos_id,
                    adapter_id=tracked.adapter_slot or 0,
                )
                if self.hybrid:
                    self._page_tbl[slot] = 0
                    self._page_tbl[slot, :len(tracked.pages)] = tracked.pages
                    self._kv_len[slot] = snap["kv_len"]
        except Exception:
            # slot back, request back — the snapshot survives requeue,
            # so a retry restores instead of re-prefilling (a re-prefill
            # would replay tokens the consumer already has).  Pages a
            # MIGRATED restore allocated here are returned (its data
            # lives on in the snapshot; a retry re-allocates).
            if migrated and tracked.pages:
                self.page_pool.free(tracked.pages)
                self._page_frees += len(tracked.pages)
                tracked.pages = None
                self._page_tbl[slot] = 0
                self._kv_len[slot] = 0
            self._free.insert(0, slot)
            self._free.sort()
            self.scheduler.requeue(tracked)
            raise
        if self.spec and not tracked.spec_pending:
            # a MIGRATED-in request arrives with a fresh tracker: derive
            # its first pending token from the artifact's logits — the
            # same bits the source engine's seed would have used, so the
            # resumed stream matches a never-migrated one exactly.  A
            # locally-preempted request keeps its surviving pending.
            self._seed_spec(tracked, snap["logits"])
        tracked.snapshot = None
        tracked.slot = slot
        tracked.status = RequestStatus.DECODE
        self._slots[slot] = tracked
        if migrated and tracked.itl_hist is None:
            # a migrated-in tracker is FRESH on this scheduler and
            # skipped _admit's lifecycle stamping: the admission stamp
            # travels in the artifact (queue-wait was recorded once,
            # on the prefill replica — re-recording here would double-
            # count it in the histogram) and the per-request ITL
            # histogram starts empty (no token has streamed yet)
            tracked.t_admit = snap.get("t_admit") or time.perf_counter()
            tracked.itl_hist = StreamingHistogram()
        if migrated and not parked:
            # handoff latency = source-side packaging + this restore's
            # host dispatch (the router's serving_migrate span covers
            # the placement hop between them)
            dt_ms = (snap.get("package_ms", 0.0)
                     + (time.perf_counter() - t0) * 1000)
            tracked.migrations += 1
            tracked.migration_ms += dt_ms
            self._migrations_in += 1
            self.metrics.record_migration_in(dt_ms)
        elif parked and self.session_store is not None:
            # a parked resume is NOT a tier migration (the counters
            # stay clean); it lands in the sessions resume-latency
            # histogram instead — store hydrate + restore dispatch
            self._session_resumes += 1
            self.metrics.record_session_resume(
                (time.perf_counter() - t0) * 1000)
        return True

    # ------------------------------------- disaggregated tier migration

    def _package_migration(self, slot: int, tracked: _Tracked) -> dict:
        """Serialize a prefill-complete slot into the migration
        artifact: the same preempt-style host snapshot
        ``state_cache.restore`` consumes (O(1) conv/SSM carry + last
        logits + the token counter, here 0) plus — hybrids — the live
        KV pages' contents read out of the page pool
        (``state_cache.read_pages``, pow2-bucketed page count so one
        gather trace covers every prompt length).  The ``device_get``
        is the one deliberate sync on this path: a migration IS a
        device->host->device move, and Mamba makes it O(1) in the
        sequence length (plus O(prompt) KV pages only for hybrid
        stacks)."""
        t0 = time.perf_counter()
        state = state_cache.read_state(self.pool, slot)
        snap = {
            "migrated": True,
            "blocks": jax.device_get(state["blocks"]),
            "logits": jax.device_get(self.pool["logits"][slot][None]),
            # relative to the CURRENT request: a hot-swapped stream's
            # continuation restarted the device counter at 0, and the
            # receiver restores against the continuation's budget
            "step": len(tracked.new_tokens) - tracked.swap_base,
            # only swapped streams stamp the re-base (artifacts from
            # never-swapped streams stay byte-identical to PR-19's)
            **({"swap_base": tracked.swap_base}
               if tracked.swap_base else {}),
            "t_submit": tracked.t_submit,
            "t_admit": tracked.t_admit,
            # clock-transportable journey stamps: raw perf_counter
            # values are meaningless on another HOST (each machine has
            # its own monotonic epoch), so the artifact also carries
            # AGES at packaging time — the receiver reconstructs
            # equivalent local stamps, keeping queue-wait/TTFT/e2e
            # correct across genuine host boundaries (the wire transit
            # itself lands in the journey, as it should)
            "t_submit_age_s": t0 - tracked.t_submit,
            "t_admit_age_s": (None if tracked.t_admit is None
                              else t0 - tracked.t_admit),
        }
        if self.hybrid:
            kv_len = int(self._kv_len[slot])
            n_live = -(-kv_len // self.cfg.kv_page_tokens) if kv_len else 0
            bucket = next_pow2_bucket(max(n_live, 1), min_bucket=1)
            ids = np.zeros((bucket,), np.int32)  # pad -> trash page 0
            ids[:n_live] = tracked.pages[:n_live]
            snap["kv_data"] = jax.device_get(state_cache.read_pages(
                self.pool["state"]["attn_blocks"], jnp.asarray(ids)
            ))
            snap["kv_len"] = kv_len
            snap["n_live"] = n_live
        snap["package_ms"] = (time.perf_counter() - t0) * 1000
        return snap

    def _migrate_ready(self) -> None:
        """Prefill-tier handoff (``migrate_hook`` engines only): offer
        every prefill-complete slot — DECODE status, zero tokens
        emitted, so chunked, one-shot and full-cache-hit prefills all
        qualify — to the hook BEFORE it ever decodes here.  The hook
        (serving/router._migrate_from) re-places the packaged artifact
        on a decode-tier replica and returns True: this engine then
        frees the slot and drops its page refs (the artifact carries
        page CONTENTS, so the physical pages recycle immediately).
        False = no decode capacity right now: the slot decodes HERE
        (mixed-mode fallback) and is marked ``no_migrate`` so it is
        offered exactly once — graceful degradation, never a stall."""
        for slot in [s for s, t in self._slots.items()
                     if t.status is RequestStatus.DECODE
                     and not t.new_tokens and not t.no_migrate]:
            tracked = self._slots[slot]
            if self.migrate_hook(
                tracked,
                lambda s=slot, t=tracked: self._package_migration(s, t),
            ):
                self.pool = state_cache.evict(self.pool, slot)
                self._release_pages(slot, tracked)
                self._release_adapter_ref(tracked)
                del self._slots[slot]
                self._free.append(slot)
                self._free.sort()
                if self.spec:
                    # the target engine reseeds from the artifact's
                    # logits and restarts its own drafter stream
                    self.drafter.forget(tracked.request_id)
                self._migrations_out += 1
                self.metrics.record_migration_out()
            else:
                tracked.no_migrate = True

    # chunk grants a slot can be passed over in a row before it outranks
    # SRPT's shortest-remaining rule (the starvation guard)
    SRPT_STARVATION_GRANTS = 4

    def _pick_prefill_slot(self) -> int:
        """Which in-flight partial prefill gets the next chunk grant.

        ``cfg.prefill_schedule == "rr"`` takes the rotation head —
        ``_advance_prefill`` moves a still-partial slot to the back, so
        repeatedly granting the head IS the round-robin PR 4 pinned.
        ``"srpt"`` grants the slot with the fewest REMAINING chunks
        (shortest-remaining-processing-time: a nearly-done prompt
        reaches its first token before a fresh long one begins, which
        minimizes mean TTFT across concurrent prefills), except that a
        slot passed over ``SRPT_STARVATION_GRANTS`` times in a row gets
        the grant regardless — a stream of short arrivals can't starve
        a long prompt indefinitely.  Ties break toward the prefill
        queue head (rotation order: a granted-but-partial slot moves to
        the back, so among tied slots the one granted least recently
        wins)."""
        queue = self._prefill_queue
        if self.cfg.prefill_schedule != "srpt" or len(queue) == 1:
            self._slots[queue[0]].prefill_skipped = 0  # a grant is a grant
            return queue[0]
        starved = [s for s in queue
                   if (self._slots[s].prefill_skipped
                       >= self.SRPT_STARVATION_GRANTS)]
        if starved:
            pick = starved[0]
        else:
            pick = min(queue, key=lambda s: (
                self._slots[s].plan.n_chunks - self._slots[s].chunks_done
            ))
        for s in queue:
            if s != pick:
                self._slots[s].prefill_skipped += 1
        self._slots[pick].prefill_skipped = 0
        return pick

    def _prefill_phase(self) -> tuple[float, int]:
        """Between-ticks prefill work: admit what fits, then spend the
        chunk budget one grant at a time across in-flight partial
        prefills — ``_pick_prefill_slot`` chooses each grant (rotation
        under ``cfg.prefill_schedule="rr"``, shortest-remaining-first
        with a starvation guard under ``"srpt"``) — so a second long
        prompt makes progress instead of waiting for the first to
        drain (FCFS head-of-line blocking on TTFT).  At least one
        chunk runs per step even when the budget is smaller than a
        chunk, so progress is guaranteed.

        Priority pressure valve: when the queue's best request outranks
        a resident decoding slot and no slot is free, the lowest-
        priority victim is PREEMPTED (carry swapped to host, slot
        freed, resumed later without re-prefill) so the high-priority
        request admits this step instead of queueing behind it.
        Returns (host seconds spent — the tick's ``prefill_stall`` —
        and chunk tokens dispatched)."""
        # one victim scan serves both the gate and the loop's first
        # iteration (peek + slot scan per engine step adds up)
        next_victim = (self._pick_victim()
                       if self.scheduler.depth and not self._free else None)
        if not ((self._free and self.scheduler.depth) or self._prefill_queue
                or next_victim is not None):
            return 0.0, 0
        t0 = time.perf_counter()
        chunk_tokens0 = self.metrics.prefill_chunk_tokens
        chunk_s0 = self.metrics.prefill_chunk_time_s
        if self.scheduler.depth and (self._free or next_victim is not None):
            with self.tracer.span("serving_admit",
                                  queued=self.scheduler.depth):
                while self.scheduler.depth:
                    if not self._free:
                        victim = next_victim or self._pick_victim()
                        next_victim = None
                        if victim is None:
                            break
                        self._pressure_evict(victim)
                    if not self._admit(self.scheduler.pop()):
                        # the head stalled on KV pages or a shard-pinned
                        # slot.  A suitable victim may still unblock it
                        # — a free slot in the WRONG shard suppressed
                        # the gate above (_victim_slot_admits guarantees
                        # the retry admits) — else resume parked
                        # preempted requests: their swap-ins need no
                        # pages and eventually release the pages the
                        # head is waiting on.
                        victim = self._pick_victim()
                        if victim is not None:
                            self._pressure_evict(victim)
                            continue
                        self._resume_parked()
                        break
        budget = self.prefill_tokens_per_tick
        left = float("inf") if budget == 0 else float(budget)
        if self.spec and budget:
            # verify ticks consume token lanes of interleaving budget
            # too (the previous tick computed live * (K+1) chunk-width
            # lanes): debit them so speculation on + chunked prefill
            # never exceeds the per-step work bound the knob promises.
            # The >=1-chunk progress guarantee below still holds.
            left = max(0.0, left - self._spec_budget_debt)
            self._spec_budget_debt = 0
        chunks_run = 0
        while self._prefill_queue and (left > 0 or chunks_run == 0):
            left = self._advance_prefill(self._pick_prefill_slot(), left)
            chunks_run += 1
        self._pending_chunk_ms += (
            self.metrics.prefill_chunk_time_s - chunk_s0
        ) * 1000
        return (time.perf_counter() - t0,
                self.metrics.prefill_chunk_tokens - chunk_tokens0)

    # ------------------------------------------------------------- decoding

    @property
    def pending(self) -> int:
        """Requests not yet finished (queued + in-flight)."""
        return self.scheduler.depth + len(self._slots)

    # --------------------------------------------------- compacted ticks

    def _compaction_width(self, live_slots) -> int | None:
        """Lane width of this tick's compacted launch, or None for the
        plain full-width tick (compaction off, or the bucket would not
        be narrower than capacity).  The bucket is a pow2 over the
        BUSIEST data shard's live count — every shard gets the same
        lane count so the compact tree tiles over the data axis exactly
        like the full pool — grown immediately, shrunk only after
        ``cfg.compaction_hysteresis_ticks`` consecutive ticks that
        would have fit the smaller bucket."""
        if not self.compaction:
            return None
        per = self.capacity // self.num_shards
        by_shard = [0] * self.num_shards
        for s in live_slots:
            by_shard[self._slot_shard(s)] += 1
        need = next_pow2_bucket(max(1, max(by_shard)), min_bucket=1)
        b = self._compact_bucket
        if need > b:
            b = need
            self._shrink_streak = 0
        elif need < b:
            self._shrink_streak += 1
            if self._shrink_streak >= self.cfg.compaction_hysteresis_ticks:
                b = need
                self._shrink_streak = 0
        else:
            self._shrink_streak = 0
        self._compact_bucket = b
        if b >= per:
            return None  # full width: the existing tick IS the launch
        return b * self.num_shards

    def _compact_maps(self, live_slots, width: int):
        """Host-side lane maps for one compacted launch: ``idx`` (W,)
        gathers lane j from slot idx[j] (pad lanes repeat their shard's
        first slot — garbage lanes the scatter never reads), ``inv``/
        ``touched`` (S,) scatter lane inv[s] back into live slot s, and
        ``lanes`` maps slot -> lane for the host-side token plumbing.
        Shard d's live slots land in lanes [d*b, d*b + n_d): the gather
        is shard-local, so the mesh-sharded pool's tiling survives
        compaction."""
        b = width // self.num_shards
        per = self.capacity // self.num_shards
        idx = np.zeros((width,), np.int32)
        inv = np.zeros((self.capacity,), np.int32)
        touched = np.zeros((self.capacity,), bool)
        lanes: dict[int, int] = {}
        fill = [d * b for d in range(self.num_shards)]
        for d in range(self.num_shards):
            idx[d * b : (d + 1) * b] = d * per  # pad default, in-shard
        for s in sorted(live_slots):
            d = self._slot_shard(s)
            lane = fill[d]
            fill[d] += 1
            idx[lane] = s
            inv[s] = lane
            touched[s] = True
            lanes[s] = lane
        return idx, inv, touched, lanes

    def _compact_rows(self):
        """The full pool's per-slot subtrees, as gather/scatter see
        them (``attn_blocks`` — the shared page pool — has no slot axis
        and rides the tick's own donation instead)."""
        return {
            "blocks": self.pool["state"]["blocks"],
            "logits": self.pool["logits"],
            "meta": self.pool["meta"],
        }

    def _compact_page_meta(self, idx, lanes, spare: bool):
        """Compacted page table + lengths for a hybrid launch: the live
        slots' rows in lane order, pad lanes pointing at the trash page
        with length 0.  The page-count bucket is the pow2 of the
        largest LIVE allocation (+1 spare trash column in spec mode,
        exactly like the full-width tick), so attention reads scale
        with what the compacted lanes actually hold."""
        largest = max(
            (len(self._slots[s].pages) for s in lanes
             if self._slots[s].pages),
            default=1,
        )
        bucket = min(
            next_pow2_bucket(largest + (1 if spare else 0), min_bucket=1),
            self._page_tbl.shape[1],
        )
        ctbl = self._page_tbl[idx, :bucket].copy()
        clen = self._kv_len[idx].copy()
        pad = np.ones((len(idx),), bool)
        pad[list(lanes.values())] = False
        ctbl[pad] = 0
        clen[pad] = 0
        return ctbl, clen

    def _scatter_pool(self, new_cpool_state, compact_out, inv, touched):
        """Reassemble ``self.pool`` from a compacted launch's output:
        scatter the per-slot lanes back (donating the old full-width
        rows) and carry the page pool forward from the launch's own
        donation."""
        res = state_cache.scatter_slots(
            self._compact_rows(), compact_out,
            jnp.asarray(inv), jnp.asarray(touched), mesh=self.mesh,
        )
        state = {"blocks": res["blocks"]}
        if self.hybrid:
            state["attn_blocks"] = new_cpool_state["attn_blocks"]
        self.pool = {"state": state, "logits": res["logits"],
                     "meta": res["meta"]}

    def _pipeline_micro(self, width: int | None) -> int | None:
        """Microbatch count for the explicit GPipe decode schedule, or
        None for the GSPMD layer scan.

        The explicit clock (parallel/pipeline.pipelined_decode_layers)
        engages only where it is defined and profitable: a 3-D mesh
        with ``stage > 1`` whose other axes are size 1, a pure-SSM
        stack (hybrid attention needs the paged-KV metadata plumbing
        the schedule doesn't thread), no multi-tenant LoRA (bound
        factor pools carry a per-slot axis the schedule doesn't
        slice), and the non-speculative tick (spec_verify launches are
        chunk-shaped, not lane-shaped).  Everywhere else the
        stage-sharded layer axis still partitions residency and GSPMD
        executes the sequential scan — the bitwise-identical fallback.

        ``n_micro = stage_shards`` when the launch width tiles over
        the stages (the pow2 compaction buckets make this the common
        case), else 1 (a sequential flush — still one trace per
        bucket, so TRACE_COUNTS stay flat across repeated ticks)."""
        if (self.stage_shards <= 1 or self.hybrid or self.spec
                or self.lora or self.model_shards > 1
                or self.num_shards > 1):
            return None
        w = self.capacity if width is None else width
        return (self.stage_shards if w % self.stage_shards == 0
                else 1)

    def _compact_tick(self, live_slots, width: int, n_micro=None):
        """One COMPACTED decode tick: gather the live slots' rows into
        ``width`` lanes, run the identical ``_tick`` jit at lane width
        (one trace per pow2 bucket), scatter the advanced rows back,
        and expand the token matrices to slot indexing for the shared
        event plumbing.  Pad lanes repeat an in-shard slot's rows and
        compute garbage — their hybrid KV writes land on the trash page
        (their compacted table rows are zeroed) and nothing ever reads
        them back.  Per-row math is the full tick's, so streams are
        bit-identical to the uncompacted engine (tests/
        test_tick_compaction.py)."""
        idx, inv, touched, lanes = self._compact_maps(live_slots, width)
        gathered = state_cache.gather_slots(
            self._compact_rows(), jnp.asarray(idx), mesh=self.mesh,
        )
        cpool = {"state": {"blocks": gathered["blocks"]},
                 "logits": gathered["logits"], "meta": gathered["meta"]}
        tick_kv = ()
        if self.hybrid:
            # the shared page pool has no slot axis: it rides the
            # tick's donation exactly as in the full-width launch
            cpool["state"]["attn_blocks"] = \
                self.pool["state"]["attn_blocks"]
            ctbl, clen = self._compact_page_meta(idx, lanes, spare=False)
            tick_kv = (jnp.asarray(ctbl), jnp.asarray(clen))
        new_cpool, tokens, emitted, done = _tick(
            self._params, cpool, *tick_kv, cfg=self.cfg,
            k_max=self.max_top_k, steps=self.tokens_per_tick,
            mesh=self.mesh, n_micro=n_micro,
        )
        self._scatter_pool(
            new_cpool["state"],
            {"blocks": new_cpool["state"]["blocks"],
             "logits": new_cpool["logits"], "meta": new_cpool["meta"]},
            inv, touched,
        )
        tokens = np.asarray(tokens)  # (steps, width) — the host sync
        emitted = np.asarray(emitted)
        done = np.asarray(done)
        steps = tokens.shape[0]
        cols = np.fromiter(lanes.keys(), np.int64, len(lanes))
        ls = np.fromiter(lanes.values(), np.int64, len(lanes))
        tokens_f = np.zeros((steps, self.capacity), tokens.dtype)
        emitted_f = np.zeros((steps, self.capacity), bool)
        done_f = np.zeros((steps, self.capacity), bool)
        tokens_f[:, cols] = tokens[:, ls]
        emitted_f[:, cols] = emitted[:, ls]
        done_f[:, cols] = done[:, ls]
        if self.hybrid:
            # the device-side lengths advance, mirrored at full width
            self._kv_len += emitted_f.sum(axis=0).astype(np.int32)
        return tokens_f, emitted_f, done_f

    def _spec_tick(self, width: int | None = None):
        """One speculative draft-verify tick (serving/spec_decode.py).

        ``width`` (from ``_compaction_width``) compacts the launch to
        the live lanes: the feed/verify/commit all run at lane width
        and the committed lanes scatter back — the same per-row math at
        a narrower batch, so the compacted spec stream is bit-identical
        to the full-width one (and to plain greedy).

        Per live slot: compose the feed (its pending committed tokens +
        up to K drafter proposals, zero-filled to the static width W),
        run ONE ``spec_verify`` launch over the whole pool, fetch the
        (S, W) greedy matrix — the tick's one host sync — and decide
        per slot: a full verification commits the launch's carries and
        final logits outright (the state advanced W tokens) plus one
        bonus token from the final position's argmax; any rejection
        rolls the slot back to its pre-tick carries (``spec_commit``'s
        per-row select) and banks the accepted prefix + the model's
        correction token as the next tick's trusted feed — every launch
        commits >= 1 token per live slot.  Mid-prefill/empty/done slots
        are masked (their KV writes flush to trash, their garbage
        carries are discarded by the rollback select), exactly like the
        non-speculative tick's ``write_mask``.

        Returns ``(tokens, emitted, done)`` shaped (W+1, S) — the same
        matrices the compiled tick yields, so ``step()``'s event/
        latency/finish plumbing is shared verbatim."""
        W = self.spec_width
        S = self.capacity
        live = {s: t for s, t in self._slots.items()
                if t.status is RequestStatus.DECODE}
        compacted = width is not None
        if compacted:
            idx, inv, touched, lanes = self._compact_maps(
                list(live), width
            )
            n_lanes = width
        else:
            lanes = {s: s for s in live}
            n_lanes = S
        ids = np.zeros((n_lanes, W), np.int32)
        tmask = np.zeros((n_lanes, W), np.float32)
        trusted: dict[int, int] = {}
        for slot, tr in live.items():
            rid = tr.request_id
            if tr.spec_observed == 0:
                # fresh (or restarted-after-requeue) stream: drop any
                # stale drafter state before re-observing from scratch
                self.drafter.forget(rid)
            # committed history the drafter must know is prompt +
            # emitted + the still-unemitted pending (fresh tok0);
            # spec_observed counts how much of that concatenation the
            # drafter has seen, so only the SUFFIX is materialized —
            # never the whole history (O(new tokens) per tick, not
            # O(prompt + stream))
            pend = tr.spec_pending[tr.spec_pending_emitted:]
            plen = len(tr.request.prompt_ids)
            total = plen + len(tr.new_tokens) + len(pend)
            if total > tr.spec_observed:
                k = tr.spec_observed - plen
                if k < 0:
                    delta = (tr.request.prompt_ids[k:].tolist()
                             + tr.new_tokens + pend)
                elif k <= len(tr.new_tokens):
                    delta = tr.new_tokens[k:] + pend
                else:
                    delta = pend[k - len(tr.new_tokens):]
                self.drafter.observe(rid, delta)
                tr.spec_observed = total
            n = W - len(tr.spec_pending)
            drafts = (list(self.drafter.draft(rid, n))[:n] if n > 0
                      else [])
            self._spec_drafted += n
            ids[lanes[slot]] = spec_decode.build_feed(
                tr.spec_pending, drafts, W
            )
            tmask[lanes[slot]] = 1.0
            trusted[slot] = len(tr.spec_pending)
        if compacted:
            gathered = state_cache.gather_slots(
                self._compact_rows(), jnp.asarray(idx), mesh=self.mesh,
            )
            state_in = {"blocks": gathered["blocks"]}
            logits_in, meta_in = gathered["logits"], gathered["meta"]
            if self.hybrid:
                state_in["attn_blocks"] = \
                    self.pool["state"]["attn_blocks"]
                ctbl, clen = self._compact_page_meta(idx, lanes,
                                                     spare=True)
                state_in["attn_meta"] = (jnp.asarray(ctbl),
                                         jnp.asarray(clen))
        else:
            state_in = dict(self.pool["state"])
            logits_in, meta_in = self.pool["logits"], self.pool["meta"]
            if self.hybrid:
                # +1 past the largest allocation so a fully-reserved
                # slot's overshoot writes clamp onto a zero (trash)
                # table entry — the table rows carry a permanent spare
                # column for exactly this (see __init__)
                largest = max(
                    (len(t.pages) for t in self._slots.values()
                     if t.pages),
                    default=1,
                )
                bucket = min(next_pow2_bucket(largest + 1, min_bucket=1),
                             self._page_tbl.shape[1])
                state_in["attn_meta"] = (
                    jnp.asarray(self._page_tbl[:, :bucket]),
                    jnp.asarray(self._kv_len),
                )
        greedy_d, final_logits, new_state, old = spec_decode.spec_verify(
            self._params, state_in, jnp.asarray(ids), jnp.asarray(tmask),
            cfg=self.cfg, mesh=self._tp_mesh,
            **({"adapter_ids": meta_in["adapter_id"]} if self.lora
               else {}),
        )
        greedy = np.asarray(greedy_d)  # (lanes, W) — the host sync point
        tokens = np.zeros((W + 1, S), np.int32)
        emitted = np.zeros((W + 1, S), bool)
        done = np.zeros((W + 1, S), bool)
        advance = np.zeros((n_lanes,), bool)
        for slot, tr in live.items():
            nt = trusted[slot]
            fed = ids[lanes[slot]].tolist()
            a, adv, nxt = spec_decode.verify_greedy(
                fed, greedy[lanes[slot]], nt
            )
            self._spec_accepted += a
            pending = tr.spec_pending
            stream = (pending[tr.spec_pending_emitted:]
                      + fed[nt:nt + a] + [nxt])
            r = tr.request
            emitted_now: list[int] = []
            finished = False
            for tok in stream:
                emitted_now.append(tok)
                # the same finish rule the compiled tick applies: the
                # eos/budget token itself is emitted, nothing after it
                if r.eos_id is not None and tok == r.eos_id:
                    finished = True
                    break
                if (len(tr.new_tokens) + len(emitted_now)
                        >= r.max_new_tokens):
                    finished = True
                    break
            for j, tok in enumerate(emitted_now):
                tokens[j, slot] = tok
                emitted[j, slot] = True
            if finished:
                done[len(emitted_now) - 1, slot] = True
            elif adv:
                advance[lanes[slot]] = True
                tr.spec_pending = [nxt]
                tr.spec_pending_emitted = 1
            else:
                tr.spec_pending = pending + fed[nt:nt + a] + [nxt]
                tr.spec_pending_emitted = len(tr.spec_pending)
        # next step's chunk budget pays for this tick's verify lanes —
        # the lanes actually COMPUTED: the compacted bucket width when
        # compaction narrowed the launch, the live count otherwise
        self._spec_budget_debt = (width if compacted else len(live)) * W
        self._spec_streams += len(live)
        new_state = {k: v for k, v in new_state.items()
                     if k != "attn_meta"}
        committed = spec_decode.spec_commit(
            new_state, old["blocks"], logits_in, meta_in, final_logits,
            jnp.asarray(advance), jnp.int32(W),
        )
        if compacted:
            self._scatter_pool(
                committed["state"],
                {"blocks": committed["state"]["blocks"],
                 "logits": committed["logits"],
                 "meta": committed["meta"]},
                inv, touched,
            )
        else:
            self.pool = committed
        if self.hybrid:
            # lengths advance by the full chunk width on accepted rows
            # only; rejected rows' freshly written cells stay dead-by-
            # lengths and the next verify overwrites them
            adv_full = np.zeros((S,), bool)
            for slot, lane in lanes.items():
                adv_full[slot] = advance[lane]
            self._kv_len += (W * adv_full).astype(np.int32)
        return tokens, emitted, done

    def step(self) -> list[TokenEvent]:
        """One engine iteration: prefill phase (admissions + chunk
        budget), then one compiled tick, streaming its tokens.

        Returns the tick's TokenEvents in emission order (empty while
        only partial prefills are resident); finished requests are
        evicted and their GenerationResults recorded in ``self.results``.
        """
        stall_s, chunk_tokens = self._prefill_phase()
        if stall_s:
            self.metrics.record_prefill_stall(stall_s)
        self._pending_stall_ms += stall_s * 1000
        self._pending_chunk_tokens += chunk_tokens
        if self.migrate_hook is not None:
            # prefill-tier handoff BEFORE the tick: a slot that just
            # finished prefill migrates out without decoding a single
            # token here (zero replayed tokens by construction)
            self._migrate_ready()
        if not any(t.status is RequestStatus.DECODE
                   for t in self._slots.values()):
            # nothing decodable yet (empty engine, or every resident slot
            # still mid-prefill): no tick this step — the loop keeps
            # granting chunk budget until a slot turns decodable
            return []
        occupied = len(self._slots)
        live_slots = [s for s, t in self._slots.items()
                      if t.status is RequestStatus.DECODE]
        # occupancy-adaptive compaction: the lane width this tick's
        # launch actually computes (None => the full-width status quo).
        # Mid-prefill residents compact OUT of the launch entirely —
        # their parked carries are simply never gathered — so the tick
        # is priced by decodable slots, not residency.
        width = self._compaction_width(live_slots)
        # explicit GPipe microbatch count for this tick's launch (None
        # => the GSPMD layer scan; _pipeline_micro documents the gate)
        # and the schedule's honest bubble bill: the warmup/drain ramp
        # idles (stage_shards - 1) stage-ticks per lm_step call, worth
        # (stage_shards - 1) * microbatch_width full-depth lane
        # equivalents x tokens_per_tick sub-steps
        n_micro = self._pipeline_micro(width)
        bubble_lanes = 0
        if n_micro:
            bubble_lanes = (
                (self.stage_shards - 1)
                * ((self.capacity if width is None else width) // n_micro)
                * self.tokens_per_tick
            )
        # live trace-id set: the requests this tick actually advances
        # (mid-prefill residents are masked out of sampling) — stamped
        # on the span AND the jsonl record so host-side attribution can
        # apportion tick_ms / analytic FLOPs across residents
        live_traces = sorted(
            t.trace_id for t in self._slots.values()
            if t.status is RequestStatus.DECODE
        )
        t0 = time.perf_counter()
        with self.tracer.span("serving_tick", occupied=occupied,
                              traces=live_traces):
            if self.spec:
                # speculative draft-verify tick: one lm_verify_chunk
                # launch commits up to spec_width+1 tokens per slot
                # (serving/spec_decode.py); _spec_tick owns the hybrid
                # lengths mirror (it advances by the chunk width only
                # on full accepts)
                tokens, emitted, done = self._spec_tick(width)
            elif width is not None:
                tokens, emitted, done = self._compact_tick(
                    live_slots, width, n_micro
                )
            else:
                tick_kv = ()
                if self.hybrid:
                    # page-count BUCKET: pow2 of the largest resident
                    # allocation, so the tick's attention reads scale
                    # with what is actually live (one trace per bucket;
                    # bucket width changes never perturb token streams —
                    # masked attention is bit-stable across page-bucket
                    # widths, models/attention.py)
                    largest = max(
                        (len(t.pages) for t in self._slots.values()
                         if t.pages), default=1,
                    )
                    bucket = min(next_pow2_bucket(largest, min_bucket=1),
                                 self._page_tbl.shape[1])
                    tick_kv = (jnp.asarray(self._page_tbl[:, :bucket]),
                               jnp.asarray(self._kv_len))
                self.pool, tokens, emitted, done = _tick(
                    self._params, self.pool, *tick_kv, cfg=self.cfg,
                    k_max=self.max_top_k, steps=self.tokens_per_tick,
                    mesh=self.mesh, n_micro=n_micro,
                )
                tokens = np.asarray(tokens)  # (steps, S) — the host sync
                emitted = np.asarray(emitted)
                done = np.asarray(done)
                if self.hybrid:
                    # mirror the device-side lengths advance: +1 per
                    # live sub-step, exactly what `emitted` marks
                    self._kv_len += emitted.sum(axis=0).astype(np.int32)
        t_now = time.perf_counter()
        dt = t_now - t0

        events: list[TokenEvent] = []
        for j in range(tokens.shape[0]):
            for slot, tracked in self._slots.items():
                if not emitted[j, slot]:
                    continue
                r = tracked.request
                tok = int(tokens[j, slot])
                tracked.new_tokens.append(tok)
                # the finish RULE lives in _tick; the host only reads its
                # verdict and labels the reason from the emitted token
                if done[j, slot]:
                    tracked.status = RequestStatus.FINISHED
                    tracked.finish_reason = (
                        "eos" if (r.eos_id is not None and tok == r.eos_id)
                        else "length"
                    )
                events.append(TokenEvent(
                    tracked.request_id, tok, len(tracked.new_tokens) - 1,
                    bool(done[j, slot]), tracked.finish_reason,
                ))
        # --- per-request latency stamps (must precede eviction).  Tokens
        # land on the host at the tick fetch, so a tick's m tokens share
        # one timestamp; the per-token ITL observation is the span since
        # the request's previous arrival (tick start for its first tick)
        # divided by m — the finest granularity the host can see.
        for slot, tracked in self._slots.items():
            m = int(emitted[:, slot].sum())
            if not m:
                continue
            if tracked.t_first_token is None:
                tracked.t_first_token = t_now
                self.metrics.record_ttft(t_now - tracked.t_submit)
                if self.prefix_cache is not None:
                    # TTFT split hit-vs-miss: the cache's whole point is
                    # this delta (summary()["prefix_cache"])
                    self.metrics.record_prefix_ttft(
                        t_now - tracked.t_submit,
                        hit=tracked.cache_hit is not None,
                    )
                gaps, t_prev = m - 1, t0
            else:
                gaps, t_prev = m, tracked.t_last_token
            if gaps:
                per_token_s = (t_now - t_prev) / m
                self.metrics.record_itl(per_token_s, gaps)
                tracked.itl_hist.record(per_token_s * 1000, gaps)
            tracked.t_last_token = t_now
        for slot in [s for s, t in self._slots.items()
                     if t.status is RequestStatus.FINISHED]:
            tracked = self._slots.pop(slot)
            self.pool = state_cache.evict(self.pool, slot)
            self._release_pages(slot, tracked)
            self._release_adapter_ref(tracked)
            # bounded finished-stream ring: lets stream_state() replay
            # a just-finished stream's tail to a re-attaching consumer
            # (SSE resume tokens) after the tracker is gone
            self._recent_finished[tracked.request_id] = (
                list(tracked.new_tokens), tracked.finish_reason
            )
            while len(self._recent_finished) > self.RECENT_FINISHED_KEEP:
                self._recent_finished.pop(
                    next(iter(self._recent_finished))
                )
            self._free.append(slot)
            if self.spec:
                self.drafter.forget(tracked.request_id)
            # a hot-swapped stream finishes as the internal continuation
            # request — the record and result must echo what the USER
            # submitted (original prompt; the full generated suffix
            # already lives in tracked.new_tokens)
            r = tracked.orig_request or tracked.request
            request_record = {
                "request_id": tracked.request_id,
                "trace_id": tracked.trace_id,
                "prompt_tokens": int(len(r.prompt_ids)),
                "new_tokens": len(tracked.new_tokens),
                "finish_reason": tracked.finish_reason,
                "queue_wait_ms": round(
                    (tracked.t_admit - tracked.t_submit) * 1000, 3),
                "ttft_ms": round(
                    (tracked.t_first_token - tracked.t_submit) * 1000, 3),
                "e2e_ms": round((t_now - tracked.t_submit) * 1000, 3),
                "itl_hist": tracked.itl_hist.to_dict(),
            }
            # cache/priority stamps only when the features are live, so
            # records from plain engines stay byte-stable
            if self.prefix_cache is not None:
                request_record["prefix_hit"] = tracked.cache_hit
            if tracked.preempted:
                request_record["preemptions"] = tracked.preempted
            if tracked.migrations:
                # the disaggregated handoff trail: how many times this
                # request moved tiers, the host time the moves cost,
                # and the prefill replica that produced the artifact
                # (this record's own `replica` stamp is the target)
                request_record["migrations"] = tracked.migrations
                request_record["migration_ms"] = round(
                    tracked.migration_ms, 3)
                request_record["migration_source"] = \
                    tracked.migration_source
            if tracked.priority != self.scheduler.default_priority:
                request_record["priority"] = tracked.priority
            if self.lora and getattr(tracked.request, "adapter", None):
                # the adapter the stream FINISHED under (the swapped-to
                # version for hot-swapped streams)
                request_record["adapter"] = tracked.request.adapter
            if tracked.hot_swaps:
                request_record["hot_swaps"] = tracked.hot_swaps
            self.metrics.record_request(request_record)
            if self.slo is not None:
                self.slo.observe_request(request_record,
                                         replica=self.metrics.replica)
            if self.retain_results:
                self.results[tracked.request_id] = GenerationResult(
                    request_id=tracked.request_id,
                    prompt_ids=r.prompt_ids,
                    new_tokens=np.asarray(tracked.new_tokens, np.int32),
                    finish_reason=tracked.finish_reason,
                )
        self._free.sort()
        kv_gauges = {}
        if self.hybrid:
            # KV-page gauges ride the serving_tick record (rendered by
            # scripts/obs_report.py): occupancy of the page pool plus
            # this window's allocator churn
            kv_gauges = dict(
                kv_pages_used=self.page_pool.pages_in_use,
                kv_pages_capacity=self.page_pool.num_pages,
                kv_page_allocs=self._page_allocs,
                kv_page_frees=self._page_frees,
            )
            self._page_allocs = 0
            self._page_frees = 0
        pc_gauges = {}
        if self.prefix_cache is not None:
            # hit/miss/bytes gauges ride the serving_tick record (host-
            # side only; absent entirely on cache-off engines)
            pc_gauges = dict(
                prefix_hits=self._pc_hits,
                prefix_misses=self._pc_misses,
                prefix_saved_tokens=self._pc_saved_tokens,
                prefix_cache_entries=len(self.prefix_cache),
                prefix_cache_bytes=self.prefix_cache.nbytes,
            )
            self._pc_hits = 0
            self._pc_misses = 0
            self._pc_saved_tokens = 0
        spec_gauges = {}
        if self.spec:
            # draft/accept counters ride every tick record when
            # speculation is on (absent at K=0 — records byte-stable);
            # obs_report.py renders the "speculation:" roll-up line
            spec_gauges = dict(
                spec_drafted=self._spec_drafted,
                spec_accepted=self._spec_accepted,
                spec_streams=self._spec_streams,
            )
            self._spec_drafted = 0
            self._spec_accepted = 0
            self._spec_streams = 0
        lora_gauges = {}
        if self.lora:
            # adapter-cache window counters + residency/live gauges
            # ride every tick record when multi-tenant LoRA is on
            # (absent otherwise — records stay byte-stable); the
            # distinct-adapter gauge counts the factor rows this
            # tick's launch actually mixed
            ac = self.adapter_cache
            lora_gauges = dict(
                adapters_resident=ac.resident_count,
                adapter_cache_hits=ac.hits - self._ad_hits0,
                adapter_cache_misses=ac.misses - self._ad_misses0,
                adapter_cache_evictions=ac.evictions
                - self._ad_evictions0,
                adapters_live=len({
                    t.adapter_slot for t in self._slots.values()
                    if t.status is RequestStatus.DECODE
                    and t.adapter_slot
                }),
            )
            self._ad_hits0 = ac.hits
            self._ad_misses0 = ac.misses
            self._ad_evictions0 = ac.evictions
        session_gauges = {}
        if self.session_store is not None:
            # durable-session gauges + window counters ride every tick
            # record when a store is attached (absent otherwise —
            # records stay byte-stable with sessions off); the TTL
            # sweep piggybacks here, rate-limited inside the store
            expired = self.session_store.maybe_sweep()
            if expired:
                self._session_expires += expired
                self.metrics.record_session_expire(expired)
            st = self.session_store.stats()
            session_gauges = dict(
                sessions_parked_host=st["parked_host"],
                sessions_parked_disk=st["parked_disk"],
                sessions_bytes_host=st["bytes_host"],
                sessions_bytes_disk=st["bytes_disk"],
                session_parks=self._session_parks,
                session_resumes=self._session_resumes,
                session_expires=self._session_expires,
            )
        quant_gauges = {}
        if self.quantized_weights or self.quantized_kv:
            # int8 serving stamps its dtype pair + resident-bytes
            # gauges on every tick record (absent otherwise — records
            # stay byte-stable with quant off)
            quant_gauges = dict(
                quantized=self._quant_stamp,
                weight_bytes=self._weight_bytes,
                page_pool_bytes=self._pool_bytes,
            )
        compile_gauges = {}
        if self.compile_watchdog is not None:
            # XLA compiles observed since the previous tick record
            # (absent without a watchdog — records stay byte-stable)
            n_compiles, compile_ms = self.compile_watchdog.drain()
            compile_gauges = dict(compiles=n_compiles,
                                  compile_ms=compile_ms)
        if self.tick_regression is not None:
            self.tick_regression.observe_tick(
                dt * 1000, replica=self.metrics.replica
            )
        self.metrics.record_tick(
            occupied=occupied, queue_depth=self.scheduler.depth,
            tokens_emitted=len(events), dt_s=dt,
            prefill_stall_ms=self._pending_stall_ms,
            prefill_chunk_tokens=self._pending_chunk_tokens,
            prefill_chunk_ms=self._pending_chunk_ms,
            prefill_real_tokens=self._pending_chunk_real_tokens,
            prefill_oneshot_tokens=self._pending_oneshot_real_tokens,
            prefill_oneshot_lanes=self._pending_oneshot_lanes,
            # goodput honesty: lanes are billed at the width the launch
            # actually computed — the compacted bucket when compaction
            # narrowed it, static capacity otherwise
            slot_lanes=(self.capacity if width is None else width)
            * (self.spec_width if self.spec else self.tokens_per_tick),
            compaction_width=(
                (self.capacity if width is None else width)
                if self.compaction else None
            ),
            traces=live_traces,
            model_shards=(self.model_shards if self.model_shards > 1
                          else None),
            # pipeline stamps only when the stage axis is live, so 2-D
            # engines' records stay byte-stable; bubble_lanes is 0 on
            # GSPMD-fallback ticks (no explicit clock, no ramp waste)
            stage_shards=(self.stage_shards if self.stage_shards > 1
                          else None),
            bubble_lanes=(bubble_lanes if self.stage_shards > 1
                          else None),
            preemptions=self._preemptions,
            migrations_out=self._migrations_out,
            migrations_in=self._migrations_in,
            # stamped only when nonzero (utils/metrics.record_tick) —
            # quota-off / swap-free engines' records stay byte-stable
            tenant_quota_stalls=self._quota_stalls,
            adapter_hot_swaps=self._hot_swaps,
            **pc_gauges,
            **kv_gauges,
            **quant_gauges,
            **spec_gauges,
            **lora_gauges,
            **session_gauges,
            **compile_gauges,
        )
        self._preemptions = 0
        self._migrations_out = 0
        self._migrations_in = 0
        self._quota_stalls = 0
        self._hot_swaps = 0
        self._session_parks = 0
        self._session_resumes = 0
        self._session_expires = 0
        self._pending_stall_ms = 0.0
        self._pending_chunk_tokens = 0
        self._pending_chunk_real_tokens = 0
        self._pending_chunk_ms = 0.0
        self._pending_oneshot_real_tokens = 0
        self._pending_oneshot_lanes = 0
        return events

    # ------------------------------------------------------------- frontends

    def serve(self, requests=()):  # -> Iterator[TokenEvent]
        """Minimal serving frontend: accept requests, stream tokens back.

        Yields TokenEvents as ticks complete; more requests may be
        ``submit``-ted concurrently from the consuming side between
        yields (the generator re-checks ``pending`` each tick).
        """
        for r in requests:
            self.submit(r)
        while self.pending:
            yield from self.step()

    def run(self, requests=()) -> list[GenerationResult]:
        """Submit ``requests``, drain the engine, return results in
        submission order."""
        if not self.retain_results:
            raise ValueError("run() needs retain_results=True; stream "
                             "via serve() instead")
        ids = [self.submit(r) for r in requests]
        for _ in self.serve():
            pass
        return [self.results[i] for i in ids]
