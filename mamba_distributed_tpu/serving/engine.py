"""Continuous-batching serving engine over the pooled recurrent-state cache.

One compiled decode tick advances EVERY occupied slot by ``tokens_per_tick``
tokens; finished and empty slots are masked, and new requests are admitted
into freed slots between ticks — bucketed prefill (inference/bucketing.py)
plus ``state_cache.insert`` write a request's state into its slot without
retracing anything.  Decode is weight-bandwidth-bound, so filling more
slots costs (nearly) nothing per tick: aggregate tokens/sec scales with
occupancy (docs/SERVING.md; scripts/bench_serving.py measures it against
sequential ``generate()`` calls).

Long prompts (``t > cfg.prefill_chunk_tokens``) prefill in CHUNKS
(serving/prefill.py) interleaved with decode ticks: each ``step()``
spends at most ``cfg.prefill_tokens_per_tick`` tokens of chunk work
(oldest request first) before running the tick, and a half-prefilled
request keeps its slot with its scan carry parked in the pool
(``state_cache.stash_prefill``; the tick masks such slots from sampling
and from state writes) until the next budget grant resumes it.  Short
prompts keep the PR-1 behavior: a one-shot pow2-bucketed prefill at
admission, not counted against the chunk budget (they are at most
~chunk-sized by construction).  This bounds both the TTFT of short
requests and the ITL of running slots while a long prompt streams in —
the head-of-line blocking ``bench_serving --long-prompt`` measures.

Parity contract: a request's token stream is bit-identical to a solo
``generate(params, cfg, prompt[None], key, ...)`` call with the same key
whenever ``request.top_k == engine.max_top_k`` (the static top-k width),
regardless of what else shares the batch.  The pieces that make this
hold, pinned by tests/test_serving.py and tests/test_prefill.py:

* both pad the same prompt to the same bucket — pow2 one-shot for short
  prompts, the chunk-aligned layout driven through the SAME jitted
  chunk step for long ones (neither is an engine knob: both live on
  ModelConfig / the bucketing module, so the two callers can never
  disagree);
* the step-i sampling key is ``fold_in(request_key, i)``, reproducible
  from the per-slot counter alone — and a vmapped per-row
  ``categorical`` draws the same bits as generate's batch-1 call;
* ``lm_step`` is row-independent, so co-batched strangers can't
  perturb a slot's logits.

Requests with ``top_k < max_top_k`` are served via masking (positions
beyond the slot's k get -inf) — a valid top-k draw, but from a different
noise stream than a solo ``generate(top_k=k)`` call would use.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.inference.bucketing import next_pow2_bucket, pad_to_bucket
from mamba_distributed_tpu.obs import NULL_TRACER, StreamingHistogram
from mamba_distributed_tpu.inference.generate import vocab_pad_mask
from mamba_distributed_tpu.models.lm import init_lm_state, lm_prefill, lm_step
from mamba_distributed_tpu.serving import state_cache
from mamba_distributed_tpu.serving.prefill import (
    cast_decode_params,
    chunk_inputs,
    plan_chunks,
    prefill_chunk,
)
from mamba_distributed_tpu.serving.scheduler import (
    FCFSScheduler,
    GenerationRequest,
    GenerationResult,
    RequestStatus,
    TokenEvent,
    _Tracked,
)
from mamba_distributed_tpu.utils.metrics import ServingMetrics

# Python-side-effect trace counters (one bump per jit trace) — the
# bucketing exists to bound these; tests/test_serving.py pins them (the
# chunk step's counter lives in serving/prefill.py, pinned by
# tests/test_prefill.py).
TRACE_COUNTS = {"prefill": 0, "tick": 0}


@functools.partial(jax.jit, static_argnames=("cfg",))
def _prefill(params: dict, ids: jax.Array, mask: jax.Array, cfg: ModelConfig):
    """Bucketed batch-1 prompt prefill -> (last_logits (1, V), state)."""
    TRACE_COUNTS["prefill"] += 1
    return lm_prefill(params, cfg, ids, token_mask=mask)


@functools.partial(
    jax.jit, static_argnames=("cfg", "k_max", "steps"), donate_argnums=(1,)
)
def _tick(params: dict, pool: dict, cfg: ModelConfig, k_max: int, steps: int):
    """Advance every slot ``steps`` tokens.  Returns (pool', tokens
    (steps, S), emitted (steps, S), done (steps, S)) — ``emitted[j, s]``
    marks a real token (slot live at sub-step j), ``done[j, s]`` the
    slot's finish state after it; the rest is masked garbage.  The host
    consumes ``done`` rather than re-deriving the finish rule, so there
    is exactly one copy of it (here).

    Mirrors generate()'s decode loop exactly: sample from the carried
    logits with key fold_in(key, step), then lm_step.  Slots that hit
    their eos keep feeding it forward (same as generate's eos_id path);
    slots that are empty or budget-done still compute — that waste is
    the price of a single static-shape trace, and it is reclaimed by
    admitting new requests into those slots between ticks.
    """
    TRACE_COUNTS["tick"] += 1
    pad_mask = vocab_pad_mask(cfg)
    col = jnp.arange(k_max)[None, :]

    def one(pool, _):
        meta = pool["meta"]
        # a slot mid-chunked-prefill is resident but NOT live: it emits
        # nothing, and its parked scan carry must survive the tick
        live = meta["active"] & ~meta["done"] & ~meta["prefilling"]
        has_eos = meta["eos_id"] >= 0
        keys = jax.vmap(jax.random.fold_in)(meta["key"], meta["step"])
        vals, idx = jax.lax.top_k(pool["logits"] + pad_mask, k_max)
        vals = jnp.where(col < meta["top_k"][:, None], vals, -jnp.inf)
        # per-row categorical: same bits as generate's batch-1 draw
        choice = jax.vmap(
            lambda k, v, t: jax.random.categorical(k, v / t)
        )(keys, vals, meta["temperature"])
        tok = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]
        tok = jnp.where(meta["done"] & has_eos, meta["eos_id"], tok)
        logits, state = lm_step(params, cfg, pool["state"], tok)
        # empty/done slots may compute garbage freely (masked, overwritten
        # by the next insert), but a prefilling slot's rows hold a REAL
        # carry — keep them (select per (L, S, ...) leaf on the S axis)
        hold = meta["prefilling"]
        state = jax.tree.map(
            lambda new, old: jnp.where(
                hold.reshape((1, -1) + (1,) * (new.ndim - 2)), old, new
            ),
            state,
            pool["state"],
        )
        logits = jnp.where(hold[:, None], pool["logits"], logits)
        step = meta["step"] + live.astype(jnp.int32)
        done = meta["done"] | (
            live & ((has_eos & (tok == meta["eos_id"])) | (step >= meta["max_new"]))
        )
        new_pool = {
            "state": state,
            "logits": logits,
            "meta": {**meta, "step": step, "done": done},
        }
        return new_pool, (tok, live, done)

    pool, (tokens, emitted, done) = jax.lax.scan(one, pool, None, length=steps)
    return pool, tokens, emitted, done


class ServingEngine:
    """Continuous-batching host loop: FCFS admission -> compiled ticks.

    Args:
      params: trained fp32 params (cast once to the decode layout here).
      cfg: pure-SSM ModelConfig (attention hybrids are rejected by the
        slot pool — ROADMAP open item).
      capacity: slot count S — the max concurrent requests.
      max_top_k: static top-k width of the compiled sampler; per-request
        ``top_k`` may be anything in [1, max_top_k] (see parity note in
        the module docstring).
      tokens_per_tick: decode sub-steps fused into one compiled tick.
        Larger amortizes dispatch; smaller admits waiting requests
        sooner (admission only happens between ticks).
      prefill_tokens_per_tick: chunk-prefill token budget spent between
        consecutive ticks (oldest in-flight prefill first; at least one
        chunk per step so progress is guaranteed).  None (default) takes
        ``cfg.prefill_tokens_per_tick``; 0 => unbounded.  Short-prompt
        one-shot prefills are NOT budgeted — each is at most ~one chunk
        of work, the PR-1 admission behavior.
      retain_results: keep every finished request's GenerationResult in
        ``self.results`` (what ``run()`` reads).  A long-lived streaming
        server consuming TokenEvents should pass False — retention
        grows host memory without bound — and the final event's
        ``done``/``finish_reason`` carries the completion signal.
      metrics: a ServingMetrics, or None to create one.  Give it a
        ``jsonl_path`` to stream per-tick and per-request records.
      tracer: an obs.SpanTracer for host-side phase spans
        (``serving_admit`` / ``serving_tick``); default NULL_TRACER
        (off).  Strictly host-side: enabling it adds zero device syncs
        and zero jit traces (pinned by tests/test_obs.py).

    Prefill buckets are the module defaults of inference/bucketing.py —
    deliberately not a knob, so the engine and a solo ``generate()``
    call can never pad the same prompt differently (the parity
    contract depends on identical padding).
    """

    def __init__(
        self,
        params: dict,
        cfg: ModelConfig,
        capacity: int = 8,
        max_top_k: int = 50,
        tokens_per_tick: int = 8,
        prefill_tokens_per_tick: int | None = None,
        retain_results: bool = True,
        metrics: ServingMetrics | None = None,
        tracer=NULL_TRACER,
    ):
        if not 1 <= max_top_k <= cfg.vocab_size_padded:
            raise ValueError(
                f"max_top_k={max_top_k} must be in [1, {cfg.vocab_size_padded}]"
            )
        if tokens_per_tick < 1:
            raise ValueError("tokens_per_tick must be >= 1")
        if prefill_tokens_per_tick is None:
            prefill_tokens_per_tick = cfg.prefill_tokens_per_tick
        if prefill_tokens_per_tick < 0:
            raise ValueError("prefill_tokens_per_tick must be >= 0 "
                             "(0 => unbounded)")
        self.cfg = cfg
        self.capacity = capacity
        self.max_top_k = max_top_k
        self.tokens_per_tick = tokens_per_tick
        self.prefill_tokens_per_tick = prefill_tokens_per_tick
        self.retain_results = retain_results
        self.pool = state_cache.init_pool(cfg, capacity)  # validates cfg
        self._params = cast_decode_params(params, cfg=cfg)
        self.scheduler = FCFSScheduler()
        self.metrics = metrics or ServingMetrics(capacity)
        self.tracer = tracer
        self._free: list[int] = list(range(capacity))
        self._slots: dict[int, _Tracked] = {}
        # slots holding a partial chunked prefill, in admission order
        # (the budget drains them FCFS)
        self._prefill_queue: list[int] = []
        # prefill accounting awaiting a tick record: tick-less steps
        # (everything resident still mid-prefill) roll their stall /
        # chunk counters into the NEXT tick's jsonl record so the
        # serving_tick stream never drops work (obs_report.py totals)
        self._pending_stall_ms = 0.0
        self._pending_chunk_tokens = 0
        self._pending_chunk_ms = 0.0
        self.results: dict[int, GenerationResult] = {}

    # ------------------------------------------------------------- admission

    def submit(self, request: GenerationRequest) -> int:
        """Queue a request; returns its request_id."""
        if not 1 <= request.top_k <= self.max_top_k:
            raise ValueError(
                f"request top_k={request.top_k} must be in "
                f"[1, max_top_k={self.max_top_k}]"
            )
        tracked = self.scheduler.submit(request)
        return tracked.request_id

    def _admit(self, tracked: _Tracked) -> None:
        """Grant the next queued request a slot.  Short prompts prefill
        one-shot right here (PR-1 path); long prompts register a chunk
        plan and park a zero carry — their chunks run in the budget
        phase (``_advance_prefill``)."""
        slot = self._free.pop(0)
        tracked.status = RequestStatus.PREFILL
        r = tracked.request
        plan = plan_chunks(len(r.prompt_ids),
                           self.cfg.effective_prefill_chunk_tokens)
        t0 = time.perf_counter()
        try:
            if plan is None:
                prompt = jnp.asarray(r.prompt_ids, jnp.int32)[None, :]
                padded, mask = pad_to_bucket(
                    prompt, next_pow2_bucket(prompt.shape[1])
                )
                # async dispatch: admitting k queued requests between ticks
                # queues k prefills+inserts without a host sync each — the
                # next tick's token fetch is the one synchronization point
                logits, state = _prefill(
                    self._params, padded, mask, cfg=self.cfg
                )
                self.pool = state_cache.insert(
                    self.pool, slot, state, logits, r.resolve_key(),
                    r.max_new_tokens, r.top_k, r.temperature,
                    -1 if r.eos_id is None else r.eos_id,
                )
            else:
                tracked.plan = plan
                tracked.chunks_done = 0
                tracked.prefill_dt = 0.0
                self.pool = state_cache.stash_prefill(
                    self.pool, slot, init_lm_state(self.cfg, batch=1),
                    r.resolve_key(), r.max_new_tokens, r.top_k,
                    r.temperature, -1 if r.eos_id is None else r.eos_id,
                )
        except Exception:
            # a failed prefill must neither leak the slot (capacity would
            # shrink for the process lifetime) nor drop the request — it
            # goes back to the queue head so a caller catching the raise
            # still sees it in `pending` and can retry or cancel
            self._free.insert(0, slot)
            self.scheduler.requeue(tracked)
            raise
        # dt is host dispatch time (prefill runs async; the next tick's
        # fetch absorbs device completion)
        t_admit = time.perf_counter()
        if plan is None:
            self.metrics.record_prefill(int(len(r.prompt_ids)), t_admit - t0)
        # lifecycle stamps: queue-wait is submit -> slot granted; the
        # per-request ITL histogram rides in the finish record so
        # obs_report.py can merge per-token percentiles across requests
        tracked.t_admit = t_admit
        tracked.itl_hist = StreamingHistogram()
        self.metrics.record_queue_wait(t_admit - tracked.t_submit)
        tracked.slot = slot
        self._slots[slot] = tracked
        if plan is None:
            tracked.status = RequestStatus.DECODE
        else:
            self._prefill_queue.append(slot)

    def _advance_prefill(self, slot: int, budget_left: float) -> float:
        """Run chunks for ``slot``'s partial prefill until its plan or the
        budget runs out (>= 1 chunk per call: progress is guaranteed even
        when ``budget_left < chunk``).  Completion flips the slot
        decodable; otherwise the carry is re-stashed.  Returns the
        remaining budget."""
        tracked = self._slots[slot]
        plan, r = tracked.plan, tracked.request
        logits = None
        try:
            state = state_cache.read_state(self.pool, slot)
            while tracked.chunks_done < plan.n_chunks and budget_left > 0:
                i = tracked.chunks_done
                ids, mask = chunk_inputs(r.prompt_ids, plan, i)
                t0 = time.perf_counter()
                with self.tracer.span("serving_prefill_chunk", slot=slot,
                                      chunk=i, of=plan.n_chunks):
                    logits, state = prefill_chunk(
                        self._params, ids, mask, state, cfg=self.cfg
                    )
                dt = time.perf_counter() - t0  # host dispatch time
                tracked.chunks_done += 1
                tracked.prefill_dt += dt
                budget_left -= plan.chunk
                self.metrics.record_prefill_chunk(plan.chunk, dt)
            if tracked.chunks_done == plan.n_chunks:
                self.pool = state_cache.finish_prefill(
                    self.pool, slot, state, logits
                )
                self._prefill_queue.remove(slot)
                tracked.status = RequestStatus.DECODE
                self.metrics.record_prefill(
                    plan.prompt_len, tracked.prefill_dt
                )
            else:
                self.pool = state_cache.stash_prefill(
                    self.pool, slot, state, r.resolve_key(),
                    r.max_new_tokens, r.top_k, r.temperature,
                    -1 if r.eos_id is None else r.eos_id,
                )
        except Exception:
            # mirror the one-shot contract: free the slot, requeue the
            # request (restarting its prefill from chunk 0), re-raise
            self.pool = state_cache.evict(self.pool, slot)
            self._prefill_queue.remove(slot)
            del self._slots[slot]
            self._free.insert(0, slot)
            self._free.sort()
            tracked.plan = None
            tracked.chunks_done = 0
            tracked.slot = None
            self.scheduler.requeue(tracked)
            raise
        return budget_left

    def _prefill_phase(self) -> tuple[float, int]:
        """Between-ticks prefill work: admit what fits, then spend the
        chunk budget on in-flight partial prefills (oldest first).
        Returns (host seconds spent — the tick's ``prefill_stall`` —
        and chunk tokens dispatched)."""
        if not ((self._free and self.scheduler.depth) or self._prefill_queue):
            return 0.0, 0
        t0 = time.perf_counter()
        chunk_tokens0 = self.metrics.prefill_chunk_tokens
        chunk_s0 = self.metrics.prefill_chunk_time_s
        if self._free and self.scheduler.depth:
            with self.tracer.span("serving_admit",
                                  queued=self.scheduler.depth):
                while self._free and self.scheduler.depth:
                    self._admit(self.scheduler.pop())
        budget = self.prefill_tokens_per_tick
        left = float("inf") if budget == 0 else float(budget)
        for slot in list(self._prefill_queue):
            if left <= 0:
                break
            left = self._advance_prefill(slot, left)
        self._pending_chunk_ms += (
            self.metrics.prefill_chunk_time_s - chunk_s0
        ) * 1000
        return (time.perf_counter() - t0,
                self.metrics.prefill_chunk_tokens - chunk_tokens0)

    # ------------------------------------------------------------- decoding

    @property
    def pending(self) -> int:
        """Requests not yet finished (queued + in-flight)."""
        return self.scheduler.depth + len(self._slots)

    def step(self) -> list[TokenEvent]:
        """One engine iteration: prefill phase (admissions + chunk
        budget), then one compiled tick, streaming its tokens.

        Returns the tick's TokenEvents in emission order (empty while
        only partial prefills are resident); finished requests are
        evicted and their GenerationResults recorded in ``self.results``.
        """
        stall_s, chunk_tokens = self._prefill_phase()
        if stall_s:
            self.metrics.record_prefill_stall(stall_s)
        self._pending_stall_ms += stall_s * 1000
        self._pending_chunk_tokens += chunk_tokens
        if not any(t.status is RequestStatus.DECODE
                   for t in self._slots.values()):
            # nothing decodable yet (empty engine, or every resident slot
            # still mid-prefill): no tick this step — the loop keeps
            # granting chunk budget until a slot turns decodable
            return []
        occupied = len(self._slots)
        t0 = time.perf_counter()
        with self.tracer.span("serving_tick", occupied=occupied):
            self.pool, tokens, emitted, done = _tick(
                self._params, self.pool, cfg=self.cfg, k_max=self.max_top_k,
                steps=self.tokens_per_tick,
            )
            tokens = np.asarray(tokens)  # (steps, S) — the host sync point
            emitted = np.asarray(emitted)
            done = np.asarray(done)
        t_now = time.perf_counter()
        dt = t_now - t0

        events: list[TokenEvent] = []
        for j in range(self.tokens_per_tick):
            for slot, tracked in self._slots.items():
                if not emitted[j, slot]:
                    continue
                r = tracked.request
                tok = int(tokens[j, slot])
                tracked.new_tokens.append(tok)
                # the finish RULE lives in _tick; the host only reads its
                # verdict and labels the reason from the emitted token
                if done[j, slot]:
                    tracked.status = RequestStatus.FINISHED
                    tracked.finish_reason = (
                        "eos" if (r.eos_id is not None and tok == r.eos_id)
                        else "length"
                    )
                events.append(TokenEvent(
                    tracked.request_id, tok, len(tracked.new_tokens) - 1,
                    bool(done[j, slot]), tracked.finish_reason,
                ))
        # --- per-request latency stamps (must precede eviction).  Tokens
        # land on the host at the tick fetch, so a tick's m tokens share
        # one timestamp; the per-token ITL observation is the span since
        # the request's previous arrival (tick start for its first tick)
        # divided by m — the finest granularity the host can see.
        for slot, tracked in self._slots.items():
            m = int(emitted[:, slot].sum())
            if not m:
                continue
            if tracked.t_first_token is None:
                tracked.t_first_token = t_now
                self.metrics.record_ttft(t_now - tracked.t_submit)
                gaps, t_prev = m - 1, t0
            else:
                gaps, t_prev = m, tracked.t_last_token
            if gaps:
                per_token_s = (t_now - t_prev) / m
                self.metrics.record_itl(per_token_s, gaps)
                tracked.itl_hist.record(per_token_s * 1000, gaps)
            tracked.t_last_token = t_now
        for slot in [s for s, t in self._slots.items()
                     if t.status is RequestStatus.FINISHED]:
            tracked = self._slots.pop(slot)
            self.pool = state_cache.evict(self.pool, slot)
            self._free.append(slot)
            r = tracked.request
            self.metrics.record_request({
                "request_id": tracked.request_id,
                "prompt_tokens": int(len(r.prompt_ids)),
                "new_tokens": len(tracked.new_tokens),
                "finish_reason": tracked.finish_reason,
                "queue_wait_ms": round(
                    (tracked.t_admit - tracked.t_submit) * 1000, 3),
                "ttft_ms": round(
                    (tracked.t_first_token - tracked.t_submit) * 1000, 3),
                "e2e_ms": round((t_now - tracked.t_submit) * 1000, 3),
                "itl_hist": tracked.itl_hist.to_dict(),
            })
            if self.retain_results:
                self.results[tracked.request_id] = GenerationResult(
                    request_id=tracked.request_id,
                    prompt_ids=r.prompt_ids,
                    new_tokens=np.asarray(tracked.new_tokens, np.int32),
                    finish_reason=tracked.finish_reason,
                )
        self._free.sort()
        self.metrics.record_tick(
            occupied=occupied, queue_depth=self.scheduler.depth,
            tokens_emitted=len(events), dt_s=dt,
            prefill_stall_ms=self._pending_stall_ms,
            prefill_chunk_tokens=self._pending_chunk_tokens,
            prefill_chunk_ms=self._pending_chunk_ms,
        )
        self._pending_stall_ms = 0.0
        self._pending_chunk_tokens = 0
        self._pending_chunk_ms = 0.0
        return events

    # ------------------------------------------------------------- frontends

    def serve(self, requests=()):  # -> Iterator[TokenEvent]
        """Minimal serving frontend: accept requests, stream tokens back.

        Yields TokenEvents as ticks complete; more requests may be
        ``submit``-ted concurrently from the consuming side between
        yields (the generator re-checks ``pending`` each tick).
        """
        for r in requests:
            self.submit(r)
        while self.pending:
            yield from self.step()

    def run(self, requests=()) -> list[GenerationResult]:
        """Submit ``requests``, drain the engine, return results in
        submission order."""
        if not self.retain_results:
            raise ValueError("run() needs retain_results=True; stream "
                             "via serve() instead")
        ids = [self.submit(r) for r in requests]
        for _ in self.serve():
            pass
        return [self.results[i] for i in ids]
