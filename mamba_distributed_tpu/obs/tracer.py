"""Host-side span tracer: one jsonl event stream, zero device impact.

``SpanTracer.span("train_step")`` times a host-side phase and appends one
``{"kind": "span", ...}`` record on exit.  The tracer never touches a
jax.Array and is never called from inside a jitted function, so enabling
it adds zero device syncs and zero extra jit traces — the design point
that makes it safe to leave on in production serving loops (the pjit-at-
scale practice of structured *host* telemetry, PAPERS.md "Scalable
Training of Language Models using JAX pjit and TPUv4").

``NULL_TRACER`` is the disabled implementation: ``span()`` returns a
shared ``nullcontext``, so instrumented code pays one attribute lookup
and one function call when telemetry is off.  Code under instrumentation
takes a tracer instance (trainer, serving engine) rather than consulting
a global, so two engines in one process can write disjoint streams.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import threading
import time


def jsonable(record: dict) -> dict:
    """NaN/Inf are not valid JSON (json.dumps emits bare NaN tokens strict
    parsers reject — exactly in the diverged-run case where telemetry
    matters most); serialize them as null."""
    return {
        k: (None if isinstance(v, float) and not math.isfinite(v) else v)
        for k, v in record.items()
    }


def append_jsonl(path: str, record: dict, truncate: bool = False) -> None:
    """The one way every telemetry writer puts a record on disk: one
    jsonable object, one line, open-write-close per record — crash-safe
    (every line lands flushed+closed), and all writers are O(ms+) host
    phases so the syscall pair is noise.  ``truncate`` starts a fresh
    stream (writers defer it to their first write so a checkpoint resume
    can preserve history)."""
    with open(path, "w" if truncate else "a") as f:
        f.write(json.dumps(jsonable(record)) + "\n")


class SpanTracer:
    """Appends span/event records to one jsonl file.

    Span records carry the name, start offset from tracer creation
    (``t_ms``), duration (``dur_ms``), nesting ``depth`` and enclosing
    ``parent`` span name (per-thread stacks, so the async checkpoint
    thread can't corrupt the trainer's nesting), plus any keyword
    attributes given at the call site.  Writes are lock-serialized,
    open-append-close per record — crash-safe, and these are O(ms+)
    host phases so the syscall pair is noise.

    The first write additionally stamps one ``trace_header`` record
    (``wall_t0_s``: the wall clock paired with the tracer's t=0, plus
    the pid), which is what lets ``obs/export.py`` merge streams from
    different replicas/processes onto one timeline — ``t_ms`` alone is
    a process-local perf_counter offset and not comparable.

    ``jsonl_path=None`` keeps the tracer live with no file behind it —
    the ring-only mode a remote worker runs in when the controller
    drains its records over the wire (``obs_pull``) instead of the
    operator collecting files by hand.

    ``ring_len > 0`` additionally keeps the last N records in a
    bounded in-memory ring, each stamped with a monotonically
    increasing sequence number.  ``ring_pull(cursor)`` drains it
    incrementally — the cursor-resume idea of the PR-5 replay RPC
    applied to telemetry: a reader that comes back with its last
    cursor gets exactly the records it missed (or an explicit
    ``dropped`` count when the ring lapped it).  The ring holds
    already-jsonable dicts, so pulled records are byte-identical to
    what the file (if any) received.

    ``rotate_bytes > 0`` caps the jsonl file: when appending a record
    would push the file past the cap, the current file rolls to
    ``<path>.1`` (one generation — the previous ``.1`` is dropped) and
    a fresh ``trace_header`` opens the new file so each generation
    stays independently alignable.  ``obs/export.load_jsonl`` reads
    the rolled pair oldest-first.
    """

    enabled = True

    def __init__(self, jsonl_path: str | None = None,
                 _clock=time.perf_counter, *, ring_len: int = 0,
                 rotate_bytes: int = 0):
        if ring_len < 0:
            raise ValueError(f"ring_len must be >= 0, got {ring_len}")
        if rotate_bytes < 0:
            raise ValueError(
                f"rotate_bytes must be >= 0 (0 = no rotation), got "
                f"{rotate_bytes}"
            )
        if jsonl_path:
            parent = os.path.dirname(jsonl_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self.jsonl_path = jsonl_path
        self.rotate_bytes = rotate_bytes
        # ring of (seq, jsonable record); None when disabled
        self._ring = (collections.deque(maxlen=ring_len)
                      if ring_len else None)
        self._seq = 0
        # file size accounting for rotation; resolved lazily at the
        # first file write (a preserved-history append starts from the
        # existing file's size, a truncating first write from 0)
        self._file_bytes: int | None = None
        self._clock = _clock
        self._t0 = _clock()
        # wall clock paired with _t0 at the same instant: t_ms offsets
        # are perf_counter deltas (monotonic, but process-local), so
        # streams from different replicas/processes — or a post-resume
        # rebuilt tracer — are only comparable through this epoch.  The
        # first write stamps it as a "trace_header" record, and
        # obs/export.py aligns N streams on their headers' wall clocks.
        self.wall_t0 = time.time()
        self._lock = threading.Lock()
        self._local = threading.local()
        # small stable per-tracer thread index, stamped as ``tid`` on
        # span/event records: spans from different host threads (the
        # async checkpoint thread vs the trainer loop) overlap in wall
        # time without nesting, so the exporter must give each thread
        # its own track — overlapping slices on one track are invalid
        # trace-event JSON that Perfetto drops
        self._tids: dict[int, int] = {}
        # truncation is deferred to the first write (same contract as
        # MetricsLogger) so a checkpoint resume / --auto-restart rebuild
        # can preserve the pre-crash span history — which is exactly the
        # stream a post-mortem needs.  NB ``t_ms`` offsets restart from 0
        # for the new tracer's records (under a fresh header, so the
        # exporter still places them correctly on the shared timeline).
        self._truncate_pending = True
        self._header_pending = True

    def _stack(self) -> list[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Time the enclosed host-side block as one span record."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(name)
        t_start = self._clock()
        try:
            yield
        finally:
            dur = self._clock() - t_start
            stack.pop()
            record = {
                "kind": "span",
                "name": name,
                "t_ms": round((t_start - self._t0) * 1000, 3),
                "dur_ms": round(dur * 1000, 3),
                "depth": len(stack),
                "tid": self._tid(),
            }
            if parent is not None:
                record["parent"] = parent
            if attrs:
                record.update(attrs)
            self.write(record)

    def event(self, name: str, **attrs) -> None:
        """Record a point-in-time marker (no duration)."""
        record = {
            "kind": "event",
            "name": name,
            "t_ms": round((self._clock() - self._t0) * 1000, 3),
            "tid": self._tid(),
        }
        if attrs:
            record.update(attrs)
        self.write(record)

    def preserve_history(self) -> None:
        """Keep the existing stream (called on checkpoint resume)."""
        self._truncate_pending = False

    def _header_record(self) -> dict:
        return {"kind": "trace_header",
                "wall_t0_s": round(self.wall_t0, 6),
                "pid": os.getpid()}

    def _emit(self, record: dict, truncate: bool = False) -> None:
        """Lock held: one record into the ring and (if any) the file."""
        record = jsonable(record)
        if self._ring is not None:
            self._ring.append((self._seq, record))
            self._seq += 1
        if not self.jsonl_path:
            return
        line = json.dumps(record) + "\n"
        if self._file_bytes is None:
            self._file_bytes = (
                0 if truncate or not os.path.exists(self.jsonl_path)
                else os.path.getsize(self.jsonl_path)
            )
        if (self.rotate_bytes > 0 and self._file_bytes > 0
                and self._file_bytes + len(line) > self.rotate_bytes):
            # roll the full generation aside (one generation kept) and
            # re-head the fresh file so it stays alignable on its own —
            # the header does NOT enter the ring again (pulled streams
            # already carry the original one)
            os.replace(self.jsonl_path, self.jsonl_path + ".1")
            header = json.dumps(jsonable(self._header_record())) + "\n"
            with open(self.jsonl_path, "w") as f:
                f.write(header)
            self._file_bytes = len(header)
        with open(self.jsonl_path, "w" if truncate else "a") as f:
            f.write(line)
        self._file_bytes = (len(line) if truncate
                            else self._file_bytes + len(line))

    def write(self, record: dict) -> None:
        with self._lock:
            if self._header_pending:
                self._header_pending = False
                self._emit(self._header_record(),
                           truncate=self._truncate_pending)
                self._truncate_pending = False
            self._emit(record, truncate=self._truncate_pending)
            self._truncate_pending = False

    def ring_pull(self, cursor: int = 0, limit: int = 4096) -> dict:
        """Drain ring records with seq >= ``cursor`` (bounded).

        Returns ``{"records": [...], "cursor": next_cursor,
        "dropped": n}`` — ``dropped`` counts records that aged out of
        the ring before this pull (the reader's cursor fell behind the
        ring's oldest resident seq).  A tracer with no ring returns an
        empty page at the caller's cursor.
        """
        with self._lock:
            if self._ring is None:
                return {"records": [], "cursor": cursor, "dropped": 0}
            dropped = 0
            if self._ring:
                oldest = self._ring[0][0]
                if cursor < oldest:
                    dropped = oldest - cursor
                    cursor = oldest
            out = [rec for seq, rec in self._ring
                   if seq >= cursor][:max(0, limit)]
            return {"records": out, "cursor": cursor + len(out),
                    "dropped": dropped}


class _NullTracer:
    """Telemetry off: every operation is a no-op."""

    enabled = False
    _ctx = contextlib.nullcontext()  # reusable + reentrant

    def span(self, name: str, **attrs):
        return self._ctx

    def event(self, name: str, **attrs) -> None:
        pass

    def preserve_history(self) -> None:
        pass

    def write(self, record: dict) -> None:
        pass

    def ring_pull(self, cursor: int = 0, limit: int = 4096) -> dict:
        return {"records": [], "cursor": cursor, "dropped": 0}


NULL_TRACER = _NullTracer()
