"""XLA compile watchdog: count and time every backend compile.

The repo's pow2 bucketing (prompt buckets, tick compaction's lane
buckets, spec lanes) exists to BOUND recompiles — which makes silent
recompile thrash the production failure mode nothing watched until
now: a config that defeats the bucketing (or an occupancy pattern that
oscillates across a pow2 boundary) turns every tick into a multi-ms
XLA compile and the only symptom is a mysteriously bad ITL histogram.

``CompileWatchdog`` hooks ``jax.monitoring`` (the
``/jax/.../backend_compile_duration`` event fires once per XLA backend
compile, with its wall duration) and keeps:

  * process-lifetime totals (``compiles`` / ``compile_ms``) — exposed
    as counters on ``GET /metrics`` and in ``summary()``;
  * per-drain window deltas — the engine drains them each tick and
    stamps ``compiles``/``compile_ms`` on the ``serving_tick`` record
    (None-gated: no watchdog, no stamp — the byte-stability contract
    every optional plane in this repo keeps);
  * a tumbling thrash window: more than ``thrash_threshold`` compiles
    inside one ``thrash_window_s`` raises ONE ``compile_thrash`` event
    record through the tracer (the ``slo_breach`` discipline — once
    per window, never a per-compile flood).

Fallback: a jax build without the monitoring listener API degrades to
polling the engine's Python-side ``TRACE_COUNTS`` deltas via
``attach_trace_counts`` — compile counts stay right (one trace = one
compile for the jit entry points those counters wrap), durations
degrade to 0.  Strictly host-side either way: the listener runs on
the thread that triggered the compile, after the compile.
"""

from __future__ import annotations

import threading
import time

from mamba_distributed_tpu.obs.tracer import NULL_TRACER

# substring match: the event key moved across jax versions
# ("/jax/backend_compile", "/jax/core/compile/backend_compile_duration")
_COMPILE_EVENT = "backend_compile"


class CompileWatchdog:
    """Counts/times XLA backend compiles; raises on compile thrash.

    Args:
      thrash_threshold: compiles allowed per window before the
        ``compile_thrash`` event fires; 0 disables thrash detection
        (counting still works).
      thrash_window_s: tumbling window length in seconds.
      tracer: where the ``compile_thrash`` event record lands.
      _clock: injectable monotonic clock (tests).
    """

    def __init__(self, *, thrash_threshold: int = 0,
                 thrash_window_s: float = 60.0, tracer=NULL_TRACER,
                 _clock=time.monotonic):
        if thrash_threshold < 0:
            raise ValueError(
                f"thrash_threshold must be >= 0 (0 disables), got "
                f"{thrash_threshold}"
            )
        if thrash_window_s <= 0:
            raise ValueError(
                f"thrash_window_s must be > 0, got {thrash_window_s}"
            )
        self.thrash_threshold = thrash_threshold
        self.thrash_window_s = thrash_window_s
        self.tracer = tracer
        self._clock = _clock
        self._lock = threading.Lock()
        # process-lifetime totals
        self.compiles = 0
        self.compile_ms = 0.0
        # per-drain window (engine tick stamps)
        self._win_compiles = 0
        self._win_ms = 0.0
        # tumbling thrash window
        self._thrash_t0 = _clock()
        self._thrash_count = 0
        self._thrash_fired = False
        self.thrash_events = 0
        self._listener = None
        self._trace_counts = None
        self._trace_counts_seen = 0

    # ---------------------------------------------------------- install

    def install(self) -> bool:
        """Register the ``jax.monitoring`` duration listener.  Returns
        False when the API is unavailable (use ``attach_trace_counts``
        then).  Idempotent."""
        if self._listener is not None:
            return True
        try:
            import jax.monitoring as monitoring

            register = monitoring.register_event_duration_secs_listener
        except (ImportError, AttributeError):
            return False

        def listener(event, duration, **kwargs):
            if _COMPILE_EVENT in event:
                self.on_compile(duration)

        register(listener)
        self._listener = listener
        return True

    def uninstall(self) -> None:
        """Best-effort deregistration (the public API has no remove;
        tests install/uninstall repeatedly and must not stack
        listeners)."""
        if self._listener is None:
            return
        try:
            from jax._src import monitoring as priv

            priv._unregister_event_duration_listener_by_callback(
                self._listener
            )
        except Exception:
            pass  # listener stays but self-filters nothing further
        self._listener = None

    def attach_trace_counts(self, counts: dict) -> None:
        """Fallback source: a dict of Python-side jit trace counters
        (``serving/engine.TRACE_COUNTS``-shaped) polled at each drain —
        new traces count as compiles with unknown (0) duration."""
        self._trace_counts = counts
        self._trace_counts_seen = sum(counts.values())

    # ------------------------------------------------------------- feed

    def on_compile(self, duration_s: float) -> None:
        """One backend compile of ``duration_s`` seconds."""
        now = self._clock()
        fire_attrs = None
        with self._lock:
            ms = float(duration_s) * 1000.0
            self.compiles += 1
            self.compile_ms += ms
            self._win_compiles += 1
            self._win_ms += ms
            if self.thrash_threshold > 0:
                if now - self._thrash_t0 >= self.thrash_window_s:
                    # tumbling window rollover: re-arm
                    self._thrash_t0 = now
                    self._thrash_count = 0
                    self._thrash_fired = False
                self._thrash_count += 1
                if (self._thrash_count > self.thrash_threshold
                        and not self._thrash_fired):
                    self._thrash_fired = True
                    self.thrash_events += 1
                    fire_attrs = dict(
                        compiles=self._thrash_count,
                        threshold=self.thrash_threshold,
                        window_s=self.thrash_window_s,
                        total_compiles=self.compiles,
                    )
        if fire_attrs is not None:
            # outside the lock: the tracer takes its own lock
            self.tracer.event("compile_thrash", **fire_attrs)

    # ------------------------------------------------------------ drain

    def drain(self) -> tuple[int, float]:
        """(compiles, compile_ms) since the previous drain — what the
        engine stamps on this tick's record."""
        if self._trace_counts is not None:
            total = sum(self._trace_counts.values())
            fresh = total - self._trace_counts_seen
            if fresh > 0:
                self._trace_counts_seen = total
                for _ in range(fresh):
                    self.on_compile(0.0)
        with self._lock:
            out = (self._win_compiles, round(self._win_ms, 3))
            self._win_compiles = 0
            self._win_ms = 0.0
            return out

    def summary(self) -> dict:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_ms": round(self.compile_ms, 3),
                "thrash_threshold": self.thrash_threshold,
                "thrash_window_s": self.thrash_window_s,
                "thrash_events": self.thrash_events,
            }

    @classmethod
    def from_config(cls, telemetry,
                    tracer=NULL_TRACER) -> "CompileWatchdog | None":
        """Build from a ``TelemetryConfig``; None when
        ``compile_watchdog`` is off (the engine then stamps nothing —
        byte-stable records)."""
        if not telemetry.compile_watchdog:
            return None
        return cls(
            thrash_threshold=telemetry.compile_thrash_threshold,
            thrash_window_s=telemetry.compile_thrash_window_s,
            tracer=tracer,
        )
