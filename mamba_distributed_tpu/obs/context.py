"""Trace context: one id for one request's journey across the fabric.

A request placed by the router travels router -> replica -> engine ->
chunked prefill -> decode ticks, and (after a failover) may restart on
a different replica.  ``mint_trace_id()`` issues the id that ties all
of those host-side records together: the router (or a solo engine's
scheduler) mints it once per request, every span and ``serving_tick``/
``request`` jsonl record stamps it, and ``obs/export.py`` turns the
stamps into Perfetto flow arrows so one request's path is a single
clickable chain across N replica streams.

Ids are strings, unique across processes (a per-process random nonce)
and ordered within one (a monotone counter), so two replicas in two
OS processes — or two routers in one — can never collide.  Everything
here is host-side bookkeeping: no jax import, no device work.
"""

from __future__ import annotations

import itertools
import os
import secrets

# process-unique prefix: pid (readable in ps/trace UIs) + random salt
# (pids recycle; two runs on one box must not collide in a merged trace)
_PROCESS_NONCE = ""
_COUNTER = itertools.count()  # atomic under the GIL — no lock for next()


def _reseed() -> None:
    """(Re)derive the process nonce and reset the counter — run at
    import AND after fork: a fork-spawned replica worker inherits the
    parent's module state, and continuing from the same nonce+counter
    would mint colliding ids across processes."""
    global _PROCESS_NONCE, _COUNTER
    _PROCESS_NONCE = f"{os.getpid():x}-{secrets.token_hex(3)}"
    _COUNTER = itertools.count()


_reseed()
if hasattr(os, "register_at_fork"):  # absent on non-POSIX
    os.register_at_fork(after_in_child=_reseed)


def mint_trace_id() -> str:
    """A fresh fabric-unique trace id (one per request journey).

    The id is deliberately a bare string, not a context object: the
    propagation convention is one ``trace=<id>`` attr per span /
    ``trace_id`` field per request record / ``traces=[...]`` set per
    tick record, and every writer spells it inline.  Cross-host
    propagation (the ROADMAP's disaggregated prefill/decode item) can
    introduce a richer context type when a process boundary actually
    needs one."""
    return f"{_PROCESS_NONCE}-{next(_COUNTER):04x}"
