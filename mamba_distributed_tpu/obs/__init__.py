"""Unified host-side telemetry: spans, latency histograms, sentinels.

One event vocabulary — a jsonl stream of one-object-per-line records
tagged by ``"kind"`` — shared by the trainer, checkpointing, eval-in-loop
and the serving engine, and consumed by ``scripts/obs_report.py``:

  kind="span"          tracer.py     timed host-side phase (data_load,
                                     train_step, serving_tick, ...)
  kind="event"         tracer.py     point-in-time marker (divergence, ...)
  kind="train"/"val"   utils/metrics MetricsLogger step records
  kind="serving_tick"  utils/metrics ServingMetrics per-tick records
  kind="request"       utils/metrics per-request latency record
                                     (queue-wait, TTFT, ITL histogram)

Everything here is strictly host-side: no device syncs, nothing traced
by jit — enabling telemetry cannot change what XLA compiles (pinned by
tests/test_obs.py trace-count tests).  docs/OBSERVABILITY.md has the
schema and span taxonomy.
"""

from mamba_distributed_tpu.obs.histogram import StreamingHistogram
from mamba_distributed_tpu.obs.sentinel import (
    DivergenceError,
    DivergenceSentinel,
    FlightRecorder,
)
from mamba_distributed_tpu.obs.tracer import (
    NULL_TRACER,
    SpanTracer,
    append_jsonl,
    jsonable,
)

__all__ = [
    "DivergenceError",
    "DivergenceSentinel",
    "FlightRecorder",
    "NULL_TRACER",
    "SpanTracer",
    "StreamingHistogram",
    "append_jsonl",
    "jsonable",
]
