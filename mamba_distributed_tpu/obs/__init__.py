"""Unified host-side telemetry: spans, latency histograms, sentinels.

One event vocabulary — a jsonl stream of one-object-per-line records
tagged by ``"kind"`` — shared by the trainer, checkpointing, eval-in-loop
and the serving engine, and consumed by ``scripts/obs_report.py``:

  kind="span"          tracer.py     timed host-side phase (data_load,
                                     train_step, serving_tick, ...)
  kind="event"         tracer.py     point-in-time marker (divergence,
                                     slo_breach, ...)
  kind="trace_header"  tracer.py     wall-clock epoch of a stream's t=0
                                     (what lets export.py merge streams)
  kind="train"/"val"   utils/metrics MetricsLogger step records
  kind="serving_tick"  utils/metrics ServingMetrics per-tick records
                                     (+ goodput/MFU + live trace ids)
  kind="request"       utils/metrics per-request latency record
                                     (queue-wait, TTFT, ITL histogram,
                                     trace_id)

Request-flow tracing rides the same records: ``context.py`` mints one
trace id per request journey, the serving fabric stamps it everywhere,
``export.py`` merges N streams into one Perfetto-loadable trace with
flow arrows per request, and ``slo.py`` watches rolling-window p95
targets over the finished-request stream.

Everything here is strictly host-side: no device syncs, nothing traced
by jit — enabling telemetry cannot change what XLA compiles (pinned by
tests/test_obs.py trace-count tests).  docs/OBSERVABILITY.md has the
schema and span taxonomy.
"""

from mamba_distributed_tpu.obs.context import mint_trace_id
from mamba_distributed_tpu.obs.export import (
    export_chrome_trace,
    split_pulled_stream,
    to_chrome_trace,
)
from mamba_distributed_tpu.obs.histogram import StreamingHistogram
from mamba_distributed_tpu.obs.slo import SLOMonitor, TickRegressionDetector
from mamba_distributed_tpu.obs.sentinel import (
    DivergenceError,
    DivergenceSentinel,
    FlightRecorder,
)
from mamba_distributed_tpu.obs.tracer import (
    NULL_TRACER,
    SpanTracer,
    append_jsonl,
    jsonable,
)
from mamba_distributed_tpu.obs.watchdog import CompileWatchdog

__all__ = [
    "CompileWatchdog",
    "DivergenceError",
    "DivergenceSentinel",
    "FlightRecorder",
    "NULL_TRACER",
    "SLOMonitor",
    "SpanTracer",
    "StreamingHistogram",
    "TickRegressionDetector",
    "append_jsonl",
    "export_chrome_trace",
    "jsonable",
    "mint_trace_id",
    "split_pulled_stream",
    "to_chrome_trace",
]
