"""Chrome trace-event (Perfetto) export of the span jsonl streams.

``to_chrome_trace`` merges N ``SpanTracer`` streams — one per replica,
plus the router's — into one trace-event JSON document that loads
directly in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``:

  * each input stream becomes one PROCESS track (``pid`` = stream
    index, named after its label/filename), so a 2-replica serving run
    renders as router + replica tracks stacked on a shared axis;
  * spans become complete ("X") slices at absolute wall-clock
    microseconds: every stream's ``trace_header`` record (stamped by
    SpanTracer on its first write) gives the wall time of that
    tracer's t=0, and ``ts = wall_t0 + t_ms`` — the alignment that
    makes cross-process ordering real.  A stream that re-stamps a
    header mid-file (checkpoint-resume rebuilt tracer) re-anchors its
    subsequent records on the new epoch;
  * spans carrying a ``trace`` attr — one request's journey, stamped
    from ``obs/context.py`` ids — are linked with FLOW arrows
    (``s``/``t``/``f`` events sharing one id), so clicking a
    ``serving_route`` slice on the router track highlights the chain
    through that request's prefill/chunk slices on whichever replica
    it landed on.  A disaggregated fabric's ``serving_migrate`` span
    (router track, same trace id) sits between the prefill replica's
    chunk spans and the decode replica's ``serving_resume``, so the
    cross-replica handoff renders as one arrow hop in the same chain
    (docs/SERVING.md "Disaggregated tiers").  A ``serving_tick``
    slice lists its resident
    requests in a ``traces`` attr; the first tick containing a
    request terminates that request's arrow (its first decode tick —
    where TTFT lands).

Host-side post-processing only: no jax import, nothing here runs in a
serving loop.  ``scripts/trace_export.py`` is the CLI.
"""

from __future__ import annotations

import json
import os


def load_jsonl(path: str, bad_lines: list | None = None) -> list[dict]:
    """All parseable records of one stream, in order.  Torn trailing
    lines (crashed writer) are skipped — an export must still come out
    of a post-mortem stream.  Pass ``bad_lines`` to collect the skipped
    raw lines (scripts/obs_report.py warns on their count); this is the
    ONE tolerant jsonl loader every stream consumer shares.

    A byte-capped SpanTracer (``rotate_bytes``) rolls its previous
    generation to ``<path>.1``; when that sibling exists the pair is
    read oldest-first (``.1`` then ``path``) so rotation never hides
    history from a consumer that was handed the live path."""
    records = []
    rolled = path + ".1"
    paths = [rolled, path] if os.path.exists(rolled) else [path]
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    if bad_lines is not None:
                        bad_lines.append(line)
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


_SPAN_META = ("kind", "name", "t_ms", "dur_ms", "depth", "parent", "tid",
              "obs_src")


def split_pulled_stream(records: list[dict]) -> tuple[list[list[dict]],
                                                      list[str]]:
    """Split one merged fabric obs stream into per-origin sub-streams.

    The FabricController's obs drain (``obs_pull``) stamps every record
    it pulls with ``obs_src`` (the origin replica) before appending it
    to ONE merged jsonl — a single file holding interleaved records
    from N worker tracers.  Grouping by ``obs_src`` (order of first
    appearance; untagged records form a ``"local"`` stream — the
    controller's own spans) recovers the per-process streams
    ``to_chrome_trace`` needs: each origin keeps its own
    ``trace_header`` epoch, so alignment and per-process tracks work
    exactly as they do for N separate files.
    """
    order: list[str] = []
    by_src: dict[str, list[dict]] = {}
    for rec in records:
        src = str(rec.get("obs_src", "local"))
        if src not in by_src:
            by_src[src] = []
            order.append(src)
        by_src[src].append(rec)
    return [by_src[s] for s in order], order


def to_chrome_trace(
    streams: list[list[dict]], labels: list[str] | None = None
) -> dict:
    """Merge record streams into one Chrome trace-event document.

    Args:
      streams: one list of jsonl records per input file (``load_jsonl``).
      labels: per-stream process-track names (default ``stream<i>``).

    Returns the trace document: ``{"traceEvents": [...],
    "displayTimeUnit": "ms", "metadata": {...}}``.  Streams without a
    ``trace_header`` fall back to epoch 0 — they still render, but on
    their own (unaligned) clock; ``metadata.unaligned_streams`` counts
    them so the caller can warn.
    """
    labels = labels or []
    events: list[dict] = []
    # per-trace flow chain members: trace_id -> list[(ts_us, event)]
    chains: dict[str, list[tuple[float, dict]]] = {}
    # earliest tick slice containing each trace (terminates its arrow).
    # Resolved on timestamp AFTER all streams load — a failed-over
    # request's true first decode tick must win regardless of the CLI
    # argument order of the replica streams it ran on.
    first_tick: dict[str, tuple[float, dict]] = {}
    unaligned = 0

    for pid, records in enumerate(streams):
        label = labels[pid] if pid < len(labels) else f"stream{pid}"
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        wall_t0_us = None
        for rec in records:
            kind = rec.get("kind")
            if kind == "trace_header":
                wall_t0_us = float(rec.get("wall_t0_s", 0.0)) * 1e6
                continue
            if kind not in ("span", "event"):
                continue  # serving_tick/request/train records carry no t_ms
            if wall_t0_us is None:
                unaligned += 1  # once per headerless stream
                wall_t0_us = 0.0
            ts = wall_t0_us + float(rec.get("t_ms", 0.0)) * 1000.0
            args = {k: v for k, v in rec.items() if k not in _SPAN_META}
            # per-thread tracks: spans of different host threads (async
            # checkpoint vs trainer) overlap un-nested in wall time, so
            # each thread index gets its own tid (0 for headerless /
            # pre-tid streams)
            tid = int(rec.get("tid", 0))
            if kind == "event":
                events.append({"name": rec["name"], "ph": "i", "s": "t",
                               "ts": ts, "pid": pid, "tid": tid,
                               "args": args})
                continue
            ev = {"name": rec["name"], "ph": "X", "ts": ts,
                  "dur": float(rec.get("dur_ms", 0.0)) * 1000.0,
                  "pid": pid, "tid": tid, "args": args}
            events.append(ev)
            trace = rec.get("trace")
            if trace is not None:
                chains.setdefault(str(trace), []).append((ts, ev))
            # a tick's `traces` list terminates each member's chain at
            # its EARLIEST tick only — one arrow into the first decode
            # tick (where TTFT lands), not one per tick of the
            # request's lifetime
            for t in rec.get("traces") or ():
                t = str(t)
                cur = first_tick.get(t)
                if cur is None or ts < cur[0]:
                    first_tick[t] = (ts, ev)

    for t, member in first_tick.items():
        chains.setdefault(t, []).append(member)

    flows = 0
    for trace_id, members in chains.items():
        if len(members) < 2:
            continue
        members.sort(key=lambda m: m[0])
        for i, (ts, ev) in enumerate(members):
            ph = "s" if i == 0 else ("f" if i == len(members) - 1 else "t")
            # the trace id itself is the flow id (the trace-event format
            # accepts string ids) — hashing to an int would reintroduce
            # a collision class that cross-links unrelated requests
            flow = {"name": f"req {trace_id}", "cat": "request", "ph": ph,
                    "id": trace_id, "ts": ts, "pid": ev["pid"],
                    "tid": ev["tid"]}
            if ph == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            events.append(flow)
            flows += 1

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "streams": len(streams),
            "flow_events": flows,
            "linked_requests": sum(1 for m in chains.values() if len(m) >= 2),
            "unaligned_streams": unaligned,
        },
    }


def export_chrome_trace(paths: list[str], out_path: str) -> dict:
    """File-level driver (what scripts/trace_export.py calls): load each
    stream, merge, write ``out_path``.  Returns the document's metadata
    block.

    A file whose records carry ``obs_src`` tags (the controller's
    merged pulled stream) expands into one sub-stream per origin, so a
    single ``--obs-stream`` file renders the same multi-process tracks
    and cross-replica flow arrows as N worker-local files would."""
    streams: list[list[dict]] = []
    labels: list[str] = []
    for p in paths:
        records = load_jsonl(p)
        base = os.path.basename(p)
        if any("obs_src" in r for r in records):
            subs, srcs = split_pulled_stream(records)
            streams.extend(subs)
            labels.extend(f"{base}:{s}" for s in srcs)
        else:
            streams.append(records)
            labels.append(base)
    doc = to_chrome_trace(streams, labels=labels)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return doc["metadata"]
