"""Rolling-window SLO monitor for the serving fabric.

Latency SLOs are attained or breached over *recent* traffic, not the
whole run — a p95 over a million requests hides an hour-long brownout.
``SLOMonitor`` watches every finished request's host-side latency
scalars (the ``"request"`` record the engine already builds: TTFT,
queue-wait, and the per-request ITL histogram) and keeps a rolling
window of the last N per targeted metric:

  * the rolling p95 is recomputed on each arrival (N is small — a
    sort of <= ``window`` floats is host noise);
  * crossing a target emits ONE ``slo_breach`` event record through
    the tracer (and ``slo_recovered`` on the way back) — a state
    transition, not a per-request alarm flood;
  * per-request attainment (did THIS request meet the target) is
    counted for the run-level attainment table
    (``scripts/obs_report.py``).

Targets live on ``TelemetryConfig`` (``slo_ttft_p95_ms`` /
``slo_itl_p95_ms`` / ``slo_queue_wait_p95_ms``, 0 = not targeted;
``slo_window_requests`` sizes the window) — ``from_config`` builds the
monitor, and a ``slo_config`` event stamps the targets into the stream
so the report can compute attainment offline.

Strictly host-side, like everything in obs/: the inputs are scalars
the engine already fetched, so enabling SLO monitoring adds zero
device syncs and zero jit traces (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import math
from collections import deque

from mamba_distributed_tpu.obs.histogram import StreamingHistogram
from mamba_distributed_tpu.obs.tracer import NULL_TRACER

# metric key in the request record -> the target's name on TelemetryConfig
_METRICS = ("ttft_ms", "itl_ms", "queue_wait_ms")


def _p95(window) -> float:
    xs = sorted(window)
    return xs[max(0, math.ceil(0.95 * len(xs)) - 1)]


class SLOMonitor:
    """Rolling-window p95 targets over finished-request latency.

    Args:
      ttft_p95_ms / itl_p95_ms / queue_wait_p95_ms: targets in
        milliseconds; 0 leaves a metric untargeted.
      window: rolling window length in requests (the last N finished
        requests, fabric-wide when one monitor is shared by every
        replica — the router wiring).
      tracer: where ``slo_config``/``slo_breach``/``slo_recovered``
        event records land (an ``obs.SpanTracer``; default off).
    """

    def __init__(self, *, ttft_p95_ms: float = 0.0, itl_p95_ms: float = 0.0,
                 queue_wait_p95_ms: float = 0.0, window: int = 64,
                 tracer=NULL_TRACER):
        targets = {"ttft_ms": ttft_p95_ms, "itl_ms": itl_p95_ms,
                   "queue_wait_ms": queue_wait_p95_ms}
        for name, t in targets.items():
            if t < 0:
                raise ValueError(f"{name} p95 target must be >= 0 "
                                 f"(0 disables), got {t}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.tracer = tracer
        self.targets = {m: t for m, t in targets.items() if t > 0}
        self._windows = {m: deque(maxlen=window) for m in self.targets}
        self._met = {m: 0 for m in self.targets}
        self._seen = {m: 0 for m in self.targets}
        self._in_breach = {m: False for m in self.targets}
        self.breaches = {m: 0 for m in self.targets}
        if self.targets:
            # stamp the targets into the stream so obs_report.py can
            # compute attainment from the request records offline
            tracer.event(
                "slo_config", window=window,
                **{f"{m}_p95_target": t for m, t in self.targets.items()},
            )

    @classmethod
    def from_config(cls, telemetry, tracer=NULL_TRACER) -> "SLOMonitor | None":
        """Build from a ``TelemetryConfig``; None when nothing is
        targeted (the monitor-off fast path costs literally nothing)."""
        if not (telemetry.slo_ttft_p95_ms or telemetry.slo_itl_p95_ms
                or telemetry.slo_queue_wait_p95_ms):
            return None
        return cls(
            ttft_p95_ms=telemetry.slo_ttft_p95_ms,
            itl_p95_ms=telemetry.slo_itl_p95_ms,
            queue_wait_p95_ms=telemetry.slo_queue_wait_p95_ms,
            window=telemetry.slo_window_requests,
            tracer=tracer,
        )

    # --------------------------------------------------------------- feed

    def observe_request(self, record: dict, replica=None) -> None:
        """One finished request (the engine's ``"request"`` record dict).
        ITL is judged on the request's own p95 (from its streaming
        histogram — the record already carries it)."""
        values = {
            "ttft_ms": record.get("ttft_ms"),
            "queue_wait_ms": record.get("queue_wait_ms"),
        }
        if "itl_ms" in self.targets:
            hist = record.get("itl_hist")
            if hist:
                if isinstance(hist, dict):
                    hist = StreamingHistogram.from_dict(hist)
                values["itl_ms"] = hist.percentile(95)
        for metric, target in self.targets.items():
            value = values.get(metric)
            if value is None:
                continue  # e.g. a 1-token request has no ITL
            self._seen[metric] += 1
            if value <= target:
                self._met[metric] += 1
            win = self._windows[metric]
            win.append(value)
            rolling = _p95(win)
            breached = rolling > target
            if breached != self._in_breach[metric]:
                self._in_breach[metric] = breached
                attrs = dict(metric=metric, target=target,
                             p95=round(rolling, 3), window=len(win))
                if replica is not None:
                    attrs["replica"] = replica
                if breached:
                    self.breaches[metric] += 1
                    self.tracer.event("slo_breach", **attrs)
                else:
                    self.tracer.event("slo_recovered", **attrs)

    # ------------------------------------------------------------ roll-up

    def summary(self) -> dict:
        """Attainment + breach state per targeted metric (rendered next
        to the goodput numbers by scripts/obs_report.py)."""
        return {
            "window": self.window,
            "metrics": {
                m: {
                    "target_p95_ms": t,
                    "requests": self._seen[m],
                    "met": self._met[m],
                    "attainment": (
                        round(self._met[m] / self._seen[m], 4)
                        if self._seen[m] else None
                    ),
                    "breaches": self.breaches[m],
                    "in_breach": self._in_breach[m],
                    "rolling_p95_ms": (
                        round(_p95(self._windows[m]), 3)
                        if self._windows[m] else None
                    ),
                }
                for m, t in self.targets.items()
            },
        }
