"""Rolling-window SLO monitor for the serving fabric.

Latency SLOs are attained or breached over *recent* traffic, not the
whole run — a p95 over a million requests hides an hour-long brownout.
``SLOMonitor`` watches every finished request's host-side latency
scalars (the ``"request"`` record the engine already builds: TTFT,
queue-wait, and the per-request ITL histogram) and keeps a rolling
window of the last N per targeted metric:

  * the rolling p95 is recomputed on each arrival (N is small — a
    sort of <= ``window`` floats is host noise);
  * crossing a target emits ONE ``slo_breach`` event record through
    the tracer (and ``slo_recovered`` on the way back) — a state
    transition, not a per-request alarm flood;
  * per-request attainment (did THIS request meet the target) is
    counted for the run-level attainment table
    (``scripts/obs_report.py``).

Targets live on ``TelemetryConfig`` (``slo_ttft_p95_ms`` /
``slo_itl_p95_ms`` / ``slo_queue_wait_p95_ms``, 0 = not targeted;
``slo_window_requests`` sizes the window) — ``from_config`` builds the
monitor, and a ``slo_config`` event stamps the targets into the stream
so the report can compute attainment offline.

Strictly host-side, like everything in obs/: the inputs are scalars
the engine already fetched, so enabling SLO monitoring adds zero
device syncs and zero jit traces (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import math
from collections import deque

from mamba_distributed_tpu.obs.histogram import StreamingHistogram
from mamba_distributed_tpu.obs.tracer import NULL_TRACER

# metric key in the request record -> the target's name on TelemetryConfig
_METRICS = ("ttft_ms", "itl_ms", "queue_wait_ms")


def _p95(window) -> float:
    xs = sorted(window)
    return xs[max(0, math.ceil(0.95 * len(xs)) - 1)]


class SLOMonitor:
    """Rolling-window p95 targets over finished-request latency.

    Args:
      ttft_p95_ms / itl_p95_ms / queue_wait_p95_ms: targets in
        milliseconds; 0 leaves a metric untargeted.
      window: rolling window length in requests (the last N finished
        requests, fabric-wide when one monitor is shared by every
        replica — the router wiring).
      tracer: where ``slo_config``/``slo_breach``/``slo_recovered``
        event records land (an ``obs.SpanTracer``; default off).
    """

    def __init__(self, *, ttft_p95_ms: float = 0.0, itl_p95_ms: float = 0.0,
                 queue_wait_p95_ms: float = 0.0, window: int = 64,
                 tracer=NULL_TRACER):
        targets = {"ttft_ms": ttft_p95_ms, "itl_ms": itl_p95_ms,
                   "queue_wait_ms": queue_wait_p95_ms}
        for name, t in targets.items():
            if t < 0:
                raise ValueError(f"{name} p95 target must be >= 0 "
                                 f"(0 disables), got {t}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.tracer = tracer
        self.targets = {m: t for m, t in targets.items() if t > 0}
        self._windows = {m: deque(maxlen=window) for m in self.targets}
        self._met = {m: 0 for m in self.targets}
        self._seen = {m: 0 for m in self.targets}
        self._in_breach = {m: False for m in self.targets}
        self.breaches = {m: 0 for m in self.targets}
        if self.targets:
            # stamp the targets into the stream so obs_report.py can
            # compute attainment from the request records offline
            tracer.event(
                "slo_config", window=window,
                **{f"{m}_p95_target": t for m, t in self.targets.items()},
            )

    @classmethod
    def from_config(cls, telemetry, tracer=NULL_TRACER) -> "SLOMonitor | None":
        """Build from a ``TelemetryConfig``; None when nothing is
        targeted (the monitor-off fast path costs literally nothing)."""
        if not (telemetry.slo_ttft_p95_ms or telemetry.slo_itl_p95_ms
                or telemetry.slo_queue_wait_p95_ms):
            return None
        return cls(
            ttft_p95_ms=telemetry.slo_ttft_p95_ms,
            itl_p95_ms=telemetry.slo_itl_p95_ms,
            queue_wait_p95_ms=telemetry.slo_queue_wait_p95_ms,
            window=telemetry.slo_window_requests,
            tracer=tracer,
        )

    # --------------------------------------------------------------- feed

    def observe_request(self, record: dict, replica=None) -> None:
        """One finished request (the engine's ``"request"`` record dict).
        ITL is judged on the request's own p95 (from its streaming
        histogram — the record already carries it)."""
        values = {
            "ttft_ms": record.get("ttft_ms"),
            "queue_wait_ms": record.get("queue_wait_ms"),
        }
        if "itl_ms" in self.targets:
            hist = record.get("itl_hist")
            if hist:
                if isinstance(hist, dict):
                    hist = StreamingHistogram.from_dict(hist)
                values["itl_ms"] = hist.percentile(95)
        for metric, target in self.targets.items():
            value = values.get(metric)
            if value is None:
                continue  # e.g. a 1-token request has no ITL
            self._seen[metric] += 1
            if value <= target:
                self._met[metric] += 1
            win = self._windows[metric]
            win.append(value)
            rolling = _p95(win)
            breached = rolling > target
            if breached != self._in_breach[metric]:
                self._in_breach[metric] = breached
                attrs = dict(metric=metric, target=target,
                             p95=round(rolling, 3), window=len(win))
                if replica is not None:
                    attrs["replica"] = replica
                if breached:
                    self.breaches[metric] += 1
                    self.tracer.event("slo_breach", **attrs)
                else:
                    self.tracer.event("slo_recovered", **attrs)

    def any_breach(self) -> bool:
        """True while ANY targeted metric's rolling p95 is in breach —
        the latency half of the autoscaler's pressure signal
        (serving/autoscale/controller.py reads it every tick; a bool
        read, no recompute)."""
        return any(self._in_breach.values())

    # ------------------------------------------------------------ roll-up

    def summary(self) -> dict:
        """Attainment + breach state per targeted metric (rendered next
        to the goodput numbers by scripts/obs_report.py)."""
        return {
            "window": self.window,
            "metrics": {
                m: {
                    "target_p95_ms": t,
                    "requests": self._seen[m],
                    "met": self._met[m],
                    "attainment": (
                        round(self._met[m] / self._seen[m], 4)
                        if self._seen[m] else None
                    ),
                    "breaches": self.breaches[m],
                    "in_breach": self._in_breach[m],
                    "rolling_p95_ms": (
                        round(_p95(self._windows[m]), 3)
                        if self._windows[m] else None
                    ),
                }
                for m, t in self.targets.items()
            },
        }


class TickRegressionDetector:
    """EWMA-baseline regression sentinel over engine tick latency.

    The SLOMonitor above judges ABSOLUTE targets the operator set; this
    detector needs no target at all — it learns the engine's own
    steady-state tick latency as an exponentially-weighted moving
    average and raises when ticks run a configured factor slower than
    that baseline.  An ITL degradation (a recompile storm, a noisy
    neighbor, a fragmenting page pool) becomes an *event* the moment it
    starts, not a bump discovered in a histogram after the run.

    Same transition discipline as the SLO monitor: one
    ``tick_regression`` event record when the smoothed latency crosses
    ``factor x baseline``, one ``tick_recovered`` when it comes back —
    never a per-tick alarm flood.  The baseline FREEZES while in
    breach (a regression must not teach the baseline that slow is
    normal); it resumes adapting on recovery.

    Args:
      factor: breach when smoothed tick ms > factor * baseline (must
        be > 1; ``from_config`` returns None when the config's factor
        is 0 = off).
      alpha: EWMA weight of the newest tick for the FAST signal.
      baseline_alpha: EWMA weight for the (out-of-breach) baseline;
        must be meaningfully smaller than ``alpha`` or the baseline
        tracks the fast signal and a breach can never open.  Defaults
        to ``alpha / 10``.
      warmup: ticks observed before judging starts — the first ticks
        pay compiles and cache fills and would poison the baseline.
      tracer: where the event records land.
    """

    def __init__(self, *, factor: float = 2.0, alpha: float = 0.1,
                 baseline_alpha: float | None = None,
                 warmup: int = 32, tracer=NULL_TRACER):
        if factor <= 1.0:
            raise ValueError(
                f"regression factor must be > 1 (breach = factor x "
                f"baseline), got {factor}"
            )
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if baseline_alpha is None:
            baseline_alpha = alpha / 10.0
        if not 0.0 < baseline_alpha < alpha:
            raise ValueError(
                f"baseline_alpha must be in (0, alpha={alpha}) so the "
                f"baseline lags the fast signal, got {baseline_alpha}"
            )
        if warmup < 1:
            raise ValueError(f"warmup must be >= 1, got {warmup}")
        self.factor = factor
        self.alpha = alpha
        self.baseline_alpha = baseline_alpha
        self.warmup = warmup
        self.tracer = tracer
        self.baseline_ms: float | None = None
        self.smoothed_ms: float | None = None
        self.ticks = 0
        self.breaches = 0
        self.in_breach = False

    @classmethod
    def from_config(cls, telemetry,
                    tracer=NULL_TRACER) -> "TickRegressionDetector | None":
        """Build from a ``TelemetryConfig``; None when
        ``tick_regression_factor`` is 0 (off — costs nothing)."""
        if not telemetry.tick_regression_factor:
            return None
        return cls(
            factor=telemetry.tick_regression_factor,
            alpha=telemetry.tick_ewma_alpha,
            warmup=telemetry.tick_regression_warmup,
            tracer=tracer,
        )

    def observe_tick(self, tick_ms: float, replica=None) -> None:
        """One engine tick's wall milliseconds."""
        if not math.isfinite(tick_ms) or tick_ms < 0:
            return  # telemetry never throws on a bad input
        self.ticks += 1
        a = self.alpha
        if self.smoothed_ms is None:
            self.smoothed_ms = tick_ms
        else:
            self.smoothed_ms = (1 - a) * self.smoothed_ms + a * tick_ms
        if self.ticks <= self.warmup:
            self.baseline_ms = self.smoothed_ms
            return
        if not self.in_breach:
            b = self.baseline_alpha
            self.baseline_ms = (1 - b) * self.baseline_ms + b * tick_ms
        breached = self.smoothed_ms > self.factor * self.baseline_ms
        if breached != self.in_breach:
            self.in_breach = breached
            attrs = dict(metric="tick_ms", factor=self.factor,
                         baseline_ms=round(self.baseline_ms, 3),
                         smoothed_ms=round(self.smoothed_ms, 3))
            if replica is not None:
                attrs["replica"] = replica
            if breached:
                self.breaches += 1
                self.tracer.event("tick_regression", **attrs)
            else:
                self.tracer.event("tick_recovered", **attrs)

    def summary(self) -> dict:
        r = lambda v: None if v is None else round(v, 3)
        return {
            "ticks": self.ticks,
            "baseline_ms": r(self.baseline_ms),
            "smoothed_ms": r(self.smoothed_ms),
            "factor": self.factor,
            "breaches": self.breaches,
            "in_breach": self.in_breach,
        }
