"""Streaming bounded-bucket histogram: percentiles without storing samples.

Latency percentiles (p50/p95/p99 of queue-wait, TTFT, inter-token
latency) must survive millions of requests, so samples cannot be kept.
``StreamingHistogram`` keeps a fixed array of geometrically-spaced bucket
counts — the HDR-histogram idea at its minimum: with growth factor ``g``
every recorded value lands in a bucket whose edges are within a factor
``g`` of it, so any percentile is reported with relative error at most
``g - 1`` (and exactly at the observed min/max, which are tracked and
clamp the estimate).

Histograms with identical bucket geometry merge by adding counts —
percentiles of the merged histogram are the percentiles of the combined
stream (tests/test_obs.py pins monotonicity under merges).  ``to_dict``/
``from_dict`` round-trip the sparse bucket counts through JSON so a
per-request histogram can ride in a jsonl record and be re-merged by
``scripts/obs_report.py``.
"""

from __future__ import annotations

import math


class StreamingHistogram:
    """Fixed-memory histogram over ``[lo, hi)`` with geometric buckets.

    Defaults cover 1 microsecond to ~17 minutes when recording
    milliseconds, at <= ~19% relative error (growth 2**0.25), in 100
    buckets.  Values below ``lo`` / at or above ``hi`` land in underflow/
    overflow buckets and are still reported exactly at the stream min/max.
    """

    def __init__(self, lo: float = 1e-3, hi: float = 1e6,
                 growth: float = 2 ** 0.25):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError(f"need 0 < lo < hi and growth > 1, got "
                             f"lo={lo}, hi={hi}, growth={growth}")
        self.lo, self.hi, self.growth = lo, hi, growth
        self._log_g = math.log(growth)
        self.n_buckets = int(math.ceil(math.log(hi / lo) / self._log_g))
        # [underflow] + n_buckets geometric + [overflow]
        self.counts = [0] * (self.n_buckets + 2)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    # ------------------------------------------------------------- recording

    def record(self, value: float, n: int = 1) -> None:
        """Add ``n`` observations of ``value``.  Non-finite values are
        dropped (a telemetry path must never throw on a diverged input)."""
        if n < 1 or not math.isfinite(value):
            return
        self.counts[self._index(value)] += n
        self.count += n
        self.total += value * n
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)

    def _index(self, value: float) -> int:
        if value < self.lo:
            return 0
        if value >= self.hi:
            return self.n_buckets + 1
        i = int(math.log(value / self.lo) / self._log_g)
        return 1 + min(max(i, 0), self.n_buckets - 1)

    def _edges(self, index: int) -> tuple[float, float]:
        """(low, high) value edges of a slot in ``counts``."""
        if index == 0:
            return (0.0, self.lo)
        if index == self.n_buckets + 1:
            return (self.hi, self.hi)
        return (self.lo * self.growth ** (index - 1),
                self.lo * self.growth ** index)

    # ----------------------------------------------------------- percentiles

    def percentile(self, q: float) -> float | None:
        """Estimate the q-th percentile (q in [0, 100]); None when empty.

        Nearest-rank bucket walk with linear interpolation inside the
        bucket, clamped to the observed [min, max] — so a single-sample
        histogram reports that sample exactly at every q."""
        if self.count == 0:
            return None
        if not 0 <= q <= 100:
            raise ValueError(f"q must be in [0, 100], got {q}")
        target = max(1, math.ceil(q / 100 * self.count))
        seen = 0
        for index, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                if index == 0:
                    # below-lo values have no bucket resolution; the
                    # observed min is the only honest point estimate
                    return self.vmin
                if index == self.n_buckets + 1:
                    return self.vmax
                b_lo, b_hi = self._edges(index)
                frac = (target - seen) / c
                value = b_lo + frac * (b_hi - b_lo)
                return min(max(value, self.vmin), self.vmax)
            seen += c
        return self.vmax  # unreachable unless float drift; be safe

    @property
    def mean(self) -> float | None:
        return self.total / self.count if self.count else None

    # -------------------------------------------------------- merge / io

    def _same_geometry(self, other: "StreamingHistogram") -> bool:
        return (self.lo == other.lo and self.hi == other.hi
                and self.growth == other.growth)

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Fold ``other``'s observations into self (in place)."""
        if not self._same_geometry(other):
            raise ValueError(
                f"cannot merge histograms with different bucket geometry: "
                f"({self.lo}, {self.hi}, {self.growth}) vs "
                f"({other.lo}, {other.hi}, {other.growth})"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        return self

    def to_dict(self) -> dict:
        """JSON-ready sparse form (bucket index -> count)."""
        return {
            "lo": self.lo, "hi": self.hi, "growth": self.growth,
            "count": self.count, "total": round(self.total, 6),
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StreamingHistogram":
        h = cls(lo=d["lo"], hi=d["hi"], growth=d["growth"])
        for i, c in d["counts"].items():
            h.counts[int(i)] = int(c)
        h.count = int(d["count"])
        h.total = float(d["total"])
        if d.get("min") is not None:
            h.vmin = float(d["min"])
        if d.get("max") is not None:
            h.vmax = float(d["max"])
        return h

    def summary(self) -> dict:
        """The roll-up ServingMetrics.summary() embeds per metric."""
        r = lambda v: None if v is None else round(v, 3)
        return {
            "count": self.count,
            "mean": r(self.mean),
            "p50": r(self.percentile(50)),
            "p95": r(self.percentile(95)),
            "p99": r(self.percentile(99)),
            "max": r(self.vmax if self.count else None),
        }
