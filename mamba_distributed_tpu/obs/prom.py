"""Prometheus text exposition for the serving fabric — stdlib only.

The fabric's live telemetry plane (docs/OBSERVABILITY.md "Live
telemetry plane") is a pull surface: ``GET /metrics`` on the HTTP
front end renders every replica's ``ServingMetrics`` roll-up — plus
the controller's own fabric gauges — in the Prometheus text format
(version 0.0.4), one scrape target for the whole fabric.  A worker
can additionally expose itself directly (``scripts/serve_worker.py
--metrics-port``) so per-host scrapers keep working when the front
end is down.

Three layers, all pure functions over plain dicts so the wire payload
(`summary` RPC: summary + full histogram dicts + live stats) renders
without touching engine objects:

- ``MetricFamily`` + ``render()``: the exposition encoder.  Counters,
  gauges and histograms; label values escaped per the format spec
  (``\\``, ``\"``, ``\n``); histogram buckets are CUMULATIVE with a
  terminal ``+Inf`` bucket and the ``_sum``/``_count`` pair, derived
  from ``StreamingHistogram.to_dict()``'s sparse geometric counts.
- ``replica_families()`` / ``fabric_families()``: the fabric's metric
  schema — every name emitted here must appear in the
  docs/OBSERVABILITY.md metric table (``scripts/check_metrics_schema.py``
  is the drift gate, mirroring bench_gate).
- ``parse_exposition()``: a minimal parser for the same format —
  enough for the round-trip unit tests and the schema gate; not a
  general Prometheus client.

Counters here are process-lifetime totals re-read from each replica's
metrics object at scrape time (the Prometheus counter contract:
monotonic within one worker boot; a worker restart resets them, which
scrapers detect as a counter reset).
"""

from __future__ import annotations

import math

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# metric name prefix shared by every family the fabric emits
PREFIX = "mamba_"

_VALID_TYPES = ("counter", "gauge", "histogram")


def escape_label_value(value) -> str:
    """Escape a label value per the text-format spec: backslash, double
    quote and newline are the only escaped characters."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(value) -> str:
    if value is None:
        return "NaN"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _format_sample(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
        )
        return f"{name}{{{body}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


class MetricFamily:
    """One named metric family: a type, a help line, N labeled samples."""

    def __init__(self, name: str, mtype: str, help: str):
        if mtype not in _VALID_TYPES:
            raise ValueError(f"metric type must be one of {_VALID_TYPES}, "
                             f"got {mtype!r}")
        self.name = name
        self.mtype = mtype
        self.help = help
        # list of (suffix, labels, value): suffix "" for plain samples,
        # "_bucket"/"_sum"/"_count" for histogram series
        self.samples: list[tuple[str, dict, object]] = []

    def add(self, value, **labels) -> "MetricFamily":
        """Add one sample (counters/gauges)."""
        if self.mtype == "histogram":
            raise ValueError(f"{self.name} is a histogram; use "
                             f"add_histogram()")
        self.samples.append(("", labels, value))
        return self

    def add_histogram(self, hist: dict, **labels) -> "MetricFamily":
        """Add one histogram from ``StreamingHistogram.to_dict()`` form.

        Buckets are emitted cumulatively at the geometric upper edges
        that actually hold counts, closed by the mandatory ``+Inf``
        bucket — sparse but valid: any quantile estimate over the
        emitted edges matches one over the full edge set because the
        omitted buckets hold zero observations.
        """
        if self.mtype != "histogram":
            raise ValueError(f"{self.name} is a {self.mtype}; "
                             f"add_histogram() needs a histogram family")
        lo = float(hist["lo"])
        growth = float(hist["growth"])
        hi = float(hist["hi"])
        n_buckets = int(math.ceil(math.log(hi / lo) / math.log(growth)))
        counts = {int(i): int(c) for i, c in hist.get("counts", {}).items()}
        cum = 0
        for index in sorted(counts):
            cum += counts[index]
            if index == 0:
                le = lo
            elif index >= n_buckets + 1:
                le = math.inf  # overflow bucket only closes at +Inf
            else:
                le = lo * growth ** index
            if math.isinf(le):
                continue  # folded into the terminal +Inf bucket below
            self.samples.append(
                ("_bucket", {**labels, "le": _format_value(le)}, cum))
        total = int(hist.get("count", 0))
        self.samples.append(("_bucket", {**labels, "le": "+Inf"}, total))
        self.samples.append(("_sum", dict(labels), float(hist.get("total",
                                                                  0.0))))
        self.samples.append(("_count", dict(labels), total))
        return self


def render(families: list[MetricFamily]) -> str:
    """Render families to one exposition document (trailing newline)."""
    lines: list[str] = []
    for fam in families:
        if not fam.samples:
            continue
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.mtype}")
        for suffix, labels, value in fam.samples:
            lines.append(_format_sample(fam.name + suffix, labels, value))
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# the fabric's metric schema
# --------------------------------------------------------------------------

def _fam(name, mtype, help) -> MetricFamily:
    return MetricFamily(PREFIX + name, mtype, help)


def replica_families(snapshots: list[dict]) -> list[MetricFamily]:
    """Render per-replica snapshots into the replica-level families.

    Each snapshot: ``{"replica": id, "role": str, "summary": dict,
    "histograms": {name: to_dict()}, "stats": dict}`` — exactly the
    worker ``summary`` RPC payload plus the controller's replica/role
    labels.  Missing feature blocks (``kv_pages`` None, no compile
    watchdog, no histograms shipped) simply emit nothing — the same
    off-means-absent contract the jsonl records keep.
    """
    ticks = _fam("ticks_total", "counter", "Engine ticks executed.")
    dtok = _fam("decode_tokens_total", "counter",
                "Decode tokens sampled (all slots).")
    tps = _fam("decode_tokens_per_sec", "gauge",
               "Decode tokens per wall second over the metrics window.")
    tickms = _fam("tick_ms_mean", "gauge", "Mean engine tick wall ms.")
    occ = _fam("slot_occupancy", "gauge",
               "Mean fraction of slots occupied per tick.")
    qdepth = _fam("queue_depth", "gauge",
                  "Requests queued (admitted, not yet resident).")
    resident = _fam("slots_resident", "gauge", "Slots currently resident.")
    cap = _fam("slot_capacity", "gauge", "Slot capacity S.")
    fin = _fam("finished_requests_total", "counter",
               "Requests finished (all finish reasons).")
    preempt = _fam("preemptions_total", "counter",
                   "Priority preemptions (slot evicted to host RAM).")
    mig_out = _fam("migrations_out_total", "counter",
                   "Streams migrated off this replica.")
    mig_in = _fam("migrations_in_total", "counter",
                  "Streams migrated onto this replica.")
    kv_used = _fam("kv_pages_used", "gauge", "Hybrid KV pages in use.")
    kv_cap = _fam("kv_pages_capacity", "gauge", "Hybrid KV page capacity.")
    kv_peak = _fam("kv_pages_peak_used", "gauge",
                   "Peak hybrid KV pages in use.")
    kv_allocs = _fam("kv_page_allocs_total", "counter",
                     "Hybrid KV page allocations.")
    kv_frees = _fam("kv_page_frees_total", "counter",
                    "Hybrid KV page frees.")
    useful = _fam("goodput_useful_fraction", "gauge",
                  "Useful fraction of computed token lanes.")
    gtps = _fam("goodput_tokens_per_sec", "gauge",
                "Useful tokens per wall second.")
    mfu = _fam("serving_mfu", "gauge",
               "Model FLOPs utilization of the serving window.")
    compiles = _fam("compiles_total", "counter",
                    "XLA backend compiles observed by the watchdog.")
    compile_ms = _fam("compile_ms_total", "counter",
                      "Wall ms spent in XLA backend compiles.")
    # online adapter tuning (serving/tuning/): None-gated on
    # summary()["tuning"] exactly like the kv/compile blocks — a fabric
    # with no tuning plane renders byte-identically to before
    quota_stalls = _fam("tenant_quota_stalls_total", "counter",
                        "Admissions deferred by the per-tenant "
                        "fairness quota (requeued, not shed).")
    hot_swaps = _fam("adapter_hot_swaps_total", "counter",
                     "Live streams switched adapter versions "
                     "mid-flight (carry invalidated once).")
    tune_jobs = _fam("tune_jobs_total", "counter",
                     "Tune-job lifecycle transitions, by state "
                     "(submitted/completed/failed).")
    tune_steps = _fam("tune_train_steps_total", "counter",
                      "Masked LoRA train steps run on trainer lanes.")
    tune_deploys = _fam("tune_deploys_total", "counter",
                        "Converged adapter versions hot-registered "
                        "fabric-wide.")
    tune_yields = _fam("tune_yields_total", "counter",
                       "Training slices yielded to serving pressure "
                       "(SLO breach).")
    tune_loss = _fam("tune_last_loss", "gauge",
                     "Most recent tune step's mean loss.")
    hists = {
        "queue_wait_ms": _fam("queue_wait_ms", "histogram",
                              "Per-request queue wait (admission to "
                              "slot), ms."),
        "ttft_ms": _fam("ttft_ms", "histogram",
                        "Per-request time to first token, ms."),
        "itl_ms": _fam("itl_ms", "histogram",
                       "Per-request inter-token latency, ms."),
        "tune_step_ms": _fam("tune_step_ms", "histogram",
                             "Per-step LoRA train wall time, ms "
                             "(shipped only when tuning is live)."),
    }
    for snap in snapshots:
        if not snap:
            continue
        labels = {"replica": snap.get("replica"),
                  "role": snap.get("role", "mixed")}
        s = snap.get("summary") or {}
        ticks.add(s.get("ticks", 0), **labels)
        dtok.add(s.get("decode_tokens", 0), **labels)
        if s.get("decode_tokens_per_sec") is not None:
            tps.add(s["decode_tokens_per_sec"], **labels)
        if s.get("mean_tick_ms") is not None:
            tickms.add(s["mean_tick_ms"], **labels)
        if s.get("mean_slot_occupancy") is not None:
            occ.add(s["mean_slot_occupancy"], **labels)
        fin.add(s.get("finished_requests", 0), **labels)
        preempt.add(s.get("preemptions", 0), **labels)
        mig = s.get("migrations") or {}
        mig_out.add(mig.get("out", 0), **labels)
        mig_in.add(mig.get("in", 0), **labels)
        stats = snap.get("stats") or {}
        if stats.get("depth") is not None:
            qdepth.add(stats["depth"], **labels)
        elif s.get("mean_queue_depth") is not None:
            qdepth.add(s["mean_queue_depth"], **labels)
        if stats.get("resident") is not None:
            resident.add(stats["resident"], **labels)
        if stats.get("capacity") is not None:
            cap.add(stats["capacity"], **labels)
        kv = s.get("kv_pages")
        if kv:
            kv_used.add(kv.get("used", 0), **labels)
            kv_cap.add(kv.get("capacity", 0), **labels)
            kv_peak.add(kv.get("peak_used", 0), **labels)
            kv_allocs.add(kv.get("allocs", 0), **labels)
            kv_frees.add(kv.get("frees", 0), **labels)
        good = s.get("goodput") or {}
        if good.get("useful_fraction") is not None:
            useful.add(good["useful_fraction"], **labels)
        if good.get("goodput_tokens_per_sec") is not None:
            gtps.add(good["goodput_tokens_per_sec"], **labels)
        if good.get("serving_mfu") is not None:
            mfu.add(good["serving_mfu"], **labels)
        comp = s.get("compile")
        if comp:
            compiles.add(comp.get("compiles", 0), **labels)
            compile_ms.add(comp.get("compile_ms", 0.0), **labels)
        tun = s.get("tuning")
        if tun:
            quota_stalls.add(tun.get("quota_stalls", 0), **labels)
            hot_swaps.add(tun.get("hot_swaps", 0), **labels)
            for state in ("submitted", "completed", "failed"):
                tune_jobs.add(tun.get(f"jobs_{state}", 0),
                              **labels, state=state)
            tune_steps.add(tun.get("train_steps", 0), **labels)
            tune_deploys.add(tun.get("deploys", 0), **labels)
            tune_yields.add(tun.get("yields", 0), **labels)
            if tun.get("last_loss") is not None:
                tune_loss.add(tun["last_loss"], **labels)
        for key, fam in hists.items():
            h = (snap.get("histograms") or {}).get(key)
            if h:
                fam.add_histogram(h, **labels)
    return [ticks, dtok, tps, tickms, occ, qdepth, resident, cap, fin,
            preempt, mig_out, mig_in, kv_used, kv_cap, kv_peak, kv_allocs,
            kv_frees, useful, gtps, mfu, compiles, compile_ms,
            quota_stalls, hot_swaps, tune_jobs, tune_steps, tune_deploys,
            tune_yields, tune_loss, *hists.values()]


def fabric_families(*, replicas: int, accepting: int, ready: bool,
                    obs_records_pulled: int | None = None,
                    obs_records_dropped: int | None = None,
                    queue_depth: int | None = None,
                    sheds: dict | None = None,
                    autoscale: dict | None = None,
                    tune_queue_depth: int | None = None
                    ) -> list[MetricFamily]:
    """The controller's own fabric-level gauges (no replica label).
    ``queue_depth``/``sheds``/``autoscale`` are None-gated like the obs
    counters: a fabric without admission control or an autoscaler
    renders byte-identically to the pre-elastic exposition."""
    fams = [
        _fam("fabric_replicas", "gauge",
             "Replicas registered with the router.").add(replicas),
        _fam("fabric_replicas_accepting", "gauge",
             "Replicas currently accepting work.").add(accepting),
        _fam("fabric_ready", "gauge",
             "1 when at least one replica accepts work "
             "(the /healthz readiness bit).").add(1 if ready else 0),
    ]
    if obs_records_pulled is not None:
        fams.append(_fam("fabric_obs_records_pulled_total", "counter",
                         "Span/event records drained off worker obs "
                         "rings.").add(obs_records_pulled))
    if obs_records_dropped is not None:
        fams.append(_fam("fabric_obs_records_dropped_total", "counter",
                         "Ring records that aged out before a pull "
                         "(cursor gaps).").add(obs_records_dropped))
    if queue_depth is not None:
        fams.append(_fam("fabric_queue_depth", "gauge",
                         "Queued-but-unstarted requests fabric-wide "
                         "(what the admission cap bounds).")
                    .add(queue_depth))
    if sheds is not None:
        fam = _fam("fabric_admission_sheds_total", "counter",
                   "Requests shed at the front door, by reason "
                   "(AdmissionRejected -> HTTP 429).")
        for reason in ("queue_cap", "queue_deadline"):
            fam.add(sheds.get(reason, 0), reason=reason)
        fams.append(fam)
    if autoscale is not None:
        fams += [
            _fam("fabric_autoscale_scale_ups_total", "counter",
                 "Replicas live-attached by the autoscaler.")
            .add(autoscale.get("scale_ups", 0)),
            _fam("fabric_autoscale_scale_downs_total", "counter",
                 "Replicas drained for retirement by the autoscaler.")
            .add(autoscale.get("scale_downs", 0)),
        ]
    if tune_queue_depth is not None:
        fams.append(_fam("fabric_tune_queue_depth", "gauge",
                         "Unfinished tune jobs (active + queued) on "
                         "the fabric's tuning plane.")
                    .add(tune_queue_depth))
    return fams


def render_fabric(snapshots: list[dict], **fabric_kw) -> str:
    """One fabric-wide exposition document: fabric gauges + replicas."""
    return render(fabric_families(**fabric_kw) + replica_families(snapshots))


# --------------------------------------------------------------------------
# minimal parser (tests + scripts/check_metrics_schema.py)
# --------------------------------------------------------------------------

def _unescape_label_value(raw: str) -> str:
    out, i = [], 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> dict:
    labels, i = {}, 0
    while i < len(body):
        if body[i] in ", ":
            i += 1
            continue
        eq = body.index("=", i)
        key = body[i:eq].strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        raw = []
        while body[j] != '"':
            if body[j] == "\\":
                raw.append(body[j:j + 2])
                j += 2
            else:
                raw.append(body[j])
                j += 1
        labels[key] = _unescape_label_value("".join(raw))
        i = j + 1
    return labels


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)


def parse_exposition(text: str) -> dict:
    """Parse an exposition document into families.

    Returns ``{family_name: {"type": str, "help": str, "samples":
    [(sample_name, labels_dict, value), ...]}}`` — histogram series
    (``_bucket``/``_sum``/``_count``) group under their base family.
    Strict enough to round-trip everything ``render()`` emits; raises
    ValueError on lines it cannot parse (the schema gate wants loud
    failure, not silent omission).
    """
    families: dict[str, dict] = {}
    types: dict[str, str] = {}

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if types.get(base) == "histogram":
                    return base
        return sample_name

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            families.setdefault(
                name, {"type": None, "help": "", "samples": []}
            )["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            name, _, mtype = line[len("# TYPE "):].partition(" ")
            mtype = mtype.strip()
            if mtype not in _VALID_TYPES:
                raise ValueError(f"unknown metric type {mtype!r} for "
                                 f"{name}")
            families.setdefault(
                name, {"type": None, "help": "", "samples": []}
            )["type"] = mtype
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue  # other comments are legal
        if "{" in line:
            name = line[: line.index("{")]
            rest = line[line.index("{") + 1:]
            close = rest.rindex("}")
            labels = _parse_labels(rest[:close])
            value = _parse_value(rest[close + 1:].strip())
        else:
            name, _, raw = line.partition(" ")
            labels = {}
            value = _parse_value(raw.strip())
        fam = family_of(name)
        families.setdefault(
            fam, {"type": None, "help": "", "samples": []}
        )["samples"].append((name, labels, value))
    return families
