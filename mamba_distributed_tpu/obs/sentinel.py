"""Divergence sentinels + crash-time flight recorder.

A diverged run's most valuable artifact is the last N steps *before* the
loss went non-finite — after it, every record is NaN noise.  So the
trainer feeds each step's already-fetched host scalars (loss, grad norm
— fetched anyway for logging, so the sentinel adds zero device syncs)
into a bounded ring buffer, and the moment a non-finite value appears —
or the loop dies on any exception — the ring dumps to
``flight_record.json`` (the crash-time state-dump practice of
pjit-at-scale training, PAPERS.md "Scalable Training of Language Models
using JAX pjit and TPUv4").

The opt-in *on-device* counterpart (TelemetryConfig.overflow_threshold)
lives in training/train_step.py: the compiled step additionally returns
an int32 overflow flag computed from the global grad norm, fused into
the one existing jit — opting in swaps the compiled step, it never adds
a second trace.
"""

from __future__ import annotations

import json
import math
import os
from collections import deque

from mamba_distributed_tpu.obs.tracer import NULL_TRACER, jsonable


class DivergenceError(RuntimeError):
    """Raised by the trainer when the sentinel sees a non-finite loss or
    grad norm and ``telemetry.halt_on_divergence`` is set."""


class FlightRecorder:
    """Bounded ring buffer of recent telemetry events.

    ``record()`` is O(1) and allocation-light; ``dump()`` writes the
    whole ring plus the dump reason as one JSON document.  Capacity is
    small by design — the point is the last-moments picture, not a log.
    """

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._events: deque[dict] = deque(maxlen=capacity)

    def record(self, kind: str, **fields) -> None:
        self._events.append({"kind": kind, **fields})

    def events(self) -> list[dict]:
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)

    def dump(self, path: str, reason: str) -> str:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        doc = {
            "reason": reason,
            "capacity": self.capacity,
            "events": [jsonable(e) for e in self._events],
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return path


class DivergenceSentinel:
    """Host-side non-finite watchdog feeding a FlightRecorder.

    ``observe_step`` takes scalars the trainer has ALREADY fetched —
    it must never be handed a jax.Array that would force a sync.
    Returns True when the step is non-finite, after dumping the flight
    record (first trigger only; a crashed run dumps once).

    ``dump_path=None`` watches without writing — multi-host trainers
    give every process a sentinel (all must halt on divergence) but only
    the master a dump path, so a shared log dir is written once.
    """

    def __init__(self, dump_path: str | None, capacity: int = 64,
                 tracer=NULL_TRACER):
        self.dump_path = dump_path
        self.flight = FlightRecorder(capacity)
        self.tracer = tracer
        self.overflow_count = 0  # host accumulator of on-device flags
        self.dumped_to: str | None = None

    def observe_step(self, step: int, loss: float, grad_norm: float,
                     overflow: int | None = None, **extra) -> bool:
        record = {"step": step, "loss": loss, "grad_norm": grad_norm}
        record.update(extra)
        if overflow is not None and overflow:
            self.overflow_count += int(overflow)
            record["overflow"] = int(overflow)
            record["overflow_total"] = self.overflow_count
        self.flight.record("train_step", **record)
        diverged = not (math.isfinite(loss) and math.isfinite(grad_norm))
        if diverged:
            self.tracer.event("divergence", step=step, loss=loss,
                              grad_norm=grad_norm)
            self.dump(f"non-finite loss/grad_norm at step {step} "
                      f"(loss={loss}, grad_norm={grad_norm})")
        return diverged

    def record_event(self, kind: str, **fields) -> None:
        """Feed a non-step event (val loss, checkpoint save, ...) into
        the ring so the dump shows the run's recent shape, not just the
        train steps."""
        self.flight.record(kind, **fields)

    def on_crash(self, exc: BaseException) -> None:
        """Dump on any loop-killing exception (unless divergence already
        dumped — the DivergenceError path would otherwise overwrite the
        reason with its own traceback)."""
        self.dump(f"crash: {type(exc).__name__}: {exc}")

    def dump(self, reason: str) -> str | None:
        if self.dump_path is not None and self.dumped_to is None:
            self.dumped_to = self.flight.dump(self.dump_path, reason)
        return self.dumped_to
