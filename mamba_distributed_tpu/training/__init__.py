"""Training subsystem: optimizer, jitted train step, trainer loop."""

from mamba_distributed_tpu.training.optimizer import lr_schedule, make_optimizer
from mamba_distributed_tpu.training.train_step import (
    make_eval_step,
    make_train_step,
)
from mamba_distributed_tpu.training.trainer import Trainer

__all__ = [
    "lr_schedule",
    "make_optimizer",
    "make_train_step",
    "make_eval_step",
    "Trainer",
]
