"""Optimizer factory: AdamW with dim>=2 decay mask + warmup/cosine schedule.

Reproduces the reference recipe exactly:
  * AdamW betas=(0.9, 0.95), eps=1e-8 (/root/reference/model.py:146-148)
  * weight decay 0.1 applied only to params with ndim >= 2 — matmul weights
    and embeddings decay, biases/norms/dt/A/D don't (model.py:126-131)
  * global-norm clip 1.0 (train.py:222)
  * LR: linear warmup over 715 steps — note the reference's (it+1)/warmup
    off-by-one — then cosine from 6e-4 to 10% over 19,073 steps, constant
    min_lr beyond (train.py:97-110)

XLA fuses the whole optax update into a couple of kernels — the TPU
equivalent of torch's fused AdamW (model.py:142-147).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import optax

from mamba_distributed_tpu.config import TrainConfig


def lr_schedule(cfg: TrainConfig):
    max_lr = cfg.max_lr
    min_lr = cfg.max_lr * cfg.min_lr_ratio
    warmup = cfg.warmup_steps
    max_steps = cfg.max_steps

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = max_lr * (step + 1.0) / warmup
        decay_ratio = jnp.clip((step - warmup) / (max_steps - warmup), 0.0, 1.0)
        coeff = 0.5 * (1.0 + jnp.cos(math.pi * decay_ratio))
        cos = min_lr + coeff * (max_lr - min_lr)
        return jnp.where(step < warmup, warm, jnp.where(step > max_steps, min_lr, cos))

    return schedule


def decay_mask(params):
    """True for every parameter the reference decays: per-layer ndim >= 2
    (reference model.py:126).

    Layer-stacked block params (under "blocks"/"attn_blocks" from the
    scan-over-layers layout) carry a leading n_layer axis that does not
    count toward the rule — a stacked norm weight (L, d) is still a 1-D
    parameter per layer and must not decay.
    """
    import jax.tree_util as jtu

    def leaf_mask(path, p):
        names = {getattr(k, "key", None) for k in path}
        stacked = "blocks" in names or "attn_blocks" in names
        return jnp.ndim(p) - (1 if stacked else 0) >= 2

    return jtu.tree_map_with_path(leaf_mask, params)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(
            learning_rate=lr_schedule(cfg),
            b1=cfg.adam_b1,
            b2=cfg.adam_b2,
            eps=cfg.adam_eps,
            weight_decay=cfg.weight_decay,
            mask=decay_mask,
        ),
    )
