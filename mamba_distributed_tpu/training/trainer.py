"""Training driver: the reference's train.py loop, TPU-native.

Covers /root/reference/train.py:128-244 — grad-accum training loop,
validation every ``val_every`` steps, reference-format text logging,
periodic checkpointing — with the DDP/NCCL runtime replaced by a
`jax.sharding.Mesh` + jitted step (XLA collectives over ICI/DCN), and
exact resume (params + optimizer + loader position + RNG) that the
reference lacks (train.py:161-162).

Multi-host: each TPU-VM host is one loader "process" (rank-strided shards,
reference dataloader.py:38), and `jax.make_array_from_process_local_data`
assembles the global batch; single-host this degenerates to a device_put.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import TrainConfig
from mamba_distributed_tpu.data import ShardedTokenLoader, ensure_synthetic_shards
from mamba_distributed_tpu.models import count_params, init_lm_params
from mamba_distributed_tpu.obs import (
    NULL_TRACER,
    DivergenceError,
    DivergenceSentinel,
    SpanTracer,
)
from mamba_distributed_tpu.parallel.mesh import build_mesh
from mamba_distributed_tpu.parallel.sharding import (
    batch_sharding,
    param_shardings,
)
from mamba_distributed_tpu.training.optimizer import lr_schedule, make_optimizer
from mamba_distributed_tpu.training.train_step import make_eval_step, make_train_step
from mamba_distributed_tpu.utils.flops import flops_per_token, peak_flops_per_chip
from mamba_distributed_tpu.utils.metrics import MetricsLogger


class Trainer:
    def __init__(
        self,
        cfg: TrainConfig,
        devices=None,
        verbose: bool = True,
        sample_prompt_ids=None,
        decode_fn=None,
    ):
        self.cfg = cfg
        self.mesh = build_mesh(cfg.mesh, devices)
        self.master = jax.process_index() == 0
        self.verbose = verbose and self.master

        if cfg.mesh.seq > 1:
            from mamba_distributed_tpu.parallel.seq_parallel import SeqContext

            batch_axes = (
                ("data", "fsdp", "expert") if cfg.mesh.expert > 1
                else ("data", "fsdp")
            )
            self.seq_ctx = SeqContext(self.mesh, "seq", batch_axes)
        else:
            self.seq_ctx = None

        # --- data (synthetic fallback per DataConfig.allow_synthetic;
        # ensure_synthetic_shards is idempotent when shards exist) ---
        data_dir = cfg.data.data_dir
        if cfg.data.allow_synthetic:
            ensure_synthetic_shards(
                data_dir,
                vocab_size=cfg.model.vocab_size,
                tokens_per_shard=cfg.data.synthetic_tokens_per_shard,
                num_shards=cfg.data.synthetic_num_shards,
                seed=cfg.seed,
            )
        dp = cfg.data_parallel_size
        nproc = jax.process_count()
        assert (cfg.micro_batch_size * dp) % nproc == 0
        self.rows_per_host = cfg.micro_batch_size * dp // nproc
        loader_args = dict(
            B=self.rows_per_host,
            T=cfg.seq_len,
            data_dir=data_dir,
            process_rank=jax.process_index(),
            num_processes=nproc,
            master_process=self.verbose,
        )
        self.train_loader = ShardedTokenLoader(split="train", **loader_args)
        self.val_loader = ShardedTokenLoader(split="val", **loader_args)

        # --- model: init directly into the sharded layout ---
        self.rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_key = jax.random.split(self.rng)
        shapes = jax.eval_shape(lambda k: init_lm_params(k, cfg.model), init_key)
        pshard = param_shardings(shapes, self.mesh, cfg.shard_params)
        self.params = jax.jit(
            lambda k: init_lm_params(k, cfg.model), out_shardings=pshard
        )(init_key)
        if self.verbose:
            n = count_params(self.params)
            print(f"model params: {n:,} (analytic {cfg.model.num_params():,})")

        # --- optimizer (moments inherit param shardings, scalars replicate) ---
        from mamba_distributed_tpu.parallel.sharding import opt_state_shardings

        self.optimizer = make_optimizer(cfg)
        opt_shapes = jax.eval_shape(self.optimizer.init, self.params)
        oshard = opt_state_shardings(opt_shapes, shapes, pshard, self.mesh)
        self.opt_state = jax.jit(self.optimizer.init, out_shardings=oshard)(
            self.params
        )
        self.schedule = lr_schedule(cfg)

        # --- telemetry (obs/): spans + divergence sentinel, host-side only.
        # The tracer/sentinel never see a jax.Array that is not already
        # fetched, so enabling them cannot add device syncs or jit traces
        # (pinned by tests/test_obs.py).
        tcfg = cfg.telemetry
        self.tracer = (
            SpanTracer(os.path.join(cfg.log_dir, "events.jsonl"))
            if tcfg.spans and self.master else NULL_TRACER
        )
        self.sentinel = (
            DivergenceSentinel(
                # every process watches (all must halt together on a
                # divergence); only the master writes the shared dump
                os.path.join(cfg.log_dir, "flight_record.json")
                if self.master else None,
                capacity=tcfg.flight_recorder_len, tracer=self.tracer,
            )
            if tcfg.sentinel else None
        )
        self._overflow_on = tcfg.overflow_threshold > 0

        self.train_step = make_train_step(
            cfg, self.optimizer, self.mesh, self.params, self.opt_state,
            seq_ctx=self.seq_ctx,
            overflow_threshold=(
                tcfg.overflow_threshold if self._overflow_on else None
            ),
        )
        self.eval_step = make_eval_step(
            cfg, self.mesh, self.params, seq_ctx=self.seq_ctx
        )
        self.bshard = batch_sharding(self.mesh, seq_sharded=self.seq_ctx is not None)

        self.logger = MetricsLogger(cfg.log_dir, self.verbose)
        self.step = 0
        self._ckpt = None  # async Checkpointer, created on first save
        self._ckpt_dir = None
        # in-training sampling (reference train.py:166-199): every
        # sample_every steps generate 4 continuations of the prompt.
        # Token ids are injected (no tokenizer download in zero-egress
        # environments); decode_fn, if given, renders them as text.
        self._sample_prompt_ids = sample_prompt_ids
        self._decode_fn = decode_fn
        self._flops_per_token = flops_per_token(cfg.model, cfg.seq_len)
        self._flops_per_token_model = flops_per_token(
            cfg.model, cfg.seq_len, convention="model"
        )
        self._peak = peak_flops_per_chip() * self.mesh.devices.size

    # ------------------------------------------------------------------

    def _global_batch(self, accum: int, loader) -> tuple[jax.Array, jax.Array]:
        xs, ys = [], []
        for _ in range(accum):
            x, y = loader.next_batch()
            xs.append(x)
            ys.append(y)
        x = np.stack(xs)  # (accum, B_local, T)
        y = np.stack(ys)
        # leading accum axis replicated; batch (and maybe seq) axes sharded
        from jax.sharding import NamedSharding, PartitionSpec as P

        ashard = NamedSharding(self.mesh, P(None, *self.bshard.spec))
        make = lambda arr: jax.make_array_from_process_local_data(ashard, arr)
        return make(x), make(y)

    def _val_batch(self):
        x, y = self.val_loader.next_batch()
        make = lambda arr: jax.make_array_from_process_local_data(self.bshard, arr)
        return make(x), make(y)

    def validate(self) -> float:
        with self.tracer.span("eval", steps=self.cfg.val_steps):
            self.val_loader.reset()
            total = 0.0
            for _ in range(self.cfg.val_steps):
                x, y = self._val_batch()
                total += float(self.eval_step(self.params, x, y))
        return total / self.cfg.val_steps

    def run(self, max_steps: int | None = None, checkpoint_dir: str | None = None):
        cfg = self.cfg
        accum = cfg.grad_accum_steps
        tokens_per_step = cfg.total_batch_size
        last = min(max_steps if max_steps is not None else cfg.max_steps, cfg.max_steps)

        try:
            self._run_loop(last, accum, tokens_per_step, checkpoint_dir)
        except BaseException as e:
            # crash-time flight dump: the last N steps before death are
            # the artifact that matters (a DivergenceError path already
            # dumped with the non-finite reason; dump() is once-only)
            if self.sentinel is not None:
                self.sentinel.on_crash(e)
            raise
        finally:
            # join any in-flight async checkpoint write even when the loop
            # raises (a checkpoint must never outlive the process
            # half-written after save() reported success)
            if self._ckpt is not None:
                self._ckpt.wait()
        return self

    def _run_loop(self, last, accum, tokens_per_step, checkpoint_dir):
        cfg = self.cfg
        while self.step < last:
            step = self.step
            if step % cfg.val_every == 0 or step == last - 1:
                val_loss = self.validate()
                self.logger.val(step, val_loss)
                if self.sentinel is not None:
                    self.sentinel.record_event("val", step=step, loss=val_loss)
            if (
                self._sample_prompt_ids is not None
                and step % cfg.sample_every == 0
                and step > 0
            ):
                with self.tracer.span("sample", step=step):
                    self.sample()
            if checkpoint_dir and step > 0 and step % cfg.checkpoint_every == 0:
                self.save_checkpoint(checkpoint_dir)

            t0 = time.time()
            with self.tracer.span("data_load", step=step):
                x, y = self._global_batch(accum, self.train_loader)
            with self.tracer.span("train_step", step=step):
                out = self.train_step(self.params, self.opt_state, x, y)
                self.params, self.opt_state, loss, grad_norm = out[:4]
                jax.block_until_ready(loss)
            dt = time.time() - t0
            # host scalars, fetched once: the logger and the sentinel both
            # consume these — the sentinel adds zero extra device syncs
            loss_f, grad_norm_f = float(loss), float(grad_norm)
            overflow = int(out[4]) if self._overflow_on else None
            tok_per_sec = tokens_per_step / dt
            mfu = self._flops_per_token_model * tok_per_sec / self._peak
            mfu_hw = self._flops_per_token * tok_per_sec / self._peak
            self.logger.train_step(
                step, loss_f, float(self.schedule(step)), grad_norm_f,
                dt, tok_per_sec, mfu, mfu_hw,
            )
            if self.sentinel is not None and self.sentinel.observe_step(
                step, loss_f, grad_norm_f, overflow=overflow,
                step_ms=round(dt * 1000, 2),
            ):
                if cfg.telemetry.halt_on_divergence:
                    where = (self.sentinel.dumped_to
                             or "written by process 0")  # non-master has
                    raise DivergenceError(  # no dump path of its own
                        f"non-finite loss/grad_norm at step {step} "
                        f"(loss={loss_f}, grad_norm={grad_norm_f}); flight "
                        f"record: {where}"
                    )
            self.step += 1

    def sample(self, num_return: int = 4, max_new_tokens: int = 32,
               top_k: int = 50):
        """Generate continuations like the reference's in-loop sampling
        (4 sequences x 32 tokens, top-k 50, train.py:170-175) — but with
        O(1) recurrent decode instead of full-prefix re-forwards."""
        import numpy as np

        from mamba_distributed_tpu.inference import generate

        prompt = jnp.asarray(self._sample_prompt_ids, jnp.int32)[None, :]
        prompt = jnp.tile(prompt, (num_return, 1))
        self.rng, key = jax.random.split(self.rng)
        out = generate(
            self.params, self.cfg.model, prompt, key,
            max_new_tokens=max_new_tokens, top_k=top_k,
        )
        if self.verbose:
            for row in np.asarray(out):
                text = (
                    self._decode_fn(row.tolist()) if self._decode_fn
                    else f"tokens {row.tolist()}"
                )
                print(f"sample: {text}")
        return out

    # --- checkpointing (training/checkpoint.py; full-state, exact resume;
    # async: the write overlaps the next training steps) ---

    def save_checkpoint(self, directory: str) -> None:
        from mamba_distributed_tpu.training.checkpoint import Checkpointer

        if self._ckpt is None or self._ckpt_dir != directory:
            if self._ckpt is not None:
                self._ckpt.close()
            self._ckpt = Checkpointer(directory)
            self._ckpt_dir = directory
        # the span covers the async dispatch (on-device snapshot), not the
        # background write — that's the cost the training loop actually pays
        with self.tracer.span("checkpoint_save", step=self.step):
            self._ckpt.save(
                self.step, self.params, self.opt_state,
                self.train_loader.state(), self.rng,
            )
        if self.sentinel is not None:
            self.sentinel.record_event("checkpoint_save", step=self.step)

    def finish(self) -> None:
        """Join any in-flight async checkpoint write (call before exit)."""
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None

    def restore_checkpoint(self, directory: str, step: int | None = None) -> None:
        from mamba_distributed_tpu.training.checkpoint import restore_checkpoint

        if self._ckpt is not None:
            self._ckpt.wait()  # never restore past an uncommitted write

        self.step, self.params, self.opt_state, loader_state, self.rng = (
            restore_checkpoint(directory, self.params, self.opt_state, step)
        )
        self.train_loader.restore(loader_state)
        self.logger.preserve_history()
        self.tracer.preserve_history()
