"""Jitted train/eval steps with gradient accumulation.

One ``jax.jit`` covers the whole reference inner loop
(/root/reference/train.py:205-227): the micro-batch loop is a ``lax.scan``
over the leading accum axis, gradient averaging replaces DDP's allreduce
(XLA inserts the psum from the batch sharding), clip + AdamW update run
fused on-device.  Params/optimizer buffers are donated, so the step is
in-place at the HBM level.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mamba_distributed_tpu.config import TrainConfig
from mamba_distributed_tpu.models import lm_loss
from mamba_distributed_tpu.models.lm import lm_loss_pipelined
from mamba_distributed_tpu.parallel.sharding import batch_sharding

# Python-side-effect trace counters (one bump per jit trace), same idiom
# as serving/engine.py — tests/test_obs.py pins that enabling host-side
# telemetry (spans + sentinels) leaves these unchanged.
TRACE_COUNTS = {"train_step": 0, "eval_step": 0}


def make_train_step(
    cfg: TrainConfig,
    optimizer: optax.GradientTransformation,
    mesh,
    params,
    opt_state,
    seq_ctx=None,
    overflow_threshold: float | None = None,
    freeze=None,
    params_map=None,
):
    """Build the compiled train step.

    Shardings are read off the already-placed ``params``/``opt_state`` so
    the step preserves them exactly (and donates the buffers).

    Returns ``step(params, opt_state, x, y) ->
    (params, opt_state, loss, grad_norm)`` with x/y (accum, B_global, T).

    ``overflow_threshold`` (TelemetryConfig) appends an int32 overflow
    flag to the outputs: 1 when the pre-clip global grad norm exceeds the
    threshold or is non-finite.  It is fused into the one existing jit —
    the sentinel's on-device half costs no extra trace and no extra
    launch; the host accumulates the flags into a counter
    (obs/sentinel.py).

    ``freeze`` (a pytree of bools matching ``params``; None = train
    everything, the exact status quo) splices the ORIGINAL frozen
    leaves back after ``apply_updates`` — the partial-fine-tune path
    (online LoRA tuning, serving/tuning/trainer.py).  The caller's
    masked optimizer (``optax.multi_transform`` + ``set_to_zero``)
    already produces zero updates for frozen leaves; the splice turns
    "adds 0.0" into "bit-identical" (a +0.0 rewrite would flip any
    -0.0 base weight's sign bit, breaking the frozen-base contract).

    ``params_map`` (pure tree->tree function; None = identity) is
    applied to the param tree INSIDE the loss, at trace time, before
    the forward.  Gradients flow through it to the original leaves,
    while anything it splices in (e.g. the constant adapter-id vector
    ``bind_adapter_ids`` adds for the LoRA delta path) stays a closed-
    over constant rather than a differentiated — and int-dtype —
    argument leaf.  Non-pipelined losses only (tuning never runs with
    ``mesh.pipe > 1``).
    """
    model_cfg = cfg.model

    def loss_fn(p, x, y):
        if params_map is not None:
            p = params_map(p)
        return lm_loss(p, model_cfg, x, y, seq_ctx=seq_ctx)

    pipe = cfg.mesh.pipe
    if pipe > 1 and model_cfg.loss_impl == "blocked":
        # lm_loss_pipelined runs the dense head; failing loudly beats
        # silently losing the memory saving the flag was set for
        raise NotImplementedError(
            "loss_impl='blocked' is not implemented for pipeline "
            "parallelism (mesh.pipe > 1) — use the dense loss there"
        )

    def step_fn(params, opt_state, x, y):
        TRACE_COUNTS["train_step"] += 1
        accum = x.shape[0]
        if pipe > 1:
            # GPipe: the accum microbatches stream through the pipeline
            # in ONE differentiable schedule — no lax.scan accumulation.
            # Composes with data parallelism: each (data, fsdp) replica
            # runs the schedule on its batch slice
            dp_axes = ("data", "fsdp") if cfg.data_parallel_size > 1 else None
            loss, grads = jax.value_and_grad(
                lambda p, x, y: lm_loss_pipelined(
                    p, model_cfg, x, y, mesh, batch_axes=dp_axes
                )
            )(params, x, y)
        elif accum == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, x[0], y[0])
        else:
            def micro(carry, xs):
                gsum, lsum = carry
                xb, yb = xs
                l, g = jax.value_and_grad(loss_fn)(params, xb, yb)
                return (jax.tree.map(jnp.add, gsum, g), lsum + l), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (gsum, lsum), _ = jax.lax.scan(micro, (zeros, 0.0), (x, y))
            grads = jax.tree.map(lambda g: g / accum, gsum)
            loss = lsum / accum
        grad_norm = optax.global_norm(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if freeze is not None:
            new_params = jax.tree.map(
                lambda frozen, new, old: old if frozen else new,
                freeze, new_params, params,
            )
        params = new_params
        if overflow_threshold is not None:
            overflow = jnp.int32(
                ~jnp.isfinite(grad_norm) | (grad_norm > overflow_threshold)
            )
            return params, opt_state, loss, grad_norm, overflow
        return params, opt_state, loss, grad_norm

    pshard = jax.tree.map(lambda a: a.sharding, params)
    oshard = jax.tree.map(lambda a: a.sharding, opt_state)
    bshard = batch_sharding(mesh, seq_sharded=seq_ctx is not None)
    # batches carry a leading (replicated) grad-accum axis
    ashard = NamedSharding(mesh, P(None, *bshard.spec))
    scalars = (None, None, None) if overflow_threshold is not None else (None, None)
    return jax.jit(
        step_fn,
        in_shardings=(pshard, oshard, ashard, ashard),
        out_shardings=(pshard, oshard, *scalars),
        donate_argnums=(0, 1),
    )


def make_eval_step(cfg: TrainConfig, mesh, params, seq_ctx=None):
    """Compiled loss-only step, x/y (B_global, T)."""
    model_cfg = cfg.model

    def eval_fn(params, x, y):
        TRACE_COUNTS["eval_step"] += 1
        return lm_loss(params, model_cfg, x, y, seq_ctx=seq_ctx)

    pshard = jax.tree.map(lambda a: a.sharding, params)
    bshard = batch_sharding(mesh, seq_sharded=seq_ctx is not None)
    return jax.jit(eval_fn, in_shardings=(pshard, bshard, bshard))
