"""Full-state checkpointing via Orbax: params + optimizer + loader + RNG.

The reference saves model-only every 1,000 steps and cannot resume
(/root/reference/train.py:152-163, acknowledged in-code at 161-162).  Here
a checkpoint restores the *exact* training trajectory: restoring and
stepping reproduces the same losses bit-for-bit (pinned by
tests/test_training.py).  Sharded arrays save/restore distributed-aware
through Orbax's TypeHandlers — each host writes its own shards.
"""

from __future__ import annotations

import os

import jax
import numpy as np
import orbax.checkpoint as ocp


def _manager(directory: str) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
    )


class Checkpointer:
    """Async checkpointing: save() returns as soon as the on-device state
    is snapshotted; the write proceeds in Orbax's background thread while
    training continues.  ``wait()`` (or close()) joins the last write —
    the trainer calls it before the process exits so no checkpoint is
    ever truncated.  The function-level save_checkpoint below stays fully
    synchronous for one-shot use.
    """

    def __init__(self, directory: str):
        self._mngr = _manager(directory)

    def save(self, step, params, opt_state, loader_state, rng) -> None:
        state = {
            "params": params,
            "opt_state": opt_state,
            "loader": {k: np.asarray(v) for k, v in loader_state.items()},
            "rng": rng,
            "step": np.asarray(step),
        }
        self._mngr.save(step, args=ocp.args.StandardSave(state))

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def save_checkpoint(directory, step, params, opt_state, loader_state, rng) -> None:
    """One-shot synchronous save (delegates to Checkpointer)."""
    ckpt = Checkpointer(directory)
    try:
        ckpt.save(step, params, opt_state, loader_state, rng)
    finally:
        ckpt.close()


def restore_params_only(directory: str, step: int | None = None):
    """Restore just the model params from a full-state checkpoint (eval/
    inference don't need optimizer, loader, or RNG state)."""
    mngr = _manager(directory)
    if step is None:
        step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
    # no target tree: orbax restores the on-disk structure as numpy
    restored = mngr.restore(step, args=ocp.args.StandardRestore())
    mngr.close()
    return restored["params"]


def restore_checkpoint(directory, params_like, opt_state_like, step=None):
    """Restore into the shardings/dtypes of the given abstract targets."""
    mngr = _manager(directory)
    if step is None:
        step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
    target = {
        "params": params_like,
        "opt_state": opt_state_like,
        "loader": {
            "current_shard": np.asarray(0),
            "current_position": np.asarray(0),
        },
        "rng": jax.random.PRNGKey(0),
        "step": np.asarray(0),
    }
    restored = mngr.restore(step, args=ocp.args.StandardRestore(target))
    mngr.close()
    loader_state = {k: int(v) for k, v in restored["loader"].items()}
    return (
        int(restored["step"]),
        restored["params"],
        restored["opt_state"],
        loader_state,
        restored["rng"],
    )
