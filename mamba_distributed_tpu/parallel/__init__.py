"""Device-mesh parallelism: mesh construction, sharding rules, sequence
parallelism, ring attention, pipeline prototype.

Replaces the reference's NCCL/DDP runtime (/root/reference/train.py:27,86,
221) with XLA SPMD: shardings on a `jax.sharding.Mesh` drive compiler-
inserted collectives over ICI/DCN; explicit `shard_map`+`ppermute` only
where control matters (sequence-parallel state passing, ring attention,
the pipelined layer schedule).
"""

from mamba_distributed_tpu.parallel.mesh import build_mesh
from mamba_distributed_tpu.parallel.pipeline import pipelined_layers
from mamba_distributed_tpu.parallel.sharding import (
    batch_sharding,
    param_shardings,
    shard_params,
)

__all__ = [
    "build_mesh",
    "batch_sharding",
    "param_shardings",
    "pipelined_layers",
    "shard_params",
]
