"""Sequence/context parallelism for the SSD path (BASELINE config 4).

The SSM analogue of ring attention (SURVEY.md §5 long-context plan): the
sequence axis is sharded over the mesh's ``seq`` axis; each device runs
the chunked SSD on its local tokens, and only the tiny (b, h, p, n)
boundary states cross devices — O(d_state) traffic instead of O(T).

Mechanics (explicit `shard_map`, because the state recurrence has a
direction XLA's sharding propagation can't infer):

  * conv halo: each device ppermutes its last (width-1) inputs to the next
    device, which uses them as ``initial_state`` — exactly the decode-cache
    hook `ops/conv.py` exposes.
  * SSD state passing: each device computes its local per-chunk states and
    a (decay, final_state) summary; summaries are all-gathered over the seq
    axis (S entries of (b,h)+(b,h,p,n) — tiny), every device combines the
    prefix before it into its incoming state, and re-runs the local
    associative state pass seeded with it.

Both transforms are exact: sharded output == single-device output to fp32
tolerance (pinned by tests/test_seq_parallel.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mamba_distributed_tpu.ops.conv import causal_conv1d
from mamba_distributed_tpu.ops.ssd import (
    chunk_local,
    combine_chunk_outputs,
    state_passing,
)


@dataclasses.dataclass(frozen=True)
class SeqContext:
    """Carries the mesh and axis names the sequence-sharded ops run over.

    ``batch_axes`` must match how the caller shards the batch dimension
    (the trainer's batch sharding: ('data', 'fsdp')).
    """

    mesh: Mesh
    axis: str = "seq"
    batch_axes: tuple[str, ...] = ("data", "fsdp")

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]


def _shifted(ctx: SeqContext, x: jax.Array) -> jax.Array:
    """Value from the previous seq rank (zeros into rank 0)."""
    n = ctx.size
    perm = [(i, i + 1) for i in range(n - 1)]
    return jax.lax.ppermute(x, ctx.axis, perm)


def sp_conv1d(
    ctx: SeqContext,
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None,
    activation: str | None = "silu",
):
    """Causal depthwise conv with a (width-1)-token halo exchange.

    x (b, t_global, d) with t sharded over ``ctx.axis``.
    Returns (y, None) — the decode conv state is meaningless under SP.
    """
    width = weight.shape[1]
    bat = P(ctx.batch_axes, ctx.axis, None)
    has_bias = bias is not None

    def local(x_l, w, *rest):
        b = rest[0] if has_bias else None
        halo = None
        if width > 1:  # width=1 needs no halo (and -(width-1) would slice badly)
            assert x_l.shape[1] >= width - 1, (
                f"local sequence shard ({x_l.shape[1]}) shorter than the "
                f"conv halo ({width - 1})"
            )
            halo = _shifted(ctx, x_l[:, -(width - 1) :, :])
        return causal_conv1d(x_l, w, b, activation=activation, initial_state=halo)

    in_specs = (bat, P(None, None)) + ((P(None),) if has_bias else ())
    fn = jax.shard_map(
        local, mesh=ctx.mesh, in_specs=in_specs, out_specs=bat, check_vma=False
    )
    args = (x, weight) + ((bias,) if has_bias else ())
    return fn(*args), None


def sp_ssd(
    ctx: SeqContext,
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int,
    D: jax.Array | None = None,
    compute_dtype=jnp.bfloat16,
):
    """Sequence-sharded chunked SSD.

    Shapes as ops/ssd.ssd_chunked: x (b, t, h, p), dt (b, t, h),
    B/C (b, t, g, n), with t sharded over ``ctx.axis``.
    Returns (y, None) — the final state stays on the last shard.
    """
    from mamba_distributed_tpu.ops.scan import _divisor_chunk

    bat3 = P(ctx.batch_axes, ctx.axis, None)
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)
    has_D = D is not None

    def local(x_l, dt_l, A_, B_l, C_l, *rest):
        D_ = rest[0] if has_D else None
        b, t_l, h, p = x_l.shape
        l = _divisor_chunk(t_l, chunk_size)
        y_diag, states, chunk_decay, off_ctx = chunk_local(
            x_l, dt_l, A_, B_l, C_l, l, compute_dtype
        )
        # local pass to get this shard's summary
        _, final_local = state_passing(states, chunk_decay)
        decay_total = jnp.prod(chunk_decay, axis=1)  # (b, h)

        # gather (decay_total, final_local) from every seq rank
        n = ctx.size
        idx = jax.lax.axis_index(ctx.axis)
        decays = jax.lax.all_gather(decay_total, ctx.axis)  # (S, b, h)
        finals = jax.lax.all_gather(final_local, ctx.axis)  # (S, b, h, p, n)

        # incoming state = sum over ranks j < idx of final_j * prod_{j<m<idx} decay_m
        ranks = jnp.arange(n)
        # suffix[j] = prod over m with j < m < idx of decays[m]
        def suffix_prod(j):
            mask = ((ranks > j) & (ranks < idx)).astype(decays.dtype)
            return jnp.prod(
                decays * mask[:, None, None] + (1.0 - mask)[:, None, None], axis=0
            )

        suffixes = jax.vmap(suffix_prod)(ranks)  # (S, b, h)
        contrib_mask = (ranks < idx).astype(decays.dtype)  # (S,)
        s_in = jnp.sum(
            finals
            * (suffixes * contrib_mask[:, None, None])[..., None, None],
            axis=0,
        )  # (b, h, p, n)

        # local pass seeded with the incoming state, then the shared
        # output assembly (ops/ssd.combine_chunk_outputs)
        prev_states, _ = state_passing(states, chunk_decay, initial_state=s_in)
        return combine_chunk_outputs(
            y_diag, off_ctx, prev_states, x_l, D_, compute_dtype
        )

    in_specs = (bat4, bat3, P(None), bat4, bat4)
    if has_D:
        in_specs += (P(None, None) if D.ndim == 2 else P(None),)
    fn = jax.shard_map(
        local, mesh=ctx.mesh, in_specs=in_specs, out_specs=bat4, check_vma=False
    )
    args = (x, dt, A, B, C) + ((D,) if has_D else ())
    return fn(*args), None
