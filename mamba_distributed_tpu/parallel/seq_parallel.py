"""Sequence/context parallelism for the SSD path (BASELINE config 4).

The SSM analogue of ring attention (SURVEY.md §5 long-context plan): the
sequence axis is sharded over the mesh's ``seq`` axis; each device runs
the chunked SSD on its local tokens, and only the tiny (b, h, p, n)
boundary states cross devices — O(d_state) traffic instead of O(T).

Mechanics (explicit `shard_map`, because the state recurrence has a
direction XLA's sharding propagation can't infer):

  * conv halo: each device ppermutes its last (width-1) inputs to the next
    device, which uses them as ``initial_state`` — exactly the decode-cache
    hook `ops/conv.py` exposes.
  * SSD state passing: each device computes its local per-chunk states and
    a (decay, final_state) summary; an exclusive prefix scan over the seq
    axis (log2(S) distance-doubling ppermute rounds, O(d_state) traffic
    each) hands every device its incoming state, and the local associative
    state pass re-runs seeded with it.

Both transforms are exact: sharded output == single-device output to fp32
tolerance (pinned by tests/test_seq_parallel.py).

Compute/communication overlap (SURVEY §7 hard-part 3): the expensive
intra-chunk work — the Gram/decay matmuls behind ``y_diag`` and the
off-diagonal context — has no data dependence on the cross-device state
exchange (only the cheap final ``combine_chunk_outputs`` consumes both),
so the XLA scheduler is free to run the ppermute chain concurrently with
the local matmuls; nothing in the program order forces the exchange onto
the critical path.  Whether the scheduler actually hides the (tiny,
O(d_state)) exchange is a hardware-profile question — measure with
``scripts/profile_step.py`` on a seq-sharded config before tuning
further.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mamba_distributed_tpu.parallel.compat import shard_map
from mamba_distributed_tpu.ops.conv import causal_conv1d
from mamba_distributed_tpu.ops.ssd import (
    chunk_local,
    combine_chunk_outputs,
    cumsum_mxu,
    state_passing,
)


@dataclasses.dataclass(frozen=True)
class SeqContext:
    """Carries the mesh and axis names the sequence-sharded ops run over.

    ``batch_axes`` must match how the caller shards the batch dimension
    (the trainer's batch sharding: ('data', 'fsdp')).
    """

    mesh: Mesh
    axis: str = "seq"
    batch_axes: tuple[str, ...] = ("data", "fsdp")

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]


def _shifted(ctx: SeqContext, x: jax.Array) -> jax.Array:
    """Value from the previous seq rank (zeros into rank 0)."""
    n = ctx.size
    perm = [(i, i + 1) for i in range(n - 1)]
    return jax.lax.ppermute(x, ctx.axis, perm)


def sp_conv1d(
    ctx: SeqContext,
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None,
    activation: str | None = "silu",
):
    """Causal depthwise conv with a (width-1)-token halo exchange.

    x (b, t_global, d) with t sharded over ``ctx.axis``.
    Returns (y, None) — the decode conv state is meaningless under SP.
    """
    width = weight.shape[1]
    bat = P(ctx.batch_axes, ctx.axis, None)
    has_bias = bias is not None

    def local(x_l, w, *rest):
        b = rest[0] if has_bias else None
        halo = None
        if width > 1:  # width=1 needs no halo (and -(width-1) would slice badly)
            assert x_l.shape[1] >= width - 1, (
                f"local sequence shard ({x_l.shape[1]}) shorter than the "
                f"conv halo ({width - 1})"
            )
            halo = _shifted(ctx, x_l[:, -(width - 1) :, :])
        return causal_conv1d(x_l, w, b, activation=activation, initial_state=halo)

    in_specs = (bat, P(None, None)) + ((P(None),) if has_bias else ())
    fn = shard_map(
        local, mesh=ctx.mesh, in_specs=in_specs, out_specs=bat, check_vma=False
    )
    args = (x, weight) + ((bias,) if has_bias else ())
    return fn(*args), None


def _seeded_correction(dt, A, C, s_in, chunk_size, compute_dtype):
    """Off-diagonal contribution of a shard's incoming state.

    The seeded SSD output is *linear* in the incoming state: chunk c adds
    ``diag(e^{a}) C @ (prefix_c * s_in)^T`` where ``prefix_c`` is the
    product of the chunk decays before c.  Computing the seed as a
    correction on top of the *unseeded* forward keeps the intra-chunk
    work (Pallas kernels) to a single pass, with the cross-shard state
    dependency confined to this cheap O(t*n*p) einsum.
    """
    from mamba_distributed_tpu.ops.scan import _divisor_chunk

    b, t, g, n = C.shape
    h = dt.shape[-1]
    l = _divisor_chunk(t, chunk_size)
    nc = t // l
    hpg = h // g
    p = s_in.shape[2]

    dA = (dt.astype(jnp.float32) * A.astype(jnp.float32)).reshape(b, nc, l, h)
    a_cum = cumsum_mxu(dA, axis=2)                   # in-chunk log-decay
    chunk_sum = a_cum[:, :, -1, :]                   # (b, nc, h)
    # prod of chunk decays BEFORE chunk c (exclusive prefix)
    prefix = jnp.exp(cumsum_mxu(chunk_sum, axis=1) - chunk_sum)
    e_a = jnp.exp(a_cum)                             # (b, nc, l, h)

    s_eff = s_in.astype(jnp.float32)[:, None] * prefix[..., None, None]
    s_eff = s_eff.reshape(b, nc, g, hpg, p, n)       # heads grouped: i -> (i//hpg, i%hpg)
    C_r = C.reshape(b, nc, l, g, n)
    corr = jnp.einsum(
        "bclgn,bcgqpn->bclgqp",
        C_r.astype(compute_dtype), s_eff.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    corr = corr * e_a.reshape(b, nc, l, g, hpg)[..., None]
    return corr.reshape(b, t, h, p)


def sp_ssd(
    ctx: SeqContext,
    x: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    chunk_size: int,
    D: jax.Array | None = None,
    compute_dtype=jnp.bfloat16,
    ssm_impl: str = "xla",
):
    """Sequence-sharded chunked SSD.

    Shapes as ops/ssd.ssd_chunked: x (b, t, h, p), dt (b, t, h),
    B/C (b, t, g, n), with t sharded over ``ctx.axis``.
    Returns (y, None) — the final state stays on the last shard.

    ``ssm_impl="pallas"`` runs each shard's intra-chunk compute through
    the fused VMEM kernels (ops/pallas/ssd_kernels.py, including their
    Pallas backward via the seeded custom_vjp); only the O(d_state)
    cross-shard state exchange stays shard_map/ppermute.  BASELINE
    config 4 (2.8B, seq 8192) is exactly where this matters.
    """
    from mamba_distributed_tpu.ops.scan import _divisor_chunk

    bat3 = P(ctx.batch_axes, ctx.axis, None)
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)
    has_D = D is not None

    def local(x_l, dt_l, A_, B_l, C_l, *rest):
        D_ = rest[0] if has_D else None
        b, t_l, h, p = x_l.shape
        l = _divisor_chunk(t_l, chunk_size)
        y_diag, states, chunk_decay, off_ctx = chunk_local(
            x_l, dt_l, A_, B_l, C_l, l, compute_dtype
        )
        # local pass to get this shard's summary, then combine across ranks
        _, final_local = state_passing(states, chunk_decay)
        decay_total = jnp.prod(chunk_decay, axis=1)  # (b, h)
        s_in = _incoming_state(ctx, decay_total, final_local)  # (b, h, p, n)

        # local pass seeded with the incoming state, then the shared
        # output assembly (ops/ssd.combine_chunk_outputs)
        prev_states, _ = state_passing(states, chunk_decay, initial_state=s_in)
        return combine_chunk_outputs(
            y_diag, off_ctx, prev_states, x_l, D_, compute_dtype
        )

    def local_pallas(x_l, dt_l, A_, B_l, C_l, *rest):
        from mamba_distributed_tpu.ops.pallas import ssd_chunked_pallas

        D_ = rest[0] if has_D else None
        # one unseeded Pallas pass gives both the local output and the
        # shard summary; the incoming-state contribution is added as the
        # linear correction (see _seeded_correction)
        y0, final_local = ssd_chunked_pallas(
            x_l, dt_l, A_, B_l, C_l, chunk_size=chunk_size, D=D_,
            return_final_state=True, compute_dtype=compute_dtype,
        )
        decay_total = jnp.exp(
            jnp.einsum(
                "bth,h->bh",
                dt_l.astype(jnp.float32), A_.astype(jnp.float32),
            )
        )
        s_in = _incoming_state(ctx, decay_total, final_local)
        corr = _seeded_correction(dt_l, A_, C_l, s_in, chunk_size, compute_dtype)
        return (y0.astype(jnp.float32) + corr).astype(y0.dtype)

    in_specs = (bat4, bat3, P(None), bat4, bat4)
    if has_D:
        in_specs += (P(None, None) if D.ndim == 2 else P(None),)
    fn = shard_map(
        local_pallas if ssm_impl == "pallas" else local,
        mesh=ctx.mesh, in_specs=in_specs, out_specs=bat4, check_vma=False,
    )
    args = (x, dt, A, B, C) + ((D,) if has_D else ())
    return fn(*args), None


def _incoming_state(ctx: SeqContext, decay_total, final_local):
    """Combine per-rank (decay, final-state) summaries into each rank's
    incoming state: sum over ranks j < idx of final_j * prod_{j<m<idx} decay_m.

    Implemented as an **exclusive prefix scan over the seq axis** via
    log2(S) distance-doubling ``ppermute`` rounds (Hillis-Steele on the
    associative pair combine (a, s) o (a', s') = (a a', s a' + s')),
    followed by a single shift-by-one.  Per round each rank moves one
    O(state) summary over ICI — total O(log S) latency and O(log S *
    state) traffic, vs the O(S * state) every-rank footprint of an
    all-gather formulation; nothing of size S is ever resident.
    ``ppermute`` delivers zeros to ranks with no sender, which is the
    combine's identity for ``s`` but not for ``a`` — those lanes are
    patched to the identity (a=1) by rank index.  ``decay_total`` must be
    broadcastable over ``final_local``.  Shared by the SSD and
    selective-scan SP paths.
    """
    n = ctx.size
    if n == 1:
        return jnp.zeros_like(final_local)
    axis = ctx.axis
    idx = jax.lax.axis_index(axis)

    a = decay_total
    s = final_local
    bcast = lambda v: v.reshape(v.shape + (1,) * (s.ndim - v.ndim))

    d = 1
    while d < n:
        perm = [(i, i + d) for i in range(n - d)]
        a_in = jax.lax.ppermute(a, axis, perm)
        s_in = jax.lax.ppermute(s, axis, perm)
        a_in = jnp.where(idx >= d, a_in, jnp.ones_like(a_in))
        # left-prefix (received) combined into the local value
        s = s_in * bcast(a) + s
        a = a_in * a
        d *= 2

    # inclusive -> exclusive: state entering rank r = prefix through r-1
    return jax.lax.ppermute(s, axis, [(i, i + 1) for i in range(n - 1)])


def sp_selective_scan(
    ctx: SeqContext,
    u: jax.Array,
    dt: jax.Array,
    A: jax.Array,
    B: jax.Array,
    C: jax.Array,
    D: jax.Array | None = None,
    z: jax.Array | None = None,
    delta_bias: jax.Array | None = None,
    delta_softplus: bool = False,
    ssm_impl: str = "xla",
):
    """Sequence-sharded Mamba-1 selective scan.

    Shapes as ops/scan.selective_scan: u/dt/z (b, t, d), A (d, n),
    B/C (b, t, n), with t sharded over ``ctx.axis``.  Two local passes:
    the first produces this shard's (elementwise decay, final state)
    summary, the summaries are all-gathered (O(d*n) traffic, not O(T)),
    and the second pass re-runs the local scan seeded with the combined
    incoming state.  Exact: matches the full-sequence scan to fp32
    tolerance (tests/test_seq_parallel.py).

    The second pass deliberately re-runs the recurrence instead of
    correcting pass 1's output with C_t . (exp(cumsum dt*A) * h_in) —
    that correction needs the (b, t, d, n) cumulative-decay tensor the
    chunked scan exists to avoid materializing, and the M1 recurrence is
    a few percent of layer FLOPs (the projections dominate), so 2x scan
    cost buys O(T/devices) memory with a negligible step-time impact.

    ``ssm_impl="pallas"`` runs both local passes through the fused VMEM
    kernel (ops/pallas/scan_kernels.py — its seeded custom_vjp makes the
    h_in-dependent second pass differentiable); the cross-shard exchange
    stays shard_map/ppermute either way.

    Returns (y, None) — the final state stays on the last shard.
    """
    from mamba_distributed_tpu.ops.scan import _prep, selective_scan

    if ssm_impl == "pallas":
        from mamba_distributed_tpu.ops.pallas import selective_scan_pallas
        scan_fn = selective_scan_pallas
    else:
        scan_fn = selective_scan

    bat3 = P(ctx.batch_axes, ctx.axis, None)
    has_D, has_z, has_bias = D is not None, z is not None, delta_bias is not None

    def local(u_l, dt_l, A_, B_l, C_l, *rest):
        it = iter(rest)
        D_ = next(it) if has_D else None
        z_l = next(it) if has_z else None
        bias_ = next(it) if has_bias else None

        # pass 1: local summary (zero incoming state)
        _, s_local = scan_fn(
            u_l, dt_l, A_, B_l, C_l,
            delta_bias=bias_, delta_softplus=delta_softplus,
            return_final_state=True,
        )
        _, df, Af, _, _, _ = _prep(
            u_l, dt_l, A_, B_l, C_l, None, bias_, delta_softplus
        )
        # elementwise decay over the local shard: exp(sum_t dt_t * A) (b, d, n)
        decay_total = jnp.exp(jnp.einsum("btd,dn->bdn", df, Af))
        h_in = _incoming_state(ctx, decay_total, s_local)

        # pass 2: the real scan, seeded
        return scan_fn(
            u_l, dt_l, A_, B_l, C_l, D=D_, z=z_l,
            delta_bias=bias_, delta_softplus=delta_softplus,
            initial_state=h_in,
        )

    in_specs = [bat3, bat3, P(None, None), bat3, bat3]
    args = [u, dt, A, B, C]
    if has_D:
        in_specs.append(P(None))
        args.append(D)
    if has_z:
        in_specs.append(bat3)
        args.append(z)
    if has_bias:
        in_specs.append(P(None))
        args.append(delta_bias)
    fn = shard_map(
        local, mesh=ctx.mesh, in_specs=tuple(in_specs), out_specs=bat3,
        check_vma=False,
    )
    return fn(*args), None
