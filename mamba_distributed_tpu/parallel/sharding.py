"""Sharding rules: how params, optimizer state, and batches lay out on the mesh.

Data parallel (BASELINE config 2): batch axis over (data, fsdp); params
replicated — XLA inserts the gradient psum that DDP's bucketed NCCL
all-reduce did (/root/reference/train.py:86,219-221).

FSDP (config 3): additionally shard every large parameter (and its Adam
moments, which inherit the same spec) over the fsdp axis — ZeRO-3-style
param + optimizer-state sharding; XLA inserts the all-gathers/reduce-
scatters.  Layer-stacked block params (leading n_layer axis from the
scan-over-layers layout) shard a *non-layer* axis so `lax.scan` slices
locally instead of gathering the whole stack per step.

Tensor parallel (over the ``tensor`` axis): mixer weights shard their
d_inner-derived axis — in_proj/conv column-parallel, out_proj/dt_proj
row-parallel (mamba_ssm 2.2.2 carries the same, unused, ``process_group``
plumbing in its mixers, SURVEY.md §2.3).  This is GSPMD-correctness TP:
because in_proj/wqkv pack multiple segments (z|xBC|dt, q|k|v) on one
axis, an even column shard cuts inside segments and XLA inserts a
reshard after the projection rather than keeping every inner activation
sharded Megatron-style; losses are exactly single-device (tested), the
communication pattern is compiler-chosen.  A per-rank-permuted packed
layout would tighten it — future work, BASELINE configs don't use TP.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P



# (path-suffix pattern, axis-from-end carrying the d_inner/head dimension)
# column-parallel weights shard their output axis, row-parallel their input
_TP_RULES: tuple[tuple[tuple[str, ...], int], ...] = (
    (("mixer", "in_proj", "kernel"), -1),   # column
    (("mixer", "out_proj", "kernel"), -2),  # row
    (("mixer", "conv", "kernel"), -2),
    (("mixer", "conv", "bias"), -1),
    (("mixer", "x_proj", "kernel"), -2),    # row (input is sharded x)
    (("mixer", "dt_proj", "kernel"), -1),
    (("mixer", "dt_proj", "bias"), -1),
    (("mixer", "A_log"), -1),               # mamba2 (nh,); mamba1 handled below
    (("mixer", "dt_bias"), -1),
    (("mixer", "D"), -1),
    (("mixer", "norm", "weight"), -1),
    (("mixer", "wqkv", "kernel"), -1),
    (("mlp", "fc1", "kernel"), -1),
    (("mlp", "fc2", "kernel"), -2),
    (("moe", "w1"), -1),                    # (E, d, 2*di): column
    (("moe", "w2"), -2),                    # (E, di, d): row
)

# leaves whose first non-layer axis is the MoE expert dimension
_EXPERT_RULES: tuple[tuple[str, ...], ...] = (
    ("moe", "w1"),
    ("moe", "w2"),
)


def _tp_axis(names: list[str], ndim: int, stacked: bool) -> int | None:
    """Which axis (if any) of this param shards over the tensor axis."""
    for pattern, ax in _TP_RULES:
        k = len(pattern)
        if tuple(names[-k:]) == pattern:
            # mamba1's A_log is (di, n): the head/channel axis is -2 there
            if pattern[-1] == "A_log" and ndim - (1 if stacked else 0) == 2:
                ax = -2
            return ndim + ax
    return None


def _tp_rule_end_axis(names: list[str]) -> int | None:
    """The raw rule axis-from-end (-1 column-parallel, -2 row-parallel)
    for a param path, before any ndim conversion — what the serving
    LoRA factor rules key off (a factor's rank differs from its base
    kernel's, so the absolute-axis form is useless there)."""
    for pattern, ax in _TP_RULES:
        if tuple(names[-len(pattern):]) == pattern:
            return ax
    return None


def _spec_for(names: list[str], shape: tuple[int, ...], fsdp_size: int,
              tensor_size: int, stacked: bool, expert_size: int = 1) -> P:
    """Expert axis first (MoE stacks), then the tensor-parallel axis (by
    rule), then the largest remaining fsdp-divisible axis (skipping the
    layer axis of stacked params); replicate whatever doesn't divide."""
    spec: list = [None] * len(shape)
    if expert_size > 1:
        for pattern in _EXPERT_RULES:
            k = len(pattern)
            if tuple(names[-k:]) == pattern:
                ax = 1 if stacked else 0
                if shape[ax] % expert_size == 0:
                    spec[ax] = "expert"
                break
    if tensor_size > 1:
        ax = _tp_axis(names, len(shape), stacked)
        if ax is not None and shape[ax] % tensor_size == 0:
            spec[ax] = "tensor"
    if fsdp_size > 1:
        start = 1 if stacked and len(shape) > 1 else 0
        cands = [
            (shape[i], i)
            for i in range(start, len(shape))
            if spec[i] is None and shape[i] % fsdp_size == 0
        ]
        if cands:
            _, axis = max(cands)
            spec[axis] = "fsdp"
    if all(s is None for s in spec):
        return P()
    return P(*spec)


def param_specs(params, shard: bool, fsdp_size: int, tensor_size: int = 1,
                pipe_size: int = 1, expert_size: int = 1):
    """PartitionSpec pytree matching ``params``.

    ``shard=False`` disables FSDP; tensor parallelism applies whenever
    ``tensor_size > 1`` (it is a layout requirement, not an option).
    With ``pipe_size > 1`` the stacked blocks' leading layer axis shards
    over the pipe axis — each stage holds exactly its own layers, the
    layout ``parallel/pipeline.pipelined_layers`` consumes directly.
    """
    def leaf_spec(path, leaf):
        names = [str(getattr(k, "key", getattr(k, "idx", None))) for k in path]
        stacked = "blocks" in names or "attn_blocks" in names
        spec = _spec_for(
            names, np.shape(leaf),
            fsdp_size if shard else 1, tensor_size, stacked, expert_size,
        )
        if pipe_size > 1 and stacked and np.ndim(leaf) > 0:
            rest = tuple(spec)[1:]  # layer axis -> pipe; keep fsdp/tp tail
            spec = P("pipe", *rest)
        return spec

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def param_shardings(params, mesh: Mesh, shard: bool):
    specs = param_specs(
        params, shard, mesh.shape["fsdp"], mesh.shape["tensor"],
        dict(mesh.shape).get("pipe", 1),
        dict(mesh.shape).get("expert", 1),
    )
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, mesh: Mesh, shard: bool):
    """device_put the param pytree with its shardings (lazy, async)."""
    shardings = param_shardings(params, mesh, shard)
    return jax.device_put(params, shardings)


def opt_state_shardings(opt_shapes, params, param_sharding_tree, mesh: Mesh):
    """Shardings for the optimizer state: Adam moments (and any other
    params-shaped leaf) inherit the matching parameter's sharding; scalars
    and everything else replicate on the mesh.

    Matching is by tree-path suffix: optax's ``mu``/``nu`` (and masked
    wrappers) mirror the param tree, so the param path is a suffix of the
    state leaf's path.
    """
    import jax.tree_util as jtu

    flat_params = jtu.tree_flatten_with_path(params)[0]
    by_path = {
        jtu.keystr(path): (np.shape(leaf), sh)
        for (path, leaf), sh in zip(
            flat_params, jax.tree.leaves(param_sharding_tree)
        )
    }
    replicated = NamedSharding(mesh, P())

    def leaf_shard(path, leaf):
        ks = jtu.keystr(path)
        for ppath, (shape, sh) in by_path.items():
            if ks.endswith(ppath) and np.shape(leaf) == shape:
                return sh
        return replicated

    return jtu.tree_map_with_path(leaf_shard, opt_shapes)


# ------------------------------------------- serving tensor parallelism


def serving_param_specs(params, model_shards: int, stage_shards: int = 1):
    """Per-parameter PartitionSpec pytree for SERVING weights over the
    serving mesh's ``model`` axis — and, at ``stage_shards > 1``, the
    leading LAYER axis of every layer-stacked leaf (``blocks``/
    ``attn_blocks`` subtrees, LoRA factor pools included) over the 3-D
    mesh's ``stage`` axis (parallel/mesh.serving_mesh).  Stage and
    model compose per leaf: axis 0 carries ``stage``, the TP rule axis
    carries ``model``; non-stacked leaves (embedding, head, final
    norm) stay stage-replicated.  A layer axis that doesn't divide by
    ``stage_shards`` replicates (``validate_serving_stage_shards``
    rejects that loudly at engine construction).

    The rules are the training ``_TP_RULES`` (every mixer weight's
    d_inner/head axis: Mamba in/out projections column/row-parallel,
    conv + SSM channel blocks over d_inner, attention wqkv/out_proj
    over heads, MLP/MoE inner axes) plus the two params training TP
    leaves replicated because the optimizer owns them there: the
    embedding and (untied) lm_head shard their VOCAB axis — the
    column-parallel head, the single biggest weight read of a decode
    tick.  Norm scales and anything whose rule axis doesn't divide
    evenly replicate.  ``model_shards == 1`` returns all-``P()``:
    byte-identical to the replicated pre-TP layout, so the knob's off
    position is the exact status quo.

    Slot/page state is NOT covered here — it partitions over ``data``
    only (``slot_pool_specs``); the two spec families compose because
    they name disjoint mesh axes.

    Int8-quantized serving trees (ops/quant.py) are covered too: a
    quantized leaf is ``{"kernel": int8, "scale": f32}`` whose scale
    keeps the kernel's rank with every non-channel axis sized 1 and
    whose CHANNEL axis is by construction the kernel's tensor-parallel
    axis — so a ``scale`` leaf simply rides its sibling kernel's rule
    (same path, same axis) and scales shard with their weights, no
    cross-shard rescale.  The quantized embedding's dict form
    (``embedding/kernel`` + ``embedding/scale``) keeps the vocab axis
    column-parallel exactly like the bare-array form.
    """
    def leaf_spec(path, leaf):
        names = [str(getattr(k, "key", getattr(k, "idx", None))) for k in path]
        shape = np.shape(leaf)
        spec: list = [None] * len(shape)
        if (stage_shards > 1 and shape
                and ("blocks" in names or "attn_blocks" in names)
                and shape[0] % stage_shards == 0):
            # layer-stacked leaf: stage owns whole layers (axis 0),
            # composing with whatever model-axis rule applies below
            spec[0] = "stage"
        if model_shards > 1 and len(names) >= 2 and names[-2] == "lora":
            # multi-tenant LoRA factor pools (serving/adapters.py):
            # "A" (L, slots+1, d_in, r) shards d_in with a ROW-parallel
            # base kernel's input axis (the x @ A contraction then runs
            # on the shard that holds that x slice; GSPMD all-reduces
            # the rank-r partials with the base matmul's), "B"
            # (L, slots+1, r, d_out) shards d_out with a COLUMN-
            # parallel kernel's output axis (the delta lands sharded
            # exactly like y).  The other factor of each pair — and
            # the bound "ids" rows — replicate (rank-r tensors are
            # tiny).  This is what makes LoRA and tensor parallelism
            # compose with zero cross-shard rescales.
            base_ax = _tp_rule_end_axis(names[:-2] + ["kernel"])
            ax = None
            if names[-1] == "A" and base_ax == -2:
                ax = len(shape) - 2  # d_in
            elif names[-1] == "B" and base_ax == -1:
                ax = len(shape) - 1  # d_out
            if ax is not None and shape[ax] % model_shards == 0:
                spec[ax] = "model"
            if all(s is None for s in spec):
                return P()
            return P(*spec)
        if model_shards > 1 and shape:
            lookup = names
            if names and names[-1] == "scale":
                # an int8 scale shards its kernel's axis (rank matches:
                # the scale keeps the kernel's rank, channel axis full)
                lookup = names[:-1] + ["kernel"]
            stacked = "blocks" in lookup or "attn_blocks" in lookup
            ax = _tp_axis(lookup, len(shape), stacked)
            if ax is None:
                if (lookup[-1] == "embedding"
                        or lookup[-2:] == ["embedding", "kernel"]):
                    ax = 0  # (V, d): vocab axis
                elif lookup[-2:] == ["lm_head", "kernel"]:
                    ax = len(shape) - 1  # (d, V): vocab axis
            if ax is not None and shape[ax] % model_shards == 0:
                spec[ax] = "model"
        if all(s is None for s in spec):
            return P()  # the literal pre-TP replicated spec
        return P(*spec)

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def serving_param_shardings(params, mesh: Mesh):
    """NamedSharding pytree for serving weights on a ``serving_mesh``
    (device_put at engine init / ``generate(mesh=)``; the compiled tick
    and chunk step re-assert it via sharding constraints so the layout
    can never decay mid-flight)."""
    specs = serving_param_specs(
        params, dict(mesh.shape).get("model", 1),
        dict(mesh.shape).get("stage", 1),
    )
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def constrain_serving_params(params, mesh):
    """``with_sharding_constraint`` the (decode-cast) params to their
    serving tensor-parallel layout — THE one constraint every compiled
    consumer applies (engine tick / one-shot prefill / chunk step /
    ``generate(mesh=)``), kept in a single place so the four call sites
    can never drift apart and break the engine==generate() bit-parity
    contract.  ``mesh=None`` is a no-op (the unsharded paths)."""
    if mesh is None:
        return params
    return jax.lax.with_sharding_constraint(
        params, serving_param_shardings(params, mesh)
    )


def validate_serving_model_shards(cfg, model_shards: int) -> None:
    """Reject a ``serving_model_shards`` the model's dimensions cannot
    tile — at ENGINE CONSTRUCTION, with the offending dimension named,
    instead of an opaque GSPMD error (or a silently replicated weight)
    mid-flight.  The checks mirror the axes ``serving_param_specs``
    actually shards — including the mamba2 PACKED projection widths
    (z|xBC|dt on one axis), which can be indivisible even when
    ``d_inner`` divides.  ``cfg`` is a ModelConfig."""
    if model_shards <= 1:
        return
    problems = []
    if cfg.d_inner % model_shards:
        problems.append(
            f"d_inner={cfg.d_inner} (expand * d_model — the Mamba "
            f"in/out projection and conv/SSM channel axis)"
        )
    if cfg.ssm_layer == "mamba2":
        g, ds, nh = cfg.ngroups, cfg.effective_d_state, cfg.nheads
        d_in_proj = 2 * cfg.d_inner + 2 * g * ds + nh
        conv_dim = cfg.d_inner + 2 * g * ds
        if nh % model_shards:
            problems.append(
                f"nheads={nh} (d_inner/headdim — the per-head "
                f"A_log/dt_bias/D axis and the dt segment of in_proj)"
            )
        if d_in_proj % model_shards:
            problems.append(
                f"in_proj width {d_in_proj} (the packed "
                f"2*d_inner + 2*ngroups*d_state + nheads column axis)"
            )
        if conv_dim % model_shards:
            problems.append(
                f"conv width {conv_dim} (d_inner + 2*ngroups*d_state)"
            )
    if cfg.vocab_size_padded % model_shards:
        problems.append(
            f"padded vocab={cfg.vocab_size_padded} (the embedding/"
            f"lm_head vocab axis)"
        )
    if cfg.attn_layer_idx:
        nh = cfg.effective_attn_num_heads
        nkv = cfg.effective_attn_num_kv_heads
        if nh % model_shards:
            problems.append(f"attn_num_heads={nh}")
        if nkv % model_shards:
            problems.append(f"attn_num_kv_heads={nkv}")
    if problems:
        raise ValueError(
            f"serving_model_shards={model_shards} does not divide "
            + "; ".join(problems)
            + " — pick a divisor of every listed dimension (or 1 to "
              "replicate weights)"
        )


def validate_serving_stage_shards(cfg, stage_shards: int) -> None:
    """Reject a ``serving_stage_shards`` the model's LAYER STACKS
    cannot tile — at ENGINE CONSTRUCTION, with the offending stack
    named, instead of an opaque GSPMD error (or a silently replicated
    stack) mid-flight.  The stage axis shards the leading layer axis of
    every stacked family, so EACH family must divide: pure-SSM stacks
    need ``n_layer % stage_shards == 0``; hybrid stacks need both the
    mamba stack (``n_layer - n_attn``) and the attention stack
    (``n_attn``) to divide — a stage owns whole layers of each family.
    Tick compaction is NOT required: the microbatched schedule
    (parallel/pipeline.pipelined_decode_layers) buckets whatever lane
    width the launch runs at, compacted or full-capacity, and launches
    the schedule cannot microbatch fall back to the stage-sharded
    GSPMD scan.  ``cfg`` is a ModelConfig."""
    if stage_shards <= 1:
        return
    problems = []
    n_attn = len(cfg.attn_layer_idx)
    n_mamba = cfg.n_layer - n_attn
    if cfg.n_layer % stage_shards:
        problems.append(f"n_layer={cfg.n_layer} (the layer stack)")
    if n_attn:
        if n_mamba % stage_shards:
            problems.append(
                f"mamba stack={n_mamba} (n_layer - the "
                f"{n_attn} attention layers — the hybrid 'blocks' "
                f"family shards separately)"
            )
        if n_attn % stage_shards:
            problems.append(
                f"attention stack={n_attn} (the hybrid 'attn_blocks' "
                f"family — per-layer KV page pools shard with it)"
            )
    if problems:
        raise ValueError(
            f"serving_stage_shards={stage_shards} does not divide "
            + "; ".join(problems)
            + " — pick a divisor of every listed stack (or 1 to keep "
              "the layer stacks unsharded)"
        )


# --------------------------------------------------- serving slot pool


def slot_pool_specs(pool, num_shards: int, stage_shards: int = 1):
    """PartitionSpec pytree for a serving slot pool (serving/state_cache
    .init_pool) sharded over a ``serving_mesh``'s data axis — and, at
    ``stage_shards > 1``, its per-LAYER leaves over the 3-D mesh's
    stage axis.

    The SLOT axis partitions: ``blocks`` leaves are (L, S, ...) and
    ``attn_blocks`` page-pool leaves (A, P+1, nkv, page, hd) shard the
    POOL axis 1 — the page-count axis, not the per-page token axis 3
    (head-major storage keeps the pool axis in the same position, so
    the data-axis tiling is layout-independent);
    ``logits`` (S, V) and every ``meta`` leaf (S, ...) shard axis 0.
    An axis that doesn't divide by ``num_shards`` replicates (the
    engine sizes capacity and the page pool so both divide; the
    fallback keeps arbitrary pools valid).  Weights are NOT covered
    here — serving replicates them (``NamedSharding(mesh, P())``).

    The COMPACTED-tick lane trees ride the same rules (the bucketed
    slot-pool constraint): ``state_cache.gather_slots``/
    ``scatter_slots`` pass their ``{"blocks", "logits", "meta"}``
    trees through here with the lane bucket in place of the slot
    axis — the engine keeps the bucket a multiple of the data-shard
    count and maps each shard's live slots onto that shard's lanes,
    so a compact lane tree tiles over ``data`` exactly like the full
    pool it was gathered from (docs/SERVING.md "Occupancy-adaptive
    ticks").

    STAGE tiling (``stage_shards > 1``, the 3-D mesh): the per-layer
    leaves — ``blocks`` conv/SSM carry stacks (L, S, ...) and the
    ``attn_blocks`` per-layer page pools (A, P+1, ...) — additionally
    shard their leading LAYER axis over ``stage``, so each stage owns
    exactly its own layers' decode state alongside its weight shard
    (pipeline residency; a layer axis that doesn't divide replicates,
    rejected loudly by ``validate_serving_stage_shards``).  The
    data-axis rules above are stage-blind and unchanged — ``logits``/
    ``meta`` have no layer axis and never name ``stage`` — and the
    host ``PagePool`` bookkeeping stays data-only: the stage axis
    tiles the LAYER axis of the page pools, never the page ranges.
    """
    def leaf_spec(path, leaf):
        names = [str(getattr(k, "key", getattr(k, "idx", None))) for k in path]
        shape = np.shape(leaf)
        stacked = "blocks" in names or "attn_blocks" in names
        ax = 1 if stacked else 0
        spec: list = [None] * len(shape)
        if len(shape) > ax and shape[ax] % num_shards == 0:
            spec[ax] = "data"
        if (stage_shards > 1 and stacked and shape
                and shape[0] % stage_shards == 0):
            spec[0] = "stage"
        return P(*spec)

    return jax.tree_util.tree_map_with_path(leaf_spec, pool)


def slot_pool_shardings(pool, mesh: Mesh):
    """NamedSharding pytree for the slot pool over ``mesh``'s data axis
    (and its layer stacks over a 3-D mesh's stage axis — device_put at
    engine init; re-asserted by the tick's sharding constraints every
    step so insert/evict propagation can never decay the layout)."""
    specs = slot_pool_specs(pool, mesh.shape["data"],
                            dict(mesh.shape).get("stage", 1))
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def slot_axis_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host-owned per-slot arrays the tick takes as plain
    arguments (the hybrid page table (S, B) and lengths (S,)): leading
    slot axis over data."""
    return NamedSharding(mesh, P("data"))


def batch_spec(mesh: Mesh, seq_sharded: bool = False) -> P:
    """(B, T) batches: B over (data, fsdp, expert) — expert doubles as a
    pure-DP batch axis for the non-MoE layers — T over seq when SP is on."""
    if dict(mesh.shape).get("expert", 1) > 1:
        return P(("data", "fsdp", "expert"), "seq" if seq_sharded else None)
    return P(("data", "fsdp"), "seq" if seq_sharded else None)


def batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, seq_sharded))
