"""Sharding rules: how params, optimizer state, and batches lay out on the mesh.

Data parallel (BASELINE config 2): batch axis over (data, fsdp); params
replicated — XLA inserts the gradient psum that DDP's bucketed NCCL
all-reduce did (/root/reference/train.py:86,219-221).

FSDP (config 3): additionally shard every large parameter (and its Adam
moments, which inherit the same spec) over the fsdp axis — ZeRO-3-style
param + optimizer-state sharding; XLA inserts the all-gathers/reduce-
scatters.  Layer-stacked block params (leading n_layer axis from the
scan-over-layers layout) shard a *non-layer* axis so `lax.scan` slices
locally instead of gathering the whole stack per step.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mamba_distributed_tpu.config import ModelConfig


def _spec_for(path: str, shape: tuple[int, ...], fsdp_size: int,
              stacked: bool) -> P:
    """Shard the largest fsdp-divisible axis (skipping the layer axis of
    stacked block params); replicate whatever doesn't divide."""
    if fsdp_size <= 1 or not shape:
        return P()
    start = 1 if stacked and len(shape) > 1 else 0
    cands = [
        (shape[i], i) for i in range(start, len(shape)) if shape[i] % fsdp_size == 0
    ]
    if not cands:
        return P()
    _, axis = max(cands)
    spec = [None] * len(shape)
    spec[axis] = "fsdp"
    return P(*spec)


def param_specs(params, shard: bool, fsdp_size: int):
    """PartitionSpec pytree matching ``params``.

    ``shard=False`` -> everything replicated (pure DP).
    """
    def leaf_spec(path, leaf):
        if not shard:
            return P()
        names = [getattr(k, "key", getattr(k, "idx", None)) for k in path]
        stacked = "blocks" in names or "attn_blocks" in names
        return _spec_for("/".join(map(str, names)), np.shape(leaf), fsdp_size, stacked)

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def param_shardings(params, mesh: Mesh, shard: bool):
    fsdp_size = mesh.shape["fsdp"]
    specs = param_specs(params, shard, fsdp_size)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, mesh: Mesh, shard: bool):
    """device_put the param pytree with its shardings (lazy, async)."""
    shardings = param_shardings(params, mesh, shard)
    return jax.device_put(params, shardings)


def opt_state_shardings(opt_shapes, params, param_sharding_tree, mesh: Mesh):
    """Shardings for the optimizer state: Adam moments (and any other
    params-shaped leaf) inherit the matching parameter's sharding; scalars
    and everything else replicate on the mesh.

    Matching is by tree-path suffix: optax's ``mu``/``nu`` (and masked
    wrappers) mirror the param tree, so the param path is a suffix of the
    state leaf's path.
    """
    import jax.tree_util as jtu

    flat_params = jtu.tree_flatten_with_path(params)[0]
    by_path = {
        jtu.keystr(path): (np.shape(leaf), sh)
        for (path, leaf), sh in zip(
            flat_params, jax.tree.leaves(param_sharding_tree)
        )
    }
    replicated = NamedSharding(mesh, P())

    def leaf_shard(path, leaf):
        ks = jtu.keystr(path)
        for ppath, (shape, sh) in by_path.items():
            if ks.endswith(ppath) and np.shape(leaf) == shape:
                return sh
        return replicated

    return jtu.tree_map_with_path(leaf_shard, opt_shapes)


def batch_spec(mesh: Mesh, seq_sharded: bool = False) -> P:
    """(B, T) batches: B over (data, fsdp), T over seq when SP is on."""
    return P(("data", "fsdp"), "seq" if seq_sharded else None)


def batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, seq_sharded))
