"""JAX API compatibility shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` (kwarg
``check_rep``) to top-level ``jax.shard_map`` (kwarg ``check_vma``)
around jax 0.6/0.7; the 0.4.x line this repo pins only ships the
experimental spelling.  Every in-tree call site imports ``shard_map``
from HERE with the modern signature (``check_vma=``) and the shim
translates for older jax — one place to delete when the floor moves
past the rename, instead of seven call sites in ``seq_parallel.py`` /
``ulysses.py`` / ``ring_attention.py`` / ``pipeline.py``.
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    # modern jax: the top-level API already speaks check_vma
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    @functools.wraps(_shard_map_experimental)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        """``jax.shard_map``'s signature on top of the experimental API
        (``check_vma`` was named ``check_rep`` there; same meaning)."""
        return _shard_map_experimental(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kw,
        )


__all__ = ["shard_map"]
