"""Logical device mesh over TPU chips.

The four logical axes (data, fsdp, seq, tensor) map onto the physical ICI
torus in that order — data/fsdp outermost (gradient reductions ride the
largest rings), seq innermost (ppermute neighbours stay physically
adjacent).  `jax.experimental.mesh_utils` handles the physical layout.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from mamba_distributed_tpu.config import MeshConfig


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Build the (data, fsdp, seq, tensor) mesh.

    Axis sizes must multiply to the device count; axes of size 1 are kept
    (they're free) so every sharding rule can name all four axes.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices > n:
        raise ValueError(
            f"mesh {cfg.shape} wants {cfg.num_devices} devices, have {n}"
        )
    devices = devices[: cfg.num_devices]
    try:
        dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
    except (ValueError, AssertionError):
        # non-TPU or odd topologies: plain reshape keeps neighbours adjacent
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig(), devices=jax.devices()[:1])


def serving_mesh(num_shards: int, devices=None, model_shards: int = 1) -> Mesh:
    """2-D ``("data", "model")`` mesh for the serving fabric.

    The slot pool's batch axis (and the paged-KV page axis) partition
    over ``data`` (parallel/sharding.slot_pool_shardings); the WEIGHTS
    partition over ``model`` (parallel/sharding.serving_param_shardings
    — Mamba d_inner channels, attention heads, the vocab axis of the
    embedding/head).  Decode is weight-bandwidth-bound, so the model
    axis splits the binding resource — per-device weight traffic —
    and is also what lets one engine serve a model bigger than a
    single device.  ``model_shards=1`` (the default) keeps the exact
    pre-TP behavior: every param spec is ``P()`` and the data axis is
    all that partitions anything, so shardings and trace counts match
    the one-axis mesh byte for byte.  On a CPU host, force a
    multi-device platform first
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N``, as the
    test harness does) to exercise the same GSPMD path as a pod slice.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if model_shards < 1:
        raise ValueError(f"model_shards must be >= 1, got {model_shards}")
    if devices is None:
        devices = jax.devices()
    want = num_shards * model_shards
    if want > len(devices):
        raise ValueError(
            f"serving mesh wants {num_shards} x {model_shards} = {want} "
            f"devices, have {len(devices)}"
        )
    # model innermost: a slot's weight-shard all-reduces ride the
    # fastest (most adjacent) links, like `tensor` in the training mesh
    dev_array = np.asarray(devices[:want]).reshape(num_shards, model_shards)
    return Mesh(dev_array, ("data", "model"))
