"""Logical device mesh over TPU chips.

The four logical axes (data, fsdp, seq, tensor) map onto the physical ICI
torus in that order — data/fsdp outermost (gradient reductions ride the
largest rings), seq innermost (ppermute neighbours stay physically
adjacent).  `jax.experimental.mesh_utils` handles the physical layout.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from mamba_distributed_tpu.config import MeshConfig


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Build the (data, fsdp, seq, tensor) mesh.

    Axis sizes must multiply to the device count; axes of size 1 are kept
    (they're free) so every sharding rule can name all four axes.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices > n:
        raise ValueError(
            f"mesh {cfg.shape} wants {cfg.num_devices} devices, have {n}"
        )
    devices = devices[: cfg.num_devices]
    try:
        dev_array = mesh_utils.create_device_mesh(cfg.shape, devices=devices)
    except (ValueError, AssertionError):
        # non-TPU or odd topologies: plain reshape keeps neighbours adjacent
        dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig(), devices=jax.devices()[:1])


def serving_mesh(num_shards: int, devices=None, model_shards: int = 1,
                 stage_shards: int = 1) -> Mesh:
    """Serving-fabric mesh: ``("data", "model")``, growing a middle
    ``stage`` axis — ``("data", "stage", "model")`` — when
    ``stage_shards > 1``.

    The three axes shard different things.  The slot pool's batch axis
    (and the paged-KV page axis) partition over ``data``
    (parallel/sharding.slot_pool_shardings); the WEIGHTS partition over
    ``model`` (parallel/sharding.serving_param_shardings — Mamba
    d_inner channels, attention heads, the vocab axis of the
    embedding/head); the scan-over-layers parameter stacks AND the
    per-layer slot-state stacks partition their leading LAYER axis over
    ``stage`` (GPipe-style pipeline residency: each stage holds only
    its own layers' weights, conv/SSM carries and KV page pools).
    Decode is weight-bandwidth-bound, so the model axis splits the
    binding resource — per-device weight traffic — while the stage
    axis splits total resident bytes a second way, so the two compose
    into serving models bigger than one TP group.  ``model_shards=1``
    keeps the exact pre-TP behavior: every param spec is ``P()`` and
    the data axis is all that partitions anything, so shardings and
    trace counts match the one-axis mesh byte for byte.
    ``stage_shards=1`` (the default) returns the 2-D mesh UNCHANGED —
    no size-1 stage axis is ever materialized, so ``mesh.shape`` pins,
    jit signatures and trace counts from the 2-D fabric hold byte for
    byte.  On a CPU host, force a multi-device platform first
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N``, as the
    test harness does) to exercise the same GSPMD path as a pod slice.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if model_shards < 1:
        raise ValueError(f"model_shards must be >= 1, got {model_shards}")
    if stage_shards < 1:
        raise ValueError(f"stage_shards must be >= 1, got {stage_shards}")
    if devices is None:
        devices = jax.devices()
    want = num_shards * stage_shards * model_shards
    if want > len(devices):
        raise ValueError(
            f"serving mesh wants {num_shards} x {stage_shards} x "
            f"{model_shards} = {want} devices, have {len(devices)}"
        )
    # model innermost: a slot's weight-shard all-reduces ride the
    # fastest (most adjacent) links, like `tensor` in the training
    # mesh; stage sits between — its ppermute neighbour hops are
    # next-most-frequent (once per layer-group per tick)
    if stage_shards == 1:
        dev_array = np.asarray(devices[:want]).reshape(
            num_shards, model_shards
        )
        return Mesh(dev_array, ("data", "model"))
    dev_array = np.asarray(devices[:want]).reshape(
        num_shards, stage_shards, model_shards
    )
    return Mesh(dev_array, ("data", "stage", "model"))
