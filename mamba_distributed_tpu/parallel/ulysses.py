"""Ulysses-style sequence parallelism for attention: all-to-all head sharding.

The second of the two attention SP strategies in SURVEY.md §2.3 (ring
attention is the first).  Where ring keeps heads whole and rotates KV
shards around the ``seq`` axis (S-1 ppermute hops of O(t_local) KV),
Ulysses re-distributes ONCE: an all-to-all turns the sequence sharding
into a head sharding, every device then runs ordinary *full-sequence*
causal attention for its slice of heads (via the same blockwise
online-softmax kernel the dense path uses), and a second all-to-all
restores the sequence sharding.

Trade-off (why both exist): Ulysses moves O(t·d/S) activation bytes
twice but computes each head's attention with zero inner-loop
communication — better when ICI all-to-all is cheap and heads are
plentiful; ring never materializes the full sequence on any chip —
mandatory when t/S is the memory budget.  Both are exact.

Constraints: num_heads % S == 0 and num_kv_heads % S == 0 (contiguous
head slices keep GQA groups aligned: q slice i maps exactly onto kv
slice i).  Configs that violate this should use ring attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mamba_distributed_tpu.parallel.compat import shard_map


def ulysses_attention(seq_ctx, q, k, v, impl: str = "xla"):
    """q (b, t, nh, hd), k/v (b, t, nkv, hd), t sharded over seq_ctx.axis.

    Returns (b, t, nh, hd) in q.dtype — exact match with single-device
    causal attention (pinned by tests/test_seq_parallel.py).  ``impl``
    picks the per-device SDPA backend: "xla" (blockwise scan) or
    "pallas" (flash kernel) — after the first all-to-all every device
    holds full-length sequences for its head slice, so the dense kernels
    drop in unchanged.
    """
    if impl == "pallas":
        from mamba_distributed_tpu.ops.pallas.attention_kernels import (
            flash_sdpa_causal as sdpa,
        )
    else:
        from mamba_distributed_tpu.ops.blockwise_attention import (
            blockwise_sdpa_causal as sdpa,
        )

    ctx = seq_ctx
    n = ctx.size
    nh, nkv = q.shape[2], k.shape[2]
    if nh % n or nkv % n:
        raise ValueError(
            f"ulysses_attention needs num_heads ({nh}) and num_kv_heads "
            f"({nkv}) divisible by the seq axis size ({n}); use ring "
            "attention for this config"
        )
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)

    def local(q_l, k_l, v_l):
        # seq-sharded -> head-sharded: split heads over the axis,
        # concatenate the sequence back to full length.  K and V share a
        # shape, so they ride ONE stacked collective instead of two.
        qh = jax.lax.all_to_all(
            q_l, ctx.axis, split_axis=2, concat_axis=1, tiled=True
        )
        kv = jax.lax.all_to_all(
            jnp.stack([k_l, v_l]), ctx.axis, split_axis=3, concat_axis=2,
            tiled=True,
        )
        out = sdpa(qh, kv[0], kv[1])
        # head-sharded -> seq-sharded
        return jax.lax.all_to_all(
            out, ctx.axis, split_axis=1, concat_axis=2, tiled=True
        )

    fn = shard_map(
        local, mesh=ctx.mesh, in_specs=(bat4, bat4, bat4), out_specs=bat4,
        check_vma=False,
    )
    return fn(q, k, v)
