"""Pipeline parallelism over the layer stack.

Neither the reference nor any BASELINE configuration uses pipeline
parallelism (SURVEY.md §2.3 lists it "out of scope"); it is part of the
framework's full parallelism menu.  The trainer wires it in whenever the
mesh has a ``pipe`` axis > 1 (training/train_step.py builds the train
step around :func:`pipelined_layers`, composing with data parallelism;
``__graft_entry__.dryrun_multichip`` exercises that path end-to-end).

TPU-idiomatic formulation: the scan-over-layers parameter stack is
sharded on its *layer* axis over a ``stage`` mesh axis, and a GPipe-style
schedule runs as a ``lax.scan`` over clock ticks inside ``shard_map``.
At tick t, stage s runs its local layers on the activation of microbatch
``t - s`` (bubble ticks compute on garbage and are masked out — uniform
compute, no divergent control flow, which is what the TPU wants), then
``ppermute``s the activation to stage s+1.  Total ticks =
``n_micro + n_stages - 1``; bubble fraction ``(S-1)/T`` exactly as in
the GPipe paper.

The schedule is exact: outputs equal running every layer locally
(tests/test_pipeline.py pins equality on the virtual mesh, including the
real Mamba-2 block body with its (hidden, residual) carry).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mamba_distributed_tpu.parallel.compat import shard_map


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def pipelined_layers(
    body_fn: Callable,
    stacked_params,
    xs,
    mesh: Mesh,
    axis: str = "stage",
    batch_axes=None,
):
    """Run ``scan(body_fn)`` over layer-stacked params, pipelined over
    ``axis``.

    Args:
      body_fn: ``(activation, layer_params) -> activation`` — one layer.
        The activation may be any pytree of arrays (e.g. the block
        pipeline's (hidden, residual) pair).
      stacked_params: pytree whose leaves carry a leading ``n_layer``
        axis; n_layer % n_stages must be 0 (sharded over ``axis``).
      xs: activation pytree whose leaves carry a leading (n_micro, ...)
        microbatch axis.
      mesh: mesh containing ``axis``.
      batch_axes: optional mesh axis name(s) the activations' dim 1 (the
        batch dim under the microbatch axis) is sharded over — this is
        how pipeline parallelism composes with data parallelism: each
        data replica runs the same GPipe schedule on its batch slice,
        and params stay replicated across ``batch_axes`` (their gradient
        psum over the data axes happens in the surrounding GSPMD
        program / shard_map transpose).  None = replicated activations.

    Returns the output pytree with the same (n_micro, ...) leading axis —
    identical to an unpipelined ``lax.scan`` of ``body_fn`` over all
    layers for each microbatch.
    """
    n_stages = mesh.shape[axis]
    n_layer = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layer % n_stages != 0:
        raise ValueError(
            f"pipelined_layers: n_layer ({n_layer}) must divide evenly "
            f"over the {n_stages} pipeline stages of mesh axis {axis!r}"
        )
    n_micro = jax.tree.leaves(xs)[0].shape[0]
    n_ticks = n_micro + n_stages - 1

    def local(params_local, xs_local):
        s = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def run_stage(act):
            def layer(carry, p):
                return body_fn(carry, p), None

            out, _ = jax.lax.scan(layer, act, params_local)
            return out

        buf = jax.tree.map(lambda x: jnp.zeros_like(x[0]), xs_local)
        outs = jax.tree.map(jnp.zeros_like, xs_local)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t while t < n_micro
            inject = jax.tree.map(
                lambda x: x[jnp.clip(t, 0, n_micro - 1)], xs_local
            )
            take_inject = jnp.logical_and(s == 0, t < n_micro)
            buf = _tree_where(take_inject, inject, buf)
            y = run_stage(buf)
            # the last stage finished microbatch m = t - (S-1) this tick
            m = t - (n_stages - 1)
            write = jnp.logical_and(s == n_stages - 1, m >= 0)
            idx = jnp.clip(m, 0, n_micro - 1)
            outs = jax.tree.map(
                lambda o, y_leaf: jax.lax.dynamic_update_index_in_dim(
                    o, jnp.where(write, y_leaf, o[idx]), idx, axis=0
                ),
                outs,
                y,
            )
            # activations advance one stage per tick
            buf = jax.lax.ppermute(y, axis, perm) if perm else y
            return (buf, outs), None

        (buf, outs), _ = jax.lax.scan(tick, (buf, outs), jnp.arange(n_ticks))
        # only the last stage holds real outputs; share them with everyone
        outs = jax.tree.map(
            lambda o: jax.lax.psum(
                jnp.where(s == n_stages - 1, o, jnp.zeros_like(o)), axis
            ),
            outs,
        )
        return outs

    # params shard their leading layer axis over the stage axis; activations
    # are replicated on it (and batch-sharded over batch_axes if given)
    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (jnp.ndim(p) - 1)), stacked_params
    )
    if batch_axes is None:
        xs_specs = jax.tree.map(lambda x: P(*(None,) * jnp.ndim(x)), xs)
    else:
        xs_specs = jax.tree.map(
            lambda x: P(None, batch_axes, *(None,) * (jnp.ndim(x) - 2)), xs
        )
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, xs_specs),
        out_specs=xs_specs,
        check_vma=False,
    )
    return fn(stacked_params, xs)
