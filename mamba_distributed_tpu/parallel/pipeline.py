"""Pipeline parallelism over the layer stack.

Neither the reference nor any BASELINE configuration uses pipeline
parallelism (SURVEY.md §2.3 lists it "out of scope"); it is part of the
framework's full parallelism menu.  The trainer wires it in whenever the
mesh has a ``pipe`` axis > 1 (training/train_step.py builds the train
step around :func:`pipelined_layers`, composing with data parallelism;
``__graft_entry__.dryrun_multichip`` exercises that path end-to-end).

TPU-idiomatic formulation: the scan-over-layers parameter stack is
sharded on its *layer* axis over a ``stage`` mesh axis, and a GPipe-style
schedule runs as a ``lax.scan`` over clock ticks inside ``shard_map``.
At tick t, stage s runs its local layers on the activation of microbatch
``t - s`` (bubble ticks compute on garbage and are masked out — uniform
compute, no divergent control flow, which is what the TPU wants), then
``ppermute``s the activation to stage s+1.  Total ticks =
``n_micro + n_stages - 1``; bubble fraction ``(S-1)/T`` exactly as in
the GPipe paper.

The schedule is exact: outputs equal running every layer locally
(tests/test_pipeline.py pins equality on the virtual mesh, including the
real Mamba-2 block body with its (hidden, residual) carry).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mamba_distributed_tpu.parallel.compat import shard_map


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def pipelined_layers(
    body_fn: Callable,
    stacked_params,
    xs,
    mesh: Mesh,
    axis: str = "stage",
    batch_axes=None,
):
    """Run ``scan(body_fn)`` over layer-stacked params, pipelined over
    ``axis``.

    Args:
      body_fn: ``(activation, layer_params) -> activation`` — one layer.
        The activation may be any pytree of arrays (e.g. the block
        pipeline's (hidden, residual) pair).
      stacked_params: pytree whose leaves carry a leading ``n_layer``
        axis; n_layer % n_stages must be 0 (sharded over ``axis``).
      xs: activation pytree whose leaves carry a leading (n_micro, ...)
        microbatch axis.
      mesh: mesh containing ``axis``.
      batch_axes: optional mesh axis name(s) the activations' dim 1 (the
        batch dim under the microbatch axis) is sharded over — this is
        how pipeline parallelism composes with data parallelism: each
        data replica runs the same GPipe schedule on its batch slice,
        and params stay replicated across ``batch_axes`` (their gradient
        psum over the data axes happens in the surrounding GSPMD
        program / shard_map transpose).  None = replicated activations.

    Returns the output pytree with the same (n_micro, ...) leading axis —
    identical to an unpipelined ``lax.scan`` of ``body_fn`` over all
    layers for each microbatch.
    """
    n_stages = mesh.shape[axis]
    n_layer = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layer % n_stages != 0:
        raise ValueError(
            f"pipelined_layers: n_layer ({n_layer}) must divide evenly "
            f"over the {n_stages} pipeline stages of mesh axis {axis!r}"
        )
    n_micro = jax.tree.leaves(xs)[0].shape[0]
    n_ticks = n_micro + n_stages - 1

    def local(params_local, xs_local):
        s = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def run_stage(act):
            def layer(carry, p):
                return body_fn(carry, p), None

            out, _ = jax.lax.scan(layer, act, params_local)
            return out

        buf = jax.tree.map(lambda x: jnp.zeros_like(x[0]), xs_local)
        outs = jax.tree.map(jnp.zeros_like, xs_local)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t while t < n_micro
            inject = jax.tree.map(
                lambda x: x[jnp.clip(t, 0, n_micro - 1)], xs_local
            )
            take_inject = jnp.logical_and(s == 0, t < n_micro)
            buf = _tree_where(take_inject, inject, buf)
            y = run_stage(buf)
            # the last stage finished microbatch m = t - (S-1) this tick
            m = t - (n_stages - 1)
            write = jnp.logical_and(s == n_stages - 1, m >= 0)
            idx = jnp.clip(m, 0, n_micro - 1)
            outs = jax.tree.map(
                lambda o, y_leaf: jax.lax.dynamic_update_index_in_dim(
                    o, jnp.where(write, y_leaf, o[idx]), idx, axis=0
                ),
                outs,
                y,
            )
            # activations advance one stage per tick
            buf = jax.lax.ppermute(y, axis, perm) if perm else y
            return (buf, outs), None

        (buf, outs), _ = jax.lax.scan(tick, (buf, outs), jnp.arange(n_ticks))
        # only the last stage holds real outputs; share them with everyone
        outs = jax.tree.map(
            lambda o: jax.lax.psum(
                jnp.where(s == n_stages - 1, o, jnp.zeros_like(o)), axis
            ),
            outs,
        )
        return outs

    # params shard their leading layer axis over the stage axis; activations
    # are replicated on it (and batch-sharded over batch_axes if given)
    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (jnp.ndim(p) - 1)), stacked_params
    )
    if batch_axes is None:
        xs_specs = jax.tree.map(lambda x: P(*(None,) * jnp.ndim(x)), xs)
    else:
        xs_specs = jax.tree.map(
            lambda x: P(None, batch_axes, *(None,) * (jnp.ndim(x) - 2)), xs
        )
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, xs_specs),
        out_specs=xs_specs,
        check_vma=False,
    )
    return fn(stacked_params, xs)


def pipelined_decode_layers(
    body_fn: Callable,
    stacked_params,
    stacked_state,
    act,
    mesh: Mesh,
    axis: str = "stage",
    n_micro: int | None = None,
):
    """One STATEFUL decode sub-step over the layer stack, GPipe-
    pipelined over ``axis`` with per-stage state residency — the
    serving tick's microbatched launch (docs/SERVING.md "3-D serving
    mesh").

    Where :func:`pipelined_layers` pipelines a stateless layer body
    over a microbatch axis the caller supplies, this variant owns the
    serving decode shape: the batch is a LANE axis (slots of the
    serving pool — independent streams, so lanes are the legal
    microbatch unit; consecutive tokens of one lane are sequentially
    dependent and can never pipeline), and every layer carries per-lane
    recurrent state that must stay resident on the stage that owns the
    layer.  ``stacked_state`` leaves are (L, S, ...) — layer-stacked,
    lane-indexed on axis 1 — sharded over ``axis`` on the layer axis
    exactly like ``stacked_params`` (parallel/sharding.slot_pool_specs
    at ``stage_shards > 1``), so state never crosses stages: at tick
    ``t`` stage ``s`` dynamic-slices the lane block of microbatch
    ``m = t - s`` out of its OWN state rows, runs its local layers, and
    writes the advanced rows back in place (bubble ticks — ``m``
    outside [0, n_micro) — write the old rows back unchanged, the
    tree-where masking of ``pipelined_layers`` applied to state).

    Args:
      body_fn: ``(act, layer_params, layer_state) -> (act, new_state)``
        — one decode-step layer on one lane block.  ``act`` may be any
        pytree (e.g. the block pipeline's (hidden, residual) pair);
        leaves carry a leading lane axis.
      stacked_params: pytree, leaves (L, ...); L % n_stages == 0.
      stacked_state: pytree, leaves (L, S, ...) — same L, lane axis 1.
      act: activation pytree, leaves (S, ...) — ALL lanes (the caller's
        post-embedding activations); split into ``n_micro`` contiguous
        lane blocks of width S / n_micro here.
      mesh: mesh containing ``axis``.
      n_micro: microbatch count (default ``n_stages``); S % n_micro
        must be 0.  The schedule runs ``n_micro + n_stages - 1`` clock
        ticks — bubble fraction ``(n_stages - 1) / n_ticks`` exactly as
        in the GPipe paper, so more microbatches amortize the fill/
        drain cost while n_micro = 1 degenerates to sequential stages.

    Returns ``(act_out, new_stacked_state)`` — bitwise identical to an
    unpipelined ``lax.scan`` of ``body_fn`` over all layers (each
    lane's op sequence is unchanged; the schedule only reorders WHICH
    (layer, lane-block) cell runs when, and float ops are oblivious to
    that) — pinned by tests/test_pipeline_serving.py with the real
    Mamba decode-step body.
    """
    n_stages = mesh.shape[axis]
    n_layer = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layer % n_stages != 0:
        raise ValueError(
            f"pipelined_decode_layers: n_layer ({n_layer}) must divide "
            f"evenly over the {n_stages} pipeline stages of mesh axis "
            f"{axis!r}"
        )
    n_lanes = jax.tree.leaves(act)[0].shape[0]
    if n_micro is None:
        n_micro = n_stages
    if n_lanes % n_micro != 0:
        raise ValueError(
            f"pipelined_decode_layers: lane count ({n_lanes}) must "
            f"divide over n_micro ({n_micro}) microbatches"
        )
    mw = n_lanes // n_micro
    n_ticks = n_micro + n_stages - 1

    def local(params_local, state_local, act_in):
        s = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]
        xs = jax.tree.map(
            lambda x: x.reshape((n_micro, mw) + x.shape[1:]), act_in
        )
        buf = jax.tree.map(lambda x: jnp.zeros_like(x[0]), xs)
        outs = jax.tree.map(jnp.zeros_like, xs)

        def layer(carry, xs_):
            bp, st = xs_
            return body_fn(carry, bp, st)

        def tick(carry, t):
            buf, outs, state_local = carry
            # stage 0 ingests microbatch t while t < n_micro
            inject = jax.tree.map(
                lambda x: x[jnp.clip(t, 0, n_micro - 1)], xs
            )
            take_inject = jnp.logical_and(s == 0, t < n_micro)
            buf = _tree_where(take_inject, inject, buf)
            # this stage works microbatch m = t - s (clipped: bubble
            # ticks compute on garbage lanes, masked below)
            m = t - s
            midx = jnp.clip(m, 0, n_micro - 1)
            st_m = jax.tree.map(
                lambda v: jax.lax.dynamic_slice_in_dim(
                    v, midx * mw, mw, axis=1
                ),
                state_local,
            )
            y, new_st = jax.lax.scan(layer, buf, (params_local, st_m))
            # state residency: the advanced rows write back into this
            # stage's own slice; bubble ticks re-write the OLD rows
            # (read-modify-write of identical values — a masked no-op)
            valid = jnp.logical_and(m >= 0, m < n_micro)
            write_st = _tree_where(valid, new_st, st_m)
            state_local = jax.tree.map(
                lambda v, w: jax.lax.dynamic_update_slice_in_dim(
                    v, w, midx * mw, axis=1
                ),
                state_local,
                write_st,
            )
            # the last stage finished microbatch m this tick
            write = jnp.logical_and(s == n_stages - 1, m >= 0)
            outs = jax.tree.map(
                lambda o, y_leaf: jax.lax.dynamic_update_index_in_dim(
                    o, jnp.where(write, y_leaf, o[midx]), midx, axis=0
                ),
                outs,
                y,
            )
            buf = jax.lax.ppermute(y, axis, perm) if perm else y
            return (buf, outs, state_local), None

        (buf, outs, state_local), _ = jax.lax.scan(
            tick, (buf, outs, state_local), jnp.arange(n_ticks)
        )
        # only the last stage holds real outputs; share them with
        # everyone (state stays put — each stage returns its own rows)
        outs = jax.tree.map(
            lambda o: jax.lax.psum(
                jnp.where(s == n_stages - 1, o, jnp.zeros_like(o)), axis
            ),
            outs,
        )
        act_out = jax.tree.map(
            lambda o: o.reshape((n_lanes,) + o.shape[2:]), outs
        )
        return act_out, state_local

    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (jnp.ndim(p) - 1)), stacked_params
    )
    state_specs = jax.tree.map(
        lambda v: P(axis, *(None,) * (jnp.ndim(v) - 1)), stacked_state
    )
    act_specs = jax.tree.map(lambda x: P(*(None,) * jnp.ndim(x)), act)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, state_specs, act_specs),
        out_specs=(act_specs, state_specs),
        check_vma=False,
    )
    return fn(stacked_params, stacked_state, act)
