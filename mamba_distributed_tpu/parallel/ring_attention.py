"""Ring attention over the sequence mesh axis (hybrid config 5).

Blockwise causal attention with online-softmax accumulation: each device
keeps its local Q block and rotates KV blocks around the ``seq`` ring via
``ppermute`` — S-1 hops of the local KV instead of an all-gather of the
whole sequence.  Causality is enforced per (q-block, kv-block) pair from
the global block indices; fully-future blocks are computed-and-masked
(compute is uniform, which XLA/TPU prefers over divergent control flow).

Within each hop the received KV shard is consumed in flash-style
sub-blocks (ops/blockwise_attention.py — the same update the dense path
uses), so the per-hop working set is O(t_local * block), never the
(t_local, t_local) fp32 score slab.

The math follows the published blockwise/ring-attention construction
(Liu et al. 2023); the implementation is an in-tree shard_map + lax.scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mamba_distributed_tpu.ops.blockwise_attention import (
    DEFAULT_BLOCK,
    ols_block_update,
    ols_finalize,
    ols_init,
)
from mamba_distributed_tpu.ops.scan import _divisor_chunk


def ring_attention(seq_ctx, q, k, v, k_block: int = DEFAULT_BLOCK):
    """q (b, t, nh, hd), k/v (b, t, nkv, hd), t sharded over seq_ctx.axis.

    Returns (b, t, nh, hd) in q.dtype.  Exact (up to fp32 softmax) match
    with single-device causal attention — pinned by tests.
    """
    ctx = seq_ctx
    n = ctx.size
    b, t, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)

    def local(q_l, k_l, v_l):
        bl, tl, _, _ = q_l.shape
        my = jax.lax.axis_index(ctx.axis)
        qh = q_l.reshape(bl, tl, nkv, rep, hd)
        qpos = my * tl + jnp.arange(tl)
        kb = _divisor_chunk(tl, k_block)
        nkb = tl // kb

        perm = [(i, (i + 1) % n) for i in range(n)]

        def accumulate(acc, kv, i):
            k_i, v_i = kv
            # kv shard currently held came from rank (my - i) mod n
            src = (my - i) % n
            ks = jnp.moveaxis(k_i.reshape(bl, nkb, kb, nkv, hd), 1, 0)
            vs = jnp.moveaxis(v_i.reshape(bl, nkb, kb, nkv, hd), 1, 0)

            def kv_step(a, inp):
                kj, k_b, v_b = inp
                kpos = src * tl + kj * kb + jnp.arange(kb)
                return ols_block_update(a, qh, k_b, v_b, qpos, kpos), None

            acc, _ = jax.lax.scan(kv_step, acc, (jnp.arange(nkb), ks, vs))
            return acc

        def step(carry, i):
            kv, acc = carry
            acc = accumulate(acc, kv, i)
            kv = jax.lax.ppermute(kv, ctx.axis, perm)
            return (kv, acc), None

        # n-1 hops; the last shard is consumed without a wasted final permute
        (kv, acc), _ = jax.lax.scan(
            step, ((k_l, v_l), ols_init(bl, nkv, rep, tl, hd)),
            jnp.arange(n - 1),
        )
        acc = accumulate(acc, kv, n - 1)
        return ols_finalize(acc, q_l.dtype)

    fn = jax.shard_map(
        local, mesh=ctx.mesh, in_specs=(bat4, bat4, bat4), out_specs=bat4,
        check_vma=False,
    )
    return fn(q, k, v)
