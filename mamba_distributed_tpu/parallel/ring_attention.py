"""Ring attention over the sequence mesh axis (hybrid config 5).

Blockwise causal attention with online-softmax accumulation: each device
keeps its local Q block and rotates KV blocks around the ``seq`` ring via
``ppermute`` — S-1 hops of the local KV instead of an all-gather of the
whole sequence.  Causality is enforced per (q-block, kv-block) pair from
the global block indices; fully-future blocks are computed-and-masked
(compute is uniform, which XLA/TPU prefers over divergent control flow).

The math follows the published blockwise/ring-attention construction
(Liu et al. 2023); the implementation is an in-tree shard_map + lax.scan.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _block_attn(q, k, v, qpos, kpos):
    """Masked fp32 scores for one (q-block, kv-block) pair.

    q (b, tq, nkv, rep, hd); k/v (b, tk, nkv, hd).
    Returns scores (b, nkv, rep, tq, tk) with -inf above the causal line.
    """
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bqgrh,bkgh->bgrqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    mask = qpos[:, None] >= kpos[None, :]  # (tq, tk)
    return jnp.where(mask[None, None, None], scores, -jnp.inf)


def ring_attention(seq_ctx, q, k, v):
    """q (b, t, nh, hd), k/v (b, t, nkv, hd), t sharded over seq_ctx.axis.

    Returns (b, t, nh, hd) in q.dtype.  Exact (up to fp32 softmax) match
    with single-device causal attention — pinned by tests.
    """
    ctx = seq_ctx
    n = ctx.size
    b, t, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)

    def local(q_l, k_l, v_l):
        bl, tl, _, _ = q_l.shape
        my = jax.lax.axis_index(ctx.axis)
        qh = q_l.reshape(bl, tl, nkv, rep, hd)
        qpos = my * tl + jnp.arange(tl)

        perm = [(i, (i + 1) % n) for i in range(n)]

        def accumulate(acc, kv, i):
            m, num, den = acc
            k_i, v_i = kv
            # kv block currently held came from rank (my - i) mod n
            src = (my - i) % n
            kpos = src * tl + jnp.arange(tl)
            s = _block_attn(qh, k_i, v_i, qpos, kpos)  # (b,g,r,tq,tk)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            # guard: fully-masked rows keep m at -inf; exp(-inf - -inf) -> use where
            scale = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            num = num * scale[..., None] + jnp.einsum(
                "bgrqk,bkgh->bgrqh", p.astype(v_i.dtype), v_i,
                preferred_element_type=jnp.float32,
            )
            den = den * scale + jnp.sum(p, axis=-1)
            return m_new, num, den

        def step(carry, i):
            kv, acc = carry
            acc = accumulate(acc, kv, i)
            kv = jax.lax.ppermute(kv, ctx.axis, perm)
            return (kv, acc), None

        m0 = jnp.full((bl, nkv, rep, tl), -jnp.inf, jnp.float32)
        num0 = jnp.zeros((bl, nkv, rep, tl, hd), jnp.float32)
        den0 = jnp.zeros((bl, nkv, rep, tl), jnp.float32)
        # n-1 hops; the last block is consumed without a wasted final permute
        (kv, acc), _ = jax.lax.scan(
            step, ((k_l, v_l), (m0, num0, den0)), jnp.arange(n - 1)
        )
        m, num, den = accumulate(acc, kv, n - 1)
        out = num / jnp.maximum(den[..., None], 1e-30)
        # (b, g, r, tq, hd) -> (b, tq, g*r, hd)
        out = jnp.moveaxis(out, 3, 1).reshape(bl, tl, nh, hd)
        return out.astype(q_l.dtype)

    fn = jax.shard_map(
        local, mesh=ctx.mesh, in_specs=(bat4, bat4, bat4), out_specs=bat4,
        check_vma=False,
    )
    return fn(q, k, v)
