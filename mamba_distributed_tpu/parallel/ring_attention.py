"""Ring attention over the sequence mesh axis (hybrid config 5).

Blockwise causal attention with online-softmax accumulation: each device
keeps its local Q block and rotates KV blocks around the ``seq`` ring via
``ppermute`` — S-1 hops of the local KV instead of an all-gather of the
whole sequence.  Causality is enforced per (q-block, kv-block) pair from
the global block indices; fully-future blocks are computed-and-masked
(compute is uniform, which XLA/TPU prefers over divergent control flow).

Within each hop the received KV shard is consumed in flash-style
sub-blocks (ops/blockwise_attention.py — the same update the dense path
uses), so the per-hop working set is O(t_local * block), never the
(t_local, t_local) fp32 score slab.

The math follows the published blockwise/ring-attention construction
(Liu et al. 2023); the implementation is an in-tree shard_map + lax.scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mamba_distributed_tpu.parallel.compat import shard_map
from mamba_distributed_tpu.ops.blockwise_attention import (
    DEFAULT_BLOCK,
    ols_block_update,
    ols_finalize,
    ols_init,
)
from mamba_distributed_tpu.ops.scan import _divisor_chunk


def ring_attention(seq_ctx, q, k, v, k_block: int = DEFAULT_BLOCK,
                   impl: str = "xla"):
    """q (b, t, nh, hd), k/v (b, t, nkv, hd), t sharded over seq_ctx.axis.

    Returns (b, t, nh, hd) in q.dtype.  Exact (up to fp32 softmax) match
    with single-device causal attention — pinned by tests.  ``impl``
    picks the per-hop SDPA: "xla" (blockwise scan below) or "pallas"
    (flash kernels per hop, _ring_attention_pallas).
    """
    if impl == "pallas":
        return _ring_attention_pallas(seq_ctx, q, k, v)
    ctx = seq_ctx
    n = ctx.size
    b, t, nh, hd = q.shape
    nkv = k.shape[2]
    rep = nh // nkv
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)

    def local(q_l, k_l, v_l):
        bl, tl, _, _ = q_l.shape
        my = jax.lax.axis_index(ctx.axis)
        qh = q_l.reshape(bl, tl, nkv, rep, hd)
        qpos = my * tl + jnp.arange(tl)
        kb = _divisor_chunk(tl, k_block)
        nkb = tl // kb

        perm = [(i, (i + 1) % n) for i in range(n)]

        def accumulate(acc, kv, i):
            k_i, v_i = kv
            # kv shard currently held came from rank (my - i) mod n
            src = (my - i) % n
            ks = jnp.moveaxis(k_i.reshape(bl, nkb, kb, nkv, hd), 1, 0)
            vs = jnp.moveaxis(v_i.reshape(bl, nkb, kb, nkv, hd), 1, 0)

            def kv_step(a, inp):
                kj, k_b, v_b = inp
                kpos = src * tl + kj * kb + jnp.arange(kb)
                return ols_block_update(a, qh, k_b, v_b, qpos, kpos), None

            acc, _ = jax.lax.scan(kv_step, acc, (jnp.arange(nkb), ks, vs))
            return acc

        def step(carry, i):
            kv, acc = carry
            acc = accumulate(acc, kv, i)
            kv = jax.lax.ppermute(kv, ctx.axis, perm)
            return (kv, acc), None

        # n-1 hops; the last shard is consumed without a wasted final permute
        (kv, acc), _ = jax.lax.scan(
            step, ((k_l, v_l), ols_init(bl, nkv, rep, tl, hd)),
            jnp.arange(n - 1),
        )
        acc = accumulate(acc, kv, n - 1)
        return ols_finalize(acc, q_l.dtype)

    fn = shard_map(
        local, mesh=ctx.mesh, in_specs=(bat4, bat4, bat4), out_specs=bat4,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ring attention on the Pallas flash kernels (attn_impl="pallas").
#
# Every hop of a causal ring is one of exactly three cases relative to the
# local Q shard — fully-past (the kv shard's owner precedes this device:
# every pair is unmasked, static offset = t_local), diagonal (own shard:
# ordinary causal, offset = 0), or fully-future (skipped outright, saving
# the compute the XLA path spends computing-and-masking).  That makes the
# traced per-hop offset problem disappear: ``lax.switch`` picks between
# two static-offset flash calls and a skip.
#
# Per-hop partials (o_i, lse_i) merge in XLA by the standard logsumexp
# combination; the backward exploits that the flash decomposition is
# exact per (q, kv) pair GIVEN the merged lse and delta = rowsum(dO*O):
# dq accumulates locally over hops, dk/dv ride the ring together with
# their kv shard for one full cycle (n hops), landing home fully
# accumulated.  This is the ring analogue of the dense kernel's
# custom_vjp, so the whole thing is differentiable end to end.
# ---------------------------------------------------------------------------


def _merge_partial(m, num, den, o_i, lse_i):
    """Fold one hop's normalized partial (o_i, lse_i) into the running
    (max, numerator, denominator) accumulator (all fp32)."""
    m_new = jnp.maximum(m, lse_i)
    w_prev = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    w_i = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - m_new), 0.0)
    num = num * w_prev[..., None] + o_i.astype(jnp.float32) * w_i[..., None]
    den = den * w_prev + w_i
    return m_new, num, den


def _ring_attention_pallas(seq_ctx, q, k, v):
    from mamba_distributed_tpu.ops.pallas.attention_kernels import (
        flash_pair_dkv,
        flash_pair_dq,
        flash_pair_fwd,
    )

    ctx = seq_ctx
    n = ctx.size
    nh = q.shape[2]
    nkv = k.shape[2]
    bat4 = P(ctx.batch_axes, ctx.axis, None, None)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(q_l, k_l, v_l):
        bl, tl, _, hd = q_l.shape
        qt0 = jnp.moveaxis(q_l, 2, 1)                # (b, nh, tl, hd)
        kt0 = jnp.moveaxis(k_l, 2, 1)                # (b, nkv, tl, hd)
        vt0 = jnp.moveaxis(v_l, 2, 1)

        def hop_branchno(i):
            # 0: fully-past (src < my), 1: diagonal, 2: fully-future.
            # axis_index is taken HERE (inside the traced fwd/bwd), never
            # closed over by the custom_vjp — closures over tracers leak.
            my = jax.lax.axis_index(ctx.axis)
            src = (my - i) % n
            return jnp.where(src < my, 0, jnp.where(src == my, 1, 2))

        @jax.custom_vjp
        def ring_core(qt, kt0, vt0):
            o, _ = _ring_fwd_impl(qt, kt0, vt0)
            return o

        def _ring_fwd_impl(qt, kt0, vt0):
            def pair_case(offset):
                def run(kt, vt):
                    return flash_pair_fwd(qt, kt, vt, offset)
                return run

            def skip_case(kt, vt):
                return (
                    jnp.zeros(qt.shape, qt.dtype),
                    jnp.full(qt.shape[:3], -jnp.inf, jnp.float32),
                )

            def fold(acc, kt, vt, i):
                o_i, lse_i = jax.lax.switch(
                    hop_branchno(i),
                    [pair_case(tl), pair_case(0), skip_case],
                    kt, vt,
                )
                return _merge_partial(*acc, o_i, lse_i)

            acc0 = (
                jnp.full(qt.shape[:3], -jnp.inf, jnp.float32),
                jnp.zeros(qt.shape, jnp.float32),
                jnp.zeros(qt.shape[:3], jnp.float32),
            )

            def step(carry, i):
                (kt, vt), acc = carry
                acc = fold(acc, kt, vt, i)
                kt, vt = jax.lax.ppermute((kt, vt), ctx.axis, perm)
                return ((kt, vt), acc), None

            # n-1 hops; the last shard is consumed without a final permute
            ((kt, vt), acc), _ = jax.lax.scan(
                step, ((kt0, vt0), acc0), jnp.arange(n - 1)
            )
            m, num, den = fold(acc, kt, vt, jnp.int32(n - 1))
            o = (num / jnp.maximum(den, 1e-30)[..., None]).astype(qt.dtype)
            lse = jnp.where(
                den > 0.0, m + jnp.log(jnp.maximum(den, 1e-30)), jnp.inf
            )
            return o, lse

        def ring_fwd(qt, kt0, vt0):
            o, lse = _ring_fwd_impl(qt, kt0, vt0)
            return o, (qt, kt0, vt0, o, lse)

        def ring_bwd(res, do):
            qt, kt0, vt0, o, lse = res
            dlt = jnp.sum(
                do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
            )

            def dq_case(offset):
                def run(kt, vt):
                    return flash_pair_dq(qt, kt, vt, do, lse, dlt, offset)
                return run

            def dq_skip(kt, vt):
                return jnp.zeros(qt.shape, jnp.float32)

            def dkv_case(offset):
                def run(kt, vt):
                    return flash_pair_dkv(qt, kt, vt, do, lse, dlt, offset)
                return run

            def dkv_skip(kt, vt):
                return (
                    jnp.zeros(kt.shape, jnp.float32),
                    jnp.zeros(vt.shape, jnp.float32),
                )

            def step(carry, i):
                (kt, vt, dk, dv), dq = carry
                bno = hop_branchno(i)
                dq = dq + jax.lax.switch(
                    bno, [dq_case(tl), dq_case(0), dq_skip], kt, vt
                )
                dk_i, dv_i = jax.lax.switch(
                    bno, [dkv_case(tl), dkv_case(0), dkv_skip], kt, vt
                )
                # dk/dv ride the ring WITH their kv shard: after the full
                # n-hop cycle each shard's gradient lands back home
                kt, vt, dk, dv = jax.lax.ppermute(
                    (kt, vt, dk + dk_i, dv + dv_i), ctx.axis, perm
                )
                return ((kt, vt, dk, dv), dq), None

            dk0 = jnp.zeros(kt0.shape, jnp.float32)
            dv0 = jnp.zeros(vt0.shape, jnp.float32)
            dq0 = jnp.zeros(qt.shape, jnp.float32)
            ((_, _, dk, dv), dq), _ = jax.lax.scan(
                step, ((kt0, vt0, dk0, dv0), dq0), jnp.arange(n)
            )
            return (
                dq.astype(qt.dtype), dk.astype(kt0.dtype),
                dv.astype(vt0.dtype),
            )

        ring_core.defvjp(ring_fwd, ring_bwd)

        out = ring_core(qt0, kt0, vt0)
        return jnp.moveaxis(out, 1, 2)               # (b, tl, nh, hd)

    fn = shard_map(
        local, mesh=ctx.mesh, in_specs=(bat4, bat4, bat4), out_specs=bat4,
        check_vma=False,
    )
    return fn(q, k, v)
