"""Inference: recurrent O(1)-per-token generation + sampling."""

from mamba_distributed_tpu.inference.bucketing import (
    next_pow2_bucket,
    pad_to_bucket,
)
from mamba_distributed_tpu.inference.generate import (
    generate,
    top_k_sample,
    vocab_pad_mask,
)

__all__ = [
    "generate",
    "next_pow2_bucket",
    "pad_to_bucket",
    "top_k_sample",
    "vocab_pad_mask",
]
