"""Inference: recurrent O(1)-per-token generation + sampling."""

from mamba_distributed_tpu.inference.bucketing import (
    chunk_aligned_bucket,
    next_pow2_bucket,
    pad_to_bucket,
    use_chunked_prefill,
)
from mamba_distributed_tpu.inference.generate import (
    generate,
    top_k_sample,
    vocab_pad_mask,
)

__all__ = [
    "chunk_aligned_bucket",
    "generate",
    "next_pow2_bucket",
    "pad_to_bucket",
    "top_k_sample",
    "use_chunked_prefill",
    "vocab_pad_mask",
]
