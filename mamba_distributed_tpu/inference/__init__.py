"""Inference: recurrent O(1)-per-token generation + sampling."""

from mamba_distributed_tpu.inference.generate import generate, top_k_sample

__all__ = ["generate", "top_k_sample"]
