"""Power-of-two prompt-length bucketing for prefill.

Every distinct prompt length is a distinct jit trace signature, so a
serving workload with heterogeneous prompts would recompile the prefill
for each new length.  Bucketing pads the prompt up to the next power of
two and masks the pad positions, bounding the number of traces at
log2(max_prompt_len) for any workload.

The pad is on the LEFT and the mask zeroes the mixer inputs at pad
positions (``token_mask`` in models/lm.lm_prefill), which makes the
padded prefill numerically equivalent to the unpadded one for pure-SSM
stacks: a zero conv/SSM input contributes nothing to the scan, and the
state entering the first real token is exactly the zero initial state.
Equivalent, not bit-identical — padding shifts the chunked scan's
chunk boundaries, so the SSM state's sums re-associate (~1e-7 in fp32;
the conv cache IS bit-identical).  Anything needing exact token
streams must compare padded-vs-padded, which is how the serving
engine's parity contract works: engine and solo ``generate()`` pad the
same prompt identically.  (Hybrid stacks with attention layers can't
mask pads through a full-sequence forward — real queries would still
attend to pad keys — so they skip the pow2 one-shot path and instead
take the chunk-aligned bucket through the CHUNK step for every prompt
length: pad keys are simply never written to the paged KV, see
serving/prefill.py and models/attention.attention_mixer_chunk.)

Shared by ``inference/generate.py`` and the serving prefill path
(``serving/engine.py``); the trace-count test in tests/test_serving.py
pins the one-trace-per-bucket contract.

Long prompts (``t > cfg.prefill_chunk_tokens`` when chunking is on)
leave the pow2 ladder: they pad to the next multiple of the chunk size
(``chunk_aligned_bucket``) and prefill chunk-by-chunk through one
compiled chunk shape (serving/prefill.py) — one trace total and at most
``chunk-1`` pad tokens, instead of a new pow2 trace per length class
and up-to-2x padding waste.  The pad stays on the LEFT (entirely inside
the first chunk), so the mask contract above is unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Smallest bucket: below this, padding waste is negligible and going
# finer would multiply trace count for no compile-time win.
MIN_BUCKET = 8


def next_pow2_bucket(t: int, min_bucket: int = MIN_BUCKET) -> int:
    """Smallest power-of-two >= t (and >= min_bucket)."""
    if t < 1:
        raise ValueError(f"prompt length must be >= 1, got {t}")
    b = max(min_bucket, 1)
    while b < t:
        b *= 2
    return b


def chunk_aligned_bucket(t: int, chunk: int) -> int:
    """Smallest multiple of ``chunk`` >= t (the chunked-prefill layout)."""
    if t < 1:
        raise ValueError(f"prompt length must be >= 1, got {t}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    return ((t + chunk - 1) // chunk) * chunk


def use_chunked_prefill(t: int, chunk_tokens: int) -> bool:
    """One rule for both ``generate()`` and the serving engine: prompts
    longer than the chunk size take the chunked path (token-parity
    demands the two callers never disagree).  ``chunk_tokens <= 0``
    disables chunking entirely."""
    return chunk_tokens > 0 and t > chunk_tokens


def pad_to_bucket(
    prompt_ids: jax.Array, bucket: int
) -> tuple[jax.Array, jax.Array]:
    """Left-pad (b, t) int32 prompts to (b, bucket) + float {0,1} mask.

    Pad positions hold token id 0 — the value never reaches the scan
    state because the mask zeroes the mixer inputs there.
    """
    b, t = prompt_ids.shape
    if bucket < t:
        raise ValueError(f"bucket {bucket} < prompt length {t}")
    pad = bucket - t
    padded = jnp.pad(prompt_ids, ((0, 0), (pad, 0)))
    mask = jnp.pad(jnp.ones((b, t), jnp.float32), ((0, 0), (pad, 0)))
    return padded, mask
