"""Recurrent generation with top-k sampling.

Functional upgrade of the reference's generate/top_k_sampling
(/root/reference/model.py:49-95, train.py:166-199): same sampling recipe
(top-k 50, softmax over the k logits, categorical draw), but the decode
loop carries the O(1) recurrent state (conv cache + SSM state per layer)
instead of re-running the full growing prefix through the model each token
— the reference never used its dep's ``inference_params`` (SURVEY.md §3.3).

Everything (prefill scan + decode scan) is one jit; token-for-token the
logits match the full-sequence forward (pinned by tests/test_model.py
decode-parity and tests/test_inference.py).

Serving contracts (mamba_distributed_tpu/serving/ reuses all of this):

* Prompt lengths are bucketed to powers of two for pure-SSM stacks
  (inference/bucketing.py) so heterogeneous prompts share jit traces —
  the padded prefill is numerically equivalent to the unpadded one
  (~1e-7 summation-order noise for off-bucket lengths; pass
  ``length_bucketing=False`` to reproduce pre-bucketing streams
  exactly).  Prompts longer than ``cfg.effective_prefill_chunk_tokens``
  instead run the serving chunk step chunk-by-chunk
  (serving/prefill.py) — the identical computation the engine performs,
  so long-prompt parity is exact by construction.
* The per-step sampling key is ``fold_in(key, i)`` — reproducible from
  (request key, tokens-generated counter) alone, which is what lets the
  serving engine's slot-pooled decode emit the same token stream as a
  solo ``generate`` call with the same key (tests/test_serving.py).
* ``eos_id`` moves EOT stopping into the decode loop: finished rows emit
  ``eos_id`` deterministically for the rest of the budget.  ``None``
  keeps the old truncate-on-host contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.inference.bucketing import (
    next_pow2_bucket,
    pad_to_bucket,
    use_chunked_prefill,
)
from mamba_distributed_tpu.models.lm import lm_prefill, lm_step

# Python-side-effect trace counters: _generate_impl / _decode_impl bump
# these exactly once per jit trace (retraces are what the bucketing
# exists to bound — pinned by
# tests/test_serving.py::test_generate_length_bucketing_traces and
# tests/test_prefill.py; the serving engine keeps its own counters in
# serving/engine.py, the chunk step's lives in serving/prefill.py).
TRACE_COUNTS = {"generate": 0, "decode": 0}


def top_k_sample(
    key: jax.Array,
    logits: jax.Array,
    k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """Sample from the top-k renormalized distribution.  logits (b, V) -> (b,)."""
    vals, idx = jax.lax.top_k(logits, k)
    choice = jax.random.categorical(key, vals / temperature)
    return jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]


def vocab_pad_mask(cfg: ModelConfig) -> jax.Array:
    """(V_padded,) additive mask: 0 for real tokens, -inf for the
    vocab-padding rows (tied zero-padded embeddings give them logit 0.0,
    which would outrank real negative logits)."""
    return jnp.where(
        jnp.arange(cfg.vocab_size_padded) < cfg.vocab_size, 0.0, -jnp.inf
    )


def _decode_params(params: dict, cfg: ModelConfig) -> dict:
    """Pre-cast matmul kernels + embedding to the compute dtype.

    Decode is weight-bandwidth-bound: every token step re-read the fp32
    params only for ``linear()`` to cast them to bf16 (~1.1 GB/token at
    280M — exactly the measured 1.38 ms/token on v5e).  Casting once
    outside the decode scan halves that traffic, and the values are
    bit-identical because the per-step cast produced the same bf16
    numbers.  Conv kernels, biases, norm weights, SSM scalars and the
    MoE router (routed in fp32) stay fp32 — their math runs in fp32.

    ``cfg.serving_weight_dtype="int8"`` goes further (ops/quant.py):
    the ``linear()``-routed kernels and the embedding become symmetric
    per-channel int8 (``{"kernel": int8, "scale": f32}``, scale axis =
    the tensor-parallel axis) instead of bf16, halving resident weight
    bytes again; the matmul sites dequantize at use.  The serving
    engine and ``generate()`` both quantize HERE — one shared cast —
    so the quantized engine==generate() parity argument mirrors the
    bf16 one (toleranced: ops/quant.assert_stream_close).  mamba1's
    dt_proj kernel stays on the bf16 cast (its matmul bypasses
    ``linear`` — the dt bias folds into the scan's fp32 delta path).
    """
    cd = jnp.dtype(cfg.compute_dtype)
    if cfg.serving_weight_dtype == "int8":
        from mamba_distributed_tpu.ops.quant import quantize_serving_params

        # quantize FROM THE FP32 MASTERS (before any bf16 cast — the
        # scales keep full precision); the cast below then skips the
        # int8 kernels and their f32 scales
        params = quantize_serving_params(params)

    def cast(path, leaf):
        # denylist contract: every "kernel" leaf is a bf16-matmul weight
        # UNLESS its parent is named here because its math must stay fp32.
        # Adding a new fp32-math matmul param under a new key REQUIRES
        # extending this tuple + test_decode_params_cast_selectivity
        # (tests/test_inference.py), which pins the casted/uncasted split.
        keys = [getattr(p, "key", None) for p in path]
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.integer):
            return leaf  # int8 quantized kernels stay as-is
        if keys and keys[-1] == "scale":
            return leaf  # quantization scales stay f32
        if keys and keys[-1] == "embedding":
            return leaf.astype(cd)
        if (
            keys
            and keys[-1] == "kernel"
            and len(keys) >= 2
            and keys[-2] not in ("conv", "router")
        ):
            return leaf.astype(cd)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, params)


def _decode_scan(
    params: dict,
    cfg: ModelConfig,
    state,
    last_logits: jax.Array,
    key: jax.Array,
    max_new_tokens: int,
    top_k: int,
    temperature: float,
    eos_id: jax.Array,
) -> jax.Array:
    """The decode loop: (prefill state, last logits) -> (b, n) sampled
    tokens.  ONE definition shared by ``_generate_impl`` (one-shot
    prefill) and ``_decode_impl`` (chunked prefill), so the two paths'
    decode numerics cannot diverge."""
    b = last_logits.shape[0]
    pad_mask = vocab_pad_mask(cfg)
    has_eos = eos_id >= 0

    def decode(carry, i):
        state, logits, done = carry
        # fold_in (not split) so the serving engine can reproduce step i's
        # key from (request key, per-slot counter) without a static budget
        tok = top_k_sample(
            jax.random.fold_in(key, i), logits + pad_mask, top_k, temperature
        )
        # `done` implies has_eos (it is only ever set below), so finished
        # rows deterministically keep emitting the eos token
        tok = jnp.where(done, eos_id, tok)
        done = done | (has_eos & (tok == eos_id))
        logits, state = lm_step(params, cfg, state, tok)
        return (state, logits, done), tok

    done0 = jnp.zeros((b,), bool)
    (_, _, _), new_tokens = jax.lax.scan(
        decode, (state, last_logits, done0), jnp.arange(max_new_tokens)
    )
    return jnp.moveaxis(new_tokens, 0, 1)


def _constrain_tp(params: dict, mesh):
    """Pin the decode-cast params to their serving tensor-parallel
    layout (``mesh`` a 2-D serving_mesh with model > 1; None = no-op).
    Delegates to the ONE shared constraint the serving engine's tick/
    prefill/chunk step also apply, so a solo ``generate(mesh=)``
    partitions its math identically — the engine==generate()
    bit-parity contract at ``model > 1``."""
    if mesh is None:
        return params
    from mamba_distributed_tpu.parallel.sharding import (
        constrain_serving_params,
    )

    return constrain_serving_params(params, mesh)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "top_k", "temperature", "mesh"),
)
def _generate_impl(
    params: dict,
    cfg: ModelConfig,
    prompt_ids: jax.Array,
    token_mask: jax.Array | None,
    key: jax.Array,
    max_new_tokens: int,
    top_k: int,
    temperature: float,
    eos_id: jax.Array,
    mesh=None,
) -> jax.Array:
    """(b, T_bucket) padded prompt -> (b, T_bucket + max_new_tokens).

    ``eos_id`` is a traced int32 scalar (-1 => no EOS stopping, the same
    sentinel the serving tick uses) so switching tokenizers never
    recompiles."""
    TRACE_COUNTS["generate"] += 1  # python side effect: runs once per trace
    b, t = prompt_ids.shape
    params = _constrain_tp(_decode_params(params, cfg), mesh)
    # parallel prefill: one full-sequence forward builds the decode state
    # (the reference re-ran the whole prefix per token instead)
    last_logits, state = lm_prefill(
        params, cfg, prompt_ids, max_len=t + max_new_tokens,
        token_mask=token_mask,
    )
    new_tokens = _decode_scan(
        params, cfg, state, last_logits, key, max_new_tokens, top_k,
        temperature, eos_id,
    )
    return jnp.concatenate([prompt_ids, new_tokens], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "top_k", "temperature", "mesh"),
)
def _decode_impl(
    params: dict,
    cfg: ModelConfig,
    state,
    last_logits: jax.Array,
    key: jax.Array,
    max_new_tokens: int,
    top_k: int,
    temperature: float,
    eos_id: jax.Array,
    mesh=None,
) -> jax.Array:
    """Decode from an externally built prefill state (the chunked-prefill
    path, serving/prefill.chunked_prefill) -> (b, max_new_tokens).

    One trace per (cfg, budget, sampling statics) regardless of prompt
    length — the prompt's shape never enters this function."""
    TRACE_COUNTS["decode"] += 1  # python side effect: runs once per trace
    params = _constrain_tp(_decode_params(params, cfg), mesh)
    return _decode_scan(
        params, cfg, state, last_logits, key, max_new_tokens, top_k,
        temperature, eos_id,
    )


def generate(
    params: dict,
    cfg: ModelConfig,
    prompt_ids: jax.Array,
    key: jax.Array,
    max_new_tokens: int = 32,
    top_k: int = 50,
    temperature: float = 1.0,
    eos_id: int | None = None,
    length_bucketing: bool = True,
    mesh=None,
    prefix_cache=None,
    drafter=None,
) -> jax.Array:
    """prompt_ids (b, t) int32 -> (b, t + max_new_tokens) sampled tokens.

    ``mesh`` (a 2-D ``parallel/mesh.serving_mesh``) runs the prefill +
    decode with the weights tensor-parallel over the mesh's ``model``
    axis — the SAME per-parameter constraint the serving engine
    applies, so a solo call with an engine's mesh stays bit-identical
    to the engine's streams at ``serving_model_shards > 1``.  None
    (default) is the unsharded path, unchanged.

    ``eos_id=None``: EOT stopping is a host-side concern (the full budget
    is generated; truncate at the tokenizer's EOT afterwards, as the
    caller wishes).  With ``eos_id`` set, rows that sample it keep
    emitting ``eos_id`` deterministically for the rest of the budget, so
    the output is directly truncatable and token-for-token reproducible
    by the serving engine.

    ``length_bucketing`` pads the prompt to a power-of-two bucket (pure-
    SSM stacks only) so any workload of heterogeneous prompt lengths
    compiles O(log max_len) traces instead of one per distinct length.
    Prompts longer than ``cfg.prefill_chunk_tokens`` (when > 0) instead
    prefill chunk-by-chunk through the serving chunk step
    (serving/prefill.py) — ONE compiled chunk shape + one decode trace
    for any prompt length, and the exact computation the serving engine
    runs, which is what keeps engine-vs-generate() token parity exact
    for long prompts too.

    ``prefix_cache`` (a serving/prefix_cache.PrefixCache; pure-SSM,
    batch-1) reuses carry snapshots: an exact-prompt full hit skips the
    prefill outright (one-shot AND chunked layouts), a chunked partial
    hit resumes at the first uncached chunk, and chunked prefills store
    their boundaries back.  Sharing an engine's cache (same params!)
    makes warm engine==generate() parity directly testable — and warm
    streams are bit-identical to cold ones regardless, because a
    snapshot is the identical computation's literal output.  Hybrid
    configs ignore the cache here (their entries pin a serving
    engine's KV page pool).

    ``cfg.spec_tokens > 0`` routes greedy (``top_k=1``) batch-1 calls
    through the SPECULATIVE path (serving/spec_decode.spec_generate):
    the identical draft -> verify -> accept/rollback loop the serving
    engine's spec tick runs, so engine==generate() parity holds by
    construction there too — and greedy speculative streams are token-
    identical to non-speculative greedy ones (speculation is lossless
    under argmax).  ``drafter`` overrides the config-built drafter (a
    serving/spec_decode.Drafter — required for ``spec_drafter=
    "model"``, whose companion params aren't derivable from cfg); it
    only moves the acceptance rate, never the tokens.  Non-greedy or
    batched calls fall through to the normal path unchanged.
    """
    b, t = prompt_ids.shape
    hybrid = bool(cfg.attn_layer_idx)
    chunk = cfg.effective_prefill_chunk_tokens
    if (mesh is not None and dict(mesh.shape).get("model", 1) <= 1
            and dict(mesh.shape).get("stage", 1) <= 1):
        # a data-only serving mesh shards slots, not weights — nothing
        # for generate() to constrain; dropping it keeps the TP-off jit
        # signatures (and pinned trace counts) identical to pre-TP.
        # A model OR stage axis > 1 partitions the weights (TP columns
        # / pipeline layer groups), so those meshes must be kept.
        mesh = None
    if cfg.spec_tokens > 0 and top_k == 1 and b == 1 and length_bucketing:
        # deferred import: serving imports this module at package-load
        # time, so the reverse edge must stay out of import time
        from mamba_distributed_tpu.serving.spec_decode import spec_generate

        return spec_generate(
            params, cfg, prompt_ids, max_new_tokens=max_new_tokens,
            eos_id=eos_id, mesh=mesh, prefix_cache=prefix_cache,
            drafter=drafter,
        )
    if length_bucketing and (
        (chunk > 0) if hybrid else use_chunked_prefill(t, chunk)
    ):
        # deferred import: serving imports this module at package-load
        # time, so the reverse edge must stay out of import time.
        # HYBRID prompts of ANY length go through the chunk step — it is
        # the one prefill that both masks pad keys (pads never reach the
        # paged KV) and is the exact computation the serving engine runs,
        # so hybrid engine<->generate() parity is by construction too.
        from mamba_distributed_tpu.serving.prefill import chunked_prefill

        last_logits, state = chunked_prefill(
            params, cfg, prompt_ids,
            max_len=(t + max_new_tokens) if hybrid else 0, mesh=mesh,
            prefix_cache=None if hybrid else prefix_cache,
        )
        new_tokens = _decode_impl(
            params, cfg, state, last_logits, key, max_new_tokens, top_k,
            temperature, jnp.int32(-1 if eos_id is None else eos_id),
            mesh=mesh,
        )
        return jnp.concatenate([prompt_ids, new_tokens], axis=1)
    if (prefix_cache is not None and not hybrid and b == 1
            and length_bucketing):
        # one-shot full hit: decode straight off the cached snapshot
        # (an engine's one-shot admission stores these — same pow2
        # layout, same key — so an exact prompt repeat skips lm_prefill
        # here too).  The one-shot path cannot STORE (its prefill state
        # never leaves the fused _generate_impl jit), but misses still
        # go through lookup() so hit/miss/promotion accounting matches
        # the engine's on a shared cache.
        hit = prefix_cache.lookup(np.asarray(prompt_ids[0]), None)
        if hit is not None:
            entry = hit[0]
            new_tokens = _decode_impl(
                params, cfg, {"blocks": entry.state["blocks"]},
                entry.logits, key, max_new_tokens, top_k, temperature,
                jnp.int32(-1 if eos_id is None else eos_id), mesh=mesh,
            )
            return jnp.concatenate([prompt_ids, new_tokens], axis=1)
    if length_bucketing and not cfg.attn_layer_idx:
        padded, mask = pad_to_bucket(prompt_ids, next_pow2_bucket(t))
    else:
        padded, mask = prompt_ids, None
    out = _generate_impl(
        params, cfg, padded, mask, key, max_new_tokens, top_k, temperature,
        jnp.int32(-1 if eos_id is None else eos_id), mesh=mesh,
    )
    if padded.shape[1] == t:
        return out
    # splice the unpadded prompt back onto the generated suffix
    return jnp.concatenate([prompt_ids, out[:, padded.shape[1]:]], axis=1)
