"""Recurrent generation with top-k sampling.

Functional upgrade of the reference's generate/top_k_sampling
(/root/reference/model.py:49-95, train.py:166-199): same sampling recipe
(top-k 50, softmax over the k logits, categorical draw), but the decode
loop carries the O(1) recurrent state (conv cache + SSM state per layer)
instead of re-running the full growing prefix through the model each token
— the reference never used its dep's ``inference_params`` (SURVEY.md §3.3).

Everything (prefill scan + decode scan) is one jit; token-for-token the
logits match the full-sequence forward (pinned by tests/test_model.py
decode-parity and tests/test_inference.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.lm import lm_prefill, lm_step


def top_k_sample(
    key: jax.Array,
    logits: jax.Array,
    k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """Sample from the top-k renormalized distribution.  logits (b, V) -> (b,)."""
    vals, idx = jax.lax.top_k(logits, k)
    choice = jax.random.categorical(key, vals / temperature)
    return jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]


def _decode_params(params: dict, cfg: ModelConfig) -> dict:
    """Pre-cast matmul kernels + embedding to the compute dtype.

    Decode is weight-bandwidth-bound: every token step re-read the fp32
    params only for ``linear()`` to cast them to bf16 (~1.1 GB/token at
    280M — exactly the measured 1.38 ms/token on v5e).  Casting once
    outside the decode scan halves that traffic, and the values are
    bit-identical because the per-step cast produced the same bf16
    numbers.  Conv kernels, biases, norm weights, SSM scalars and the
    MoE router (routed in fp32) stay fp32 — their math runs in fp32.
    """
    cd = jnp.dtype(cfg.compute_dtype)

    def cast(path, leaf):
        # denylist contract: every "kernel" leaf is a bf16-matmul weight
        # UNLESS its parent is named here because its math must stay fp32.
        # Adding a new fp32-math matmul param under a new key REQUIRES
        # extending this tuple + test_decode_params_cast_selectivity
        # (tests/test_inference.py), which pins the casted/uncasted split.
        keys = [getattr(p, "key", None) for p in path]
        if keys and keys[-1] == "embedding":
            return leaf.astype(cd)
        if (
            keys
            and keys[-1] == "kernel"
            and len(keys) >= 2
            and keys[-2] not in ("conv", "router")
        ):
            return leaf.astype(cd)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, params)


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "top_k", "temperature")
)
def generate(
    params: dict,
    cfg: ModelConfig,
    prompt_ids: jax.Array,
    key: jax.Array,
    max_new_tokens: int = 32,
    top_k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """prompt_ids (b, t) int32 -> (b, t + max_new_tokens) sampled tokens.

    EOT stopping is a host-side concern (jit generates the full budget;
    truncate at the tokenizer's EOT afterwards, as the caller wishes).
    """
    b, t = prompt_ids.shape
    params = _decode_params(params, cfg)
    # parallel prefill: one full-sequence forward builds the decode state
    # (the reference re-ran the whole prefix per token instead)
    last_logits, state = lm_prefill(
        params, cfg, prompt_ids, max_len=t + max_new_tokens
    )

    # never sample the vocab-padding rows (tied zero-padded embeddings give
    # them logit 0.0, which would outrank real negative logits)
    pad_mask = jnp.where(
        jnp.arange(cfg.vocab_size_padded) < cfg.vocab_size, 0.0, -jnp.inf
    )

    def decode(carry, k_i):
        state, logits = carry
        tok = top_k_sample(k_i, logits + pad_mask, top_k, temperature)
        logits, state = lm_step(params, cfg, state, tok)
        return (state, logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = jax.lax.scan(decode, (state, last_logits), keys)
    return jnp.concatenate([prompt_ids, jnp.moveaxis(new_tokens, 0, 1)], axis=1)
