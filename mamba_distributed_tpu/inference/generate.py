"""Recurrent generation with top-k sampling.

Functional upgrade of the reference's generate/top_k_sampling
(/root/reference/model.py:49-95, train.py:166-199): same sampling recipe
(top-k 50, softmax over the k logits, categorical draw), but the decode
loop carries the O(1) recurrent state (conv cache + SSM state per layer)
instead of re-running the full growing prefix through the model each token
— the reference never used its dep's ``inference_params`` (SURVEY.md §3.3).

Everything (prefill scan + decode scan) is one jit; token-for-token the
logits match the full-sequence forward (pinned by tests/test_model.py
decode-parity and tests/test_inference.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mamba_distributed_tpu.config import ModelConfig
from mamba_distributed_tpu.models.lm import lm_prefill, lm_step


def top_k_sample(
    key: jax.Array,
    logits: jax.Array,
    k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """Sample from the top-k renormalized distribution.  logits (b, V) -> (b,)."""
    vals, idx = jax.lax.top_k(logits, k)
    choice = jax.random.categorical(key, vals / temperature)
    return jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "top_k", "temperature")
)
def generate(
    params: dict,
    cfg: ModelConfig,
    prompt_ids: jax.Array,
    key: jax.Array,
    max_new_tokens: int = 32,
    top_k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """prompt_ids (b, t) int32 -> (b, t + max_new_tokens) sampled tokens.

    EOT stopping is a host-side concern (jit generates the full budget;
    truncate at the tokenizer's EOT afterwards, as the caller wishes).
    """
    b, t = prompt_ids.shape
    # parallel prefill: one full-sequence forward builds the decode state
    # (the reference re-ran the whole prefix per token instead)
    last_logits, state = lm_prefill(
        params, cfg, prompt_ids, max_len=t + max_new_tokens
    )

    # never sample the vocab-padding rows (tied zero-padded embeddings give
    # them logit 0.0, which would outrank real negative logits)
    pad_mask = jnp.where(
        jnp.arange(cfg.vocab_size_padded) < cfg.vocab_size, 0.0, -jnp.inf
    )

    def decode(carry, k_i):
        state, logits = carry
        tok = top_k_sample(k_i, logits + pad_mask, top_k, temperature)
        logits, state = lm_step(params, cfg, state, tok)
        return (state, logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = jax.lax.scan(decode, (state, last_logits), keys)
    return jnp.concatenate([prompt_ids, jnp.moveaxis(new_tokens, 0, 1)], axis=1)
