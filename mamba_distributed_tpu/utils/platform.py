"""Backend-selection workaround shared by every CLI entry point.

On axon-site machines the site plugin overrides ``JAX_PLATFORMS``
programmatically, so the env var alone does not pick the backend; the
config must be set too, *before* the backend initializes.  Used by
train.py, eval.py and bench.py so a CPU run requested via
``JAX_PLATFORMS=cpu`` can never silently queue on the TPU pool.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
