"""Shared utilities: FLOPs accounting, metrics logging, profiling."""

from mamba_distributed_tpu.utils.flops import flops_per_token, peak_flops_per_chip
from mamba_distributed_tpu.utils.metrics import MetricsLogger

__all__ = ["flops_per_token", "peak_flops_per_chip", "MetricsLogger"]
