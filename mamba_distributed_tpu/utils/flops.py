"""Analytic FLOPs accounting for MFU (the BASELINE.json headline metric).

The reference publishes no MFU (SURVEY.md §6); this is the standard
matmul-dominated accounting: 2*m*n FLOPs per (m x n) matvec per token,
3x forward for a training step (fwd + 2x bwd), attention causally halved.

Two conventions (both reported by bench.py; docs/KERNELS.md):

- ``hardware``: counts what the chunked SSD algorithm actually executes,
  including the O(chunk) Gram/decay matmuls.  This measures how busy the
  MXU is, but flatters "useful work" MFU because the chunked formulation
  does more arithmetic than the recurrence it computes.
- ``model``: counts only the math the *model* defines — parameter matmuls
  plus the recurrent-formulation state update/readout (O(1) per token,
  no chunk-size term).  This is the 6ND-style number; the >=45% target
  is judged on this stricter convention.
"""

from __future__ import annotations

import jax

from mamba_distributed_tpu.config import ModelConfig

# bf16 peak per chip. v5 lite == v5e.
_PEAK = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(device=None) -> float:
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12  # conservative default


def _mamba2_layer_flops(
    cfg: ModelConfig, seq_len: int, convention: str = "hardware"
) -> float:
    d, di = cfg.d_model, cfg.d_inner
    n, h, p = cfg.effective_d_state, cfg.nheads, cfg.headdim
    g = cfg.ngroups
    l = min(cfg.chunk_size, seq_len)
    f = 2 * d * (2 * di + 2 * g * n + h)  # in_proj
    f += 2 * (di + 2 * g * n) * cfg.d_conv  # depthwise conv
    if convention == "hardware":
        # chunked SSD per token: G Gram matrix is group-shared
        # (ops/ssd.chunk_local), M@x (l*p), chunk states (n*p) and
        # off-diag (n*p) are per-head
        f += 2 * (g * l * n + h * l * p + 2 * h * n * p)
    else:
        # recurrent formulation: B (x) x state update + C . state readout,
        # per head — what the chunked algorithm mathematically computes
        f += 2 * (2 * h * n * p)
    f += 2 * di * d  # out_proj
    return f


def _mamba1_layer_flops(cfg: ModelConfig, seq_len: int) -> float:
    d, di = cfg.d_model, cfg.d_inner
    n, dtr = cfg.effective_d_state, cfg.effective_dt_rank
    f = 2 * d * 2 * di  # in_proj
    f += 2 * di * cfg.d_conv
    f += 2 * di * (dtr + 2 * n)  # x_proj
    f += 2 * dtr * di  # dt_proj
    f += 8 * di * n  # recurrence (dA, dBu, state update, C reduction)
    f += 2 * di * d  # out_proj
    return f


def _attn_layer_flops(cfg: ModelConfig, seq_len: int) -> float:
    nh = cfg.effective_attn_num_heads
    nkv = cfg.effective_attn_num_kv_heads
    hd = cfg.d_model // nh
    f = 2 * cfg.d_model * (nh + 2 * nkv) * hd  # qkv
    f += 2 * seq_len * nh * hd  # scores + AV, causally halved: 4*(t/2)*nh*hd
    f += 2 * nh * hd * cfg.d_model  # out_proj
    return f


def flops_per_token(
    cfg: ModelConfig,
    seq_len: int,
    training: bool = True,
    convention: str = "hardware",
) -> float:
    """Matmul FLOPs per token for one forward (x3 when ``training``).

    ``convention`` is "hardware" (chunked-algorithm FLOPs) or "model"
    (parameter matmuls + recurrent state math only); see module docstring.
    The two differ only for mamba2 layers — mamba1's accounting is already
    the recurrence, and attention's O(t) score/AV terms are model FLOPs.
    """
    if convention not in ("hardware", "model"):
        raise ValueError(f"unknown FLOPs convention {convention!r}")
    attn_idx = set(cfg.attn_layer_idx)
    total = 0.0
    for i in range(cfg.n_layer):
        if i in attn_idx:
            total += _attn_layer_flops(cfg, seq_len)
        elif cfg.ssm_layer == "mamba2":
            total += _mamba2_layer_flops(cfg, seq_len, convention)
        else:
            total += _mamba1_layer_flops(cfg, seq_len)
        if cfg.d_intermediate > 0:
            mlp = 6 * cfg.d_model * cfg.d_intermediate
            if cfg.moe_num_experts:
                # each token runs top_k experts ("model"); the executed
                # capacity slots include the cf padding ("hardware")
                mult = (
                    cfg.moe_top_k * cfg.moe_capacity_factor
                    if convention == "hardware" else cfg.moe_top_k
                )
                total += mlp * mult
                total += 2 * cfg.d_model * cfg.moe_num_experts  # router
            else:
                total += mlp
    total += 2 * cfg.d_model * cfg.vocab_size_padded  # LM head
    return total * (3.0 if training else 1.0)
