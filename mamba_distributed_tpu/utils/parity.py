"""Early-loss-curve parity checking against the reference's logged run.

The reference's only correctness artifact is its training log
(/root/reference/log/log_mamba.txt: ``"{step} train {loss:.6f}"`` /
``"{step} val {loss:.4f}"`` lines, written by train.py:124,150,240).
Our MetricsLogger emits the same 3-field format, so the two runs can be
diffed directly.  Two comparison modes, because comparability depends on
the data:

- ``strict``: same data (tokenized FineWeb-Edu) — per-step losses must
  match within a tolerance covering bf16 noise and per-device data
  order.  This is the real parity claim (SURVEY.md §7 stage 3 exit
  criterion: first ~30 steps track 10.99 -> ~9.0).
- ``fingerprint``: synthetic stand-in data — only data-independent
  fingerprints are compared: the t=0 loss must sit at the uniform-logits
  value ln(vocab) (both runs start there regardless of data), the curve
  must fall monotonically after smoothing, and the early drop must be a
  healthy fraction of the reference's.  This validates the *harness*
  (init, LR schedule, loss plumbing) while the chip / real data are
  unavailable.
"""

from __future__ import annotations

import dataclasses
import math
import re

_LINE = re.compile(r"^(\d+)\s+(train|val)\s+([-+0-9.eEnainf]+)\s*$")


def parse_log(text: str) -> dict[str, list[tuple[int, float]]]:
    """Parse reference-format log text into {"train": [(step, loss)...],
    "val": [...]} keeping file order.  Unparseable lines are skipped (the
    console lines the reference also printed never land in log.txt)."""
    out: dict[str, list[tuple[int, float]]] = {"train": [], "val": []}
    for line in text.splitlines():
        m = _LINE.match(line.strip())
        if m:
            out[m.group(2)].append((int(m.group(1)), float(m.group(3))))
    return out


def parse_log_file(path: str) -> dict[str, list[tuple[int, float]]]:
    with open(path) as f:
        return parse_log(f.read())


@dataclasses.dataclass
class ParityResult:
    ok: bool
    mode: str
    steps_compared: int
    checks: list[tuple[str, bool, str]]  # (name, passed, detail)

    def report(self) -> str:
        lines = [
            f"parity mode={self.mode} steps={self.steps_compared} "
            f"=> {'OK' if self.ok else 'FAIL'}"
        ]
        for name, passed, detail in self.checks:
            lines.append(f"  [{'ok' if passed else 'FAIL'}] {name}: {detail}")
        return "\n".join(lines)


def _first_n_train(log: dict, n: int) -> list[float]:
    seen: dict[int, float] = {}
    for step, loss in log["train"]:
        if step < n and step not in seen:
            seen[step] = loss
    return [seen[s] for s in sorted(seen)]


def _val_at(log: dict, step: int) -> float | None:
    for s, loss in log["val"]:
        if s == step:
            return loss
    return None


def _val_checkpoint_check(
    ours: dict, ref: dict, step: int, mode: str, tol: float,
    min_drop_frac: float,
) -> tuple[str, bool, str] | None:
    """Score a shared val checkpoint (the reference logs val every 250
    steps: ``250 val 5.4865`` is the first, /root/reference/log/
    log_mamba.txt).  Returns None when the reference has no val point at
    ``step`` (nothing to score against)."""
    ref_v = _val_at(ref, step)
    if ref_v is None:
        return None
    our_v = _val_at(ours, step)
    name = f"val@{step}"
    if our_v is None or not math.isfinite(our_v):
        return (name, False, f"ours has no finite val point at step {step} "
                f"(ref {ref_v:.4f})")
    if mode == "strict":
        ok = abs(our_v - ref_v) <= tol
        return (name, ok, f"ours {our_v:.4f} vs ref {ref_v:.4f} "
                f"(|diff| {abs(our_v - ref_v):.4f} <= {tol})")
    # fingerprint: data/scale differ, so score the *relative* fall from
    # the t=0 val loss against the reference's fall.  A log without the
    # val@0 anchor cannot be scored — fail loud rather than degrade to a
    # near-no-op magnitude bound (r5 review).
    ref0, our0 = _val_at(ref, 0), _val_at(ours, 0)
    if ref0 is None or our0 is None:
        return (name, False,
                f"ours {our_v:.4f} vs ref {ref_v:.4f} — missing the val@0 "
                "anchor needed to normalize the fall (run with val_every "
                "covering step 0)")
    ref_drop = ref0 - ref_v
    our_drop = our0 - our_v
    frac = our_drop / ref_drop if ref_drop > 0 else float("nan")
    ok = frac >= min_drop_frac
    return (name, ok,
            f"ours fell {our_drop:.3f} ({our0:.3f}->{our_v:.3f}) vs ref "
            f"{ref_drop:.3f} ({ref0:.3f}->{ref_v:.3f}): {frac:.0%} >= "
            f"{min_drop_frac:.0%}; data/scale differ so the relative "
            "fall is the comparable quantity")


def compare_strict(
    ours: dict, ref: dict, steps: int = 30, tol: float = 0.35
) -> ParityResult:
    """Per-step loss diff over the first ``steps`` train steps.

    ``tol`` covers bf16 compute noise, data-order differences across
    device counts, and the reference's A100 vs TPU numerics — 0.35 is
    tight enough to catch a wrong init/schedule/loss (those diverge by
    >1 within 10 steps) and loose enough for hardware noise.
    """
    a = _first_n_train(ours, steps)
    b = _first_n_train(ref, steps)
    n = min(len(a), len(b))
    checks = []
    have = n >= min(steps, 10)
    checks.append(("coverage", have, f"{n} comparable steps (need >= {min(steps, 10)})"))
    if n:
        diffs = [abs(x - y) for x, y in zip(a[:n], b[:n])]
        worst = max(diffs)
        at = diffs.index(worst)
        ok = worst <= tol
        checks.append(
            ("per-step |loss diff|", ok,
             f"max {worst:.4f} at step {at} (tol {tol})")
        )
    # inclusive endpoint: --steps 250 must score the val@250 checkpoint
    for ckpt in range(250, steps + 1, 250):
        c = _val_checkpoint_check(ours, ref, ckpt, "strict", tol, 0.0)
        if c:
            checks.append(c)
    ok_all = all(p for _, p, _ in checks)
    return ParityResult(ok_all, "strict", n, checks)


def compare_fingerprint(
    ours: dict,
    ref: dict,
    steps: int = 30,
    vocab_size: int = 50304,
    init_tol: float = 0.25,
    min_drop_frac: float = 0.35,
    smooth: int = 5,
) -> ParityResult:
    """Data-independent fingerprints of a healthy reference-recipe run."""
    a = _first_n_train(ours, steps)
    b = _first_n_train(ref, steps)
    checks = []
    n = min(len(a), len(b))
    have = n >= min(steps, 10)
    checks.append(("coverage", have, f"{n} comparable steps"))
    if not have:
        return ParityResult(False, "fingerprint", n, checks)

    ln_v = math.log(vocab_size)
    init_err = abs(a[0] - ln_v)
    ref_init_err = abs(b[0] - ln_v)
    checks.append(
        ("t=0 loss ~ ln(vocab)", init_err <= init_tol,
         f"ours {a[0]:.4f} vs ln({vocab_size})={ln_v:.4f} "
         f"(|err| {init_err:.4f} <= {init_tol}; reference's was "
         f"{ref_init_err:.4f})")
    )

    # smoothed-monotonic over the EARLY curve only (first 30 steps, the
    # SURVEY §4 fingerprint window): late in training the loss bounces
    # around its floor, so long windows would fail on healthy runs
    n_early = min(n, 30)
    means = [
        sum(a[i:i + smooth]) / len(a[i:i + smooth])
        for i in range(0, n_early, smooth)
    ]
    mono = all(x > y for x, y in zip(means, means[1:]))
    checks.append(
        ("smoothed early curve falls", mono,
         f"{smooth}-step means over first {n_early}: "
         f"{['%.3f' % m for m in means]}")
    )

    # the early window alone would pass a run that falls for 30 steps
    # then blows up (r5 review): every loss must be finite, and the last
    # smoothed window must sit at or below the first
    finite = all(math.isfinite(v) for v in a)
    first_mean = sum(a[:smooth]) / len(a[:smooth])
    last_mean = sum(a[-smooth:]) / len(a[-smooth:])
    healthy = finite and last_mean <= first_mean
    checks.append(
        ("losses finite, no late blow-up", healthy,
         f"finite={finite}; last {smooth}-mean {last_mean:.3f} <= first "
         f"{first_mean:.3f}")
    )

    ref_drop = b[0] - min(b)
    our_drop = a[0] - min(a)
    frac = our_drop / ref_drop if ref_drop > 0 else float("nan")
    checks.append(
        (f"early drop >= {min_drop_frac:.0%} of reference's",
         frac >= min_drop_frac,
         f"ours {our_drop:.3f} vs ref {ref_drop:.3f} ({frac:.0%}); data "
         "differs (synthetic zipf vs FineWeb) so only the order of "
         "magnitude is comparable")
    )
    # score every val checkpoint inside the compared window, endpoint
    # inclusive (the reference's cadence is 250: first ``250 val 5.4865``)
    for ckpt in range(250, steps + 1, 250):
        c = _val_checkpoint_check(
            ours, ref, ckpt, "fingerprint", 0.0, min_drop_frac
        )
        if c:
            checks.append(c)
    ok_all = all(p for _, p, _ in checks)
    return ParityResult(ok_all, "fingerprint", n, checks)


def compare(
    ours: dict, ref: dict, mode: str = "fingerprint", steps: int = 30, **kw
) -> ParityResult:
    if mode == "strict":
        return compare_strict(ours, ref, steps, **kw)
    if mode == "fingerprint":
        return compare_fingerprint(ours, ref, steps, **kw)
    raise ValueError(f"unknown parity mode {mode!r}")
